#!/usr/bin/env python
"""graftlint — run the project's static-analysis rules over source trees.

Usage::

    python tools/graftlint.py sparkdl_tpu tools bench.py
    python tools/graftlint.py --json sparkdl_tpu     # machine-readable
    python tools/graftlint.py --list-rules

Exit status: 0 when clean, 1 when any finding survives its pragmas.
``--json`` emits a stable machine-readable document for CI consumers::

    {"findings": [{"rule": ..., "path": ..., "line": N,
                   "message": ...}, ...],
     "files": N, "rules": N}

The run-tests.sh ``graftlint`` stage runs the first form over the whole
stack under a 15 s wall-clock guard — the engine is stdlib-``ast`` only
and never imports the code it analyzes, so the repo-wide run costs
milliseconds, not a jax initialization.

Findings print as ``path:line: CODE message``; suppress a deliberate
exception with ``# graftlint: allow=CODE reason=<why>`` on the line or
the line above (a reason-less pragma is itself a finding).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from sparkdl_tpu.analysis import (RULE_HELP, lint_paths,  # noqa: E402
                                  load_event_registry_file,
                                  load_site_registry_file)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="project-native static analysis for sparkdl_tpu")
    ap.add_argument("targets", nargs="*",
                    help="files and/or directories to lint")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings (stable schema: "
                         "rule, path, line, message)")
    ap.add_argument("--sites-file", default=None,
                    help="explicit faults/sites.py to read the fault-site "
                         "registry from (default: auto-located under the "
                         "targets)")
    ap.add_argument("--events-file", default=None,
                    help="explicit obs/flight.py to read the flight-event "
                         "catalog from (default: auto-located under the "
                         "targets)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code in sorted(RULE_HELP):
            print(f"{code}  {RULE_HELP[code]}")
        return 0
    if not args.targets:
        ap.error("no targets (try: python tools/graftlint.py "
                 "sparkdl_tpu tools bench.py)")

    missing = [t for t in args.targets if not os.path.exists(t)]
    if missing:
        print(f"graftlint: no such target(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2

    sites = None
    if args.sites_file:
        sites = load_site_registry_file(args.sites_file)
        if not sites:
            print(f"graftlint: {args.sites_file} holds no SITE_HELP/"
                  f"SITES literal", file=sys.stderr)
            return 2
    events = None
    if args.events_file:
        events = load_event_registry_file(args.events_file)
        if not events:
            print(f"graftlint: {args.events_file} holds no EVENT_HELP/"
                  f"EVENTS literal", file=sys.stderr)
            return 2

    findings = lint_paths(args.targets, sites=sites, events=events)
    if args.as_json:
        import json

        print(json.dumps({
            "findings": [{"rule": f.code, "path": f.path, "line": f.line,
                          "message": f.message} for f in findings],
            "files": len({f.path for f in findings}),
            "rules": len(RULE_HELP),
        }, sort_keys=True))
        return 1 if findings else 0
    for f in findings:
        print(f.render())
    if findings:
        print(f"graftlint: {len(findings)} finding(s) across "
              f"{len({f.path for f in findings})} file(s)")
        return 1
    print(f"graftlint: clean ({len(RULE_HELP)} rules over "
          f"{', '.join(args.targets)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
