#!/usr/bin/env python
"""Fold a span trace into a PERF.md-style per-stage table.

VERDICT r5 weak #4: gap stories ("the host idles while the device
computes") stayed qualitative because nothing turned a run into a
per-stage time ledger.  This CLI does exactly that, from any trace
artifact the system writes — span JSONL (``spans_<pid>.jsonl``,
``Tracer.flush``) or Chrome trace-event JSON (``trace_<pid>.json``,
``bench.py`` per-config artifacts) — and needs no device: CPU-only
traces fold the same way.

Usage::

    python tools/trace_summary.py TRACE [--sort total|count|p99]
                                        [--wall-span NAME]

Output: one markdown table row per span name — count, total ms, p50 /
p99 ms, device ms (where stages bracketed ``block_until_ready``), and
% of wall.  Wall is the full extent of the trace (max end − min
start) unless ``--wall-span`` names a span (e.g. ``pipeline.run``) to
use as the denominator.  Stage totals can sum past 100% of wall —
overlapping stages are the point of the pipeline; the table makes the
overlap quantitative.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def summarize(spans: List[dict], wall_span: str = None) -> Dict:
    """Per-name aggregation + the wall denominator (seconds are kept in
    microseconds internally, milliseconds in the rendered table)."""
    if not spans:
        return {"wall_us": 0.0, "stages": {}}
    if wall_span:
        roots = [s for s in spans if s.get("name") == wall_span]
        if not roots:
            raise SystemExit(f"--wall-span {wall_span!r} matches no span; "
                             f"names present: "
                             f"{sorted({s['name'] for s in spans})}")
        wall_us = sum(float(s["dur_us"]) for s in roots)
    else:
        t0 = min(float(s["ts_us"]) for s in spans)
        t1 = max(float(s["ts_us"]) + float(s["dur_us"]) for s in spans)
        wall_us = t1 - t0
    from sparkdl_tpu.utils.metrics import Metrics

    stages: Dict[str, Dict] = {}
    for s in spans:
        st = stages.setdefault(s["name"], {"durs": [], "device_us": 0.0})
        st["durs"].append(float(s["dur_us"]))
        st["device_us"] += float(s.get("device_us") or 0.0)
    for st in stages.values():
        durs = st.pop("durs")
        st["count"] = len(durs)
        st["total_us"] = sum(durs)
        # THE nearest-rank percentile the registry/exporters use — one
        # definition across bench snapshots and trace tables
        st["p50_us"] = Metrics._percentile(durs, 50)
        st["p99_us"] = Metrics._percentile(durs, 99)
    return {"wall_us": wall_us, "stages": stages}


def render(summary: Dict, sort: str = "total") -> str:
    wall_us = summary["wall_us"]
    key = {"total": lambda kv: -kv[1]["total_us"],
           "count": lambda kv: -kv[1]["count"],
           "p99": lambda kv: -kv[1]["p99_us"]}[sort]
    rows = sorted(summary["stages"].items(), key=key)
    lines = [
        "| stage | count | total ms | p50 ms | p99 ms | device ms "
        "| % of wall |",
        "|---|---:|---:|---:|---:|---:|---:|",
    ]
    for name, st in rows:
        pct = (100.0 * st["total_us"] / wall_us) if wall_us else 0.0
        dev = (f"{st['device_us'] / 1e3:.1f}" if st["device_us"]
               else "-")
        lines.append(
            f"| {name} | {st['count']} | {st['total_us'] / 1e3:.1f} "
            f"| {st['p50_us'] / 1e3:.2f} | {st['p99_us'] / 1e3:.2f} "
            f"| {dev} | {pct:.0f}% |")
    lines.append(f"\nwall: {wall_us / 1e3:.1f} ms "
                 f"({summary['wall_us'] / 1e6:.3f} s); stages overlap, "
                 f"so percentages may sum past 100.")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Fold a span trace (JSONL or Chrome JSON) into a "
                    "per-stage table.")
    ap.add_argument("trace", help="spans_*.jsonl or trace_*.json path")
    ap.add_argument("--sort", choices=("total", "count", "p99"),
                    default="total")
    ap.add_argument("--wall-span", default=None,
                    help="span name to use as the wall-clock denominator "
                         "(default: full trace extent)")
    args = ap.parse_args(argv)
    from sparkdl_tpu.obs.export import load_spans

    spans = load_spans(args.trace)
    if not spans:
        print("no spans in trace", file=sys.stderr)
        return 1
    print(render(summarize(spans, wall_span=args.wall_span),
                 sort=args.sort))
    return 0


if __name__ == "__main__":
    sys.exit(main())
