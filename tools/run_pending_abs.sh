#!/usr/bin/env bash
# Run every hardware A/B that round 5's relay outage left pending, in
# priority order, each with its own timeout so one hung experiment
# cannot eat the window.  Appends all JSON lines to
# artifacts/perf_r05/experiments.jsonl (the committed measurement
# record) and drops raw logs next to it.
#
# Usage: bash tools/run_pending_abs.sh        (needs the TPU reachable)
set -uo pipefail
cd "$(dirname "$0")/.."
OUT=artifacts/perf_r05
mkdir -p "$OUT"

run() {  # run <tag> <timeout_s> <cmd...>
  local tag=$1 t=$2; shift 2
  echo "=== $tag ==="
  timeout "$t" "$@" > "$OUT/$tag.log" 2>&1
  local rc=$?
  grep -hE '^\{' "$OUT/$tag.log" | tee -a "$OUT/experiments.jsonl"
  [ $rc -ne 0 ] && echo "{\"experiment\": \"$tag\", \"error\": \"rc=$rc (timeout or failure; see $OUT/$tag.log)\"}" \
      | tee -a "$OUT/experiments.jsonl"
  return 0
}

# 1. quick probe first: abort early if the relay is still dead
timeout 120 python -c "import jax, jax.numpy as jnp; print(float(jax.jit(lambda x: x+1)(jnp.float32(1))))" \
  || { echo "{\"experiment\": \"pending_abs\", \"error\": \"relay unreachable; nothing run\", \"ts\": \"$(date -Is)\"}" \
       | tee -a "$OUT/experiments.jsonl"; exit 0; }

run resnet_fused_shortcut   900 python tools/perf_experiments.py resnet
run mobilenet_fused_tail    900 python tools/perf_experiments.py mobilenet
# batches_per_dispatch on the dispatch-bound configs: A/B via env
run bpd4_configs34          900 env SPARKDL_BATCHES_PER_DISPATCH=4 SPARKDL_BENCH_CONFIGS=3,4 python bench.py
run bpd1_configs34          900 env SPARKDL_BENCH_CONFIGS=3,4 python bench.py
# fresh fused-heads profile artifact
run profile_inception       600 python tools/capture_profile.py InceptionV3 artifacts/profile_r05 128
echo "done — review $OUT/experiments.jsonl, update PERF.md, and flip any lever that clearly wins"
