"""Crash-safe driver dryrun: ``__graft_entry__`` with incremental JSONL.

Round-5's dead relay left ``MULTICHIP_r05.json`` as a bare rc=124 — the
driver's only record of the dryrun was its stdout capture, so a hang or
kill mid-run erased every stage that HAD completed.  This CLI runs the
same entry points (``entry()`` single-chip compile check,
``dryrun_multichip(n)`` full sharded train/score step) but appends one
fsync'd JSONL record per stage to an on-disk artifact as it goes —
``started`` / ``ok`` / ``error`` with wall seconds — so a SIGKILL at any
instant leaves a valid, stage-resolved partial record (atexit cannot
survive SIGKILL; incremental flush can).

Every record is stamped ``faults: none|<spec>`` (``SPARKDL_FAULTS``), so
a chaos dryrun can never be mistaken for a clean one.

Usage::

    python tools/dryrun.py [--devices N] [--artifact PATH] [--skip-entry]

Exit code 0 iff every requested stage passed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


class StageLog:
    """Stage records through the shared crash-safe JSONL writer
    (``utils.jsonl.CrashSafeJsonlWriter``): one fsync'd write per
    record, and — same policy as bench.py's artifact rider — a
    read-only checkout disables the on-disk copy instead of failing the
    dryrun (stdout still carries every record)."""

    def __init__(self, path: str):
        from sparkdl_tpu.utils.jsonl import CrashSafeJsonlWriter

        self.writer = CrashSafeJsonlWriter(path)
        self.writer.reset()

    def write(self, **rec) -> None:
        from sparkdl_tpu.faults import current_spec

        rec.setdefault("ts", round(time.time(), 3))
        rec.setdefault("faults", current_spec() or "none")
        line = json.dumps(rec)
        print(line, flush=True)
        self.writer.write_line(line)


def _run_stage(log: StageLog, stage: str, fn) -> bool:
    log.write(stage=stage, status="started")
    t0 = time.perf_counter()
    try:
        detail = fn()
    # graftlint: allow=SDL003 reason=the written stage record IS the report; driver greps it for pass/fail
    except BaseException as e:
        log.write(stage=stage, status="error",
                  seconds=round(time.perf_counter() - t0, 3),
                  error=f"{type(e).__name__}: {str(e)[:300]}")
        return False
    log.write(stage=stage, status="ok",
              seconds=round(time.perf_counter() - t0, 3),
              **(detail or {}))
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, default=8,
                    help="mesh size for dryrun_multichip (default 8)")
    ap.add_argument("--artifact", default=os.path.join(
        _REPO, "artifacts", "dryrun_lines.jsonl"),
        help="incremental JSONL artifact path")
    ap.add_argument("--skip-entry", action="store_true",
                    help="skip the single-chip entry() compile check")
    args = ap.parse_args(argv)

    log = StageLog(args.artifact)
    import __graft_entry__

    ok = True
    if not args.skip_entry:
        def run_entry():
            import jax
            import numpy as np

            fn, (variables, batch) = __graft_entry__.entry()
            # no donation: one-shot smoke dispatch of caller-owned arrays
            out = jax.jit(fn, donate_argnums=())(variables, batch)
            return {"output_shape": list(np.asarray(out).shape)}

        ok = _run_stage(log, "entry", run_entry) and ok

    ok = _run_stage(
        log, f"dryrun_multichip[{args.devices}]",
        lambda: __graft_entry__.dryrun_multichip(args.devices)) and ok
    log.write(stage="summary", status="ok" if ok else "error")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
