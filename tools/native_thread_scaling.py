"""Native decode-core thread-scaling measurement (VERDICT r3 #6).

Prints one JSON line per thread count: fused JPEG decode+resize throughput
(500x375 JPEG -> 299x299 RGB, the flowers-like shape PERF.md uses) through
``native.decode_resize_batch(num_threads=...)``, plus the serial PIL
reference.  Run anywhere; the committed PERF.md table carries the numbers
from this sandbox (1 vCPU) and the CI step re-runs it on the 2-vCPU
runner so scaling across ≥2 distinct core counts is on record.
"""

import io
import json
import os
import sys
import time

import numpy as np


def corpus(n=64, height=375, width=500):
    from PIL import Image

    rng = np.random.default_rng(7)
    base = (rng.random((height, width, 3)) * 255).astype(np.uint8)
    blobs = []
    for i in range(n):
        arr = base.copy()
        arr[:8, :8, 0] = i % 251
        buf = io.BytesIO()
        Image.fromarray(arr, "RGB").save(buf, format="JPEG", quality=90)
        blobs.append(buf.getvalue())
    return blobs


def main():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import sparkdl_tpu.native as native
    from sparkdl_tpu.image.io import PIL_decode, resizeImage

    blobs = corpus()
    n = len(blobs)
    print(json.dumps({"host_cpus": os.cpu_count()}), flush=True)

    # serial PIL reference (what the fallback path does per core)
    def pil_once():
        for b in blobs:
            arr = PIL_decode(b)
            resizeImage(arr, 299, 299)

    pil_once()  # warm
    t0 = time.perf_counter()
    pil_once()
    pil_ips = n / (time.perf_counter() - t0)
    print(json.dumps({"backend": "pil", "threads": 1,
                      "img_per_s": round(pil_ips, 1)}), flush=True)

    if not native.native_available():
        print(json.dumps({"backend": "native", "error": "unavailable"}))
        return
    for threads in (1, 2, 4, 8):
        native.decode_resize_batch(blobs, 299, 299,
                                   num_threads=threads)  # warm
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            out, ok = native.decode_resize_batch(blobs, 299, 299,
                                                 num_threads=threads)
            dt = time.perf_counter() - t0
            best = max(best, n / dt)
        assert ok.all()
        print(json.dumps({"backend": "native", "threads": threads,
                          "img_per_s": round(best, 1)}), flush=True)


if __name__ == "__main__":
    main()
