#!/usr/bin/env python
"""blackbox — reconstruct an incident timeline from a flight dump.

Usage::

    python tools/blackbox.py artifacts/flight/                 # dir or file
    python tools/blackbox.py flight_123.jsonl --spans spans.jsonl \
        --journal stream_journal.jsonl --bench bench_lines.jsonl
    python tools/blackbox.py flight_123.jsonl --trace <id> --json

Folds the :mod:`sparkdl_tpu.obs.flight` recorder's durable dump (a
file, or a directory of ``flight_*.jsonl`` from several processes)
with whatever other artifacts the run left behind — span JSONL /
Chrome trace / trace directory (``obs.export.load_spans`` forms), a
streaming commit journal, and a bench ``bench_lines.jsonl`` artifact —
into ONE trace-id-correlated incident timeline: every state-change
event in order, annotated with the request trace it happened inside,
ending with per-tracker health verdicts and the journal's replay
state.  ``--trace`` narrows the timeline to one request's incident
slice.

All inputs are read with the shared torn-tail-tolerant
``utils.jsonl.read_jsonl`` reader where they are crash-safe JSONL, so
pointing this at the dump of a SIGKILLed process works by design —
that is the scenario the recorder exists for.

Exit codes: 0 — timeline ends healthy (every degraded tracker
recovered, no journal replay pending); 1 — unresolved incident (a
tracker is still degraded, or uncommitted stream work remains);
2 — unreadable/corrupt input.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_events(path: str) -> List[Dict[str, Any]]:
    """Flight events from a dump file or a directory of
    ``flight_*.jsonl``, ordered for the timeline: wall clock first (the
    only cross-process axis), per-process ``seq`` as the tiebreak (the
    authoritative within-process order — two events in the same
    microsecond still render in emit order)."""
    from sparkdl_tpu.utils.jsonl import read_jsonl

    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "flight_*.jsonl")))
    else:
        files = [path]
    events: List[Dict[str, Any]] = []
    for f in files:
        recs, _ = read_jsonl(f)
        events.extend(recs)
    events.sort(key=lambda e: (e.get("t_wall", 0.0), e.get("pid", 0),
                               e.get("seq", 0)))
    return events


def _span_index(spans: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """trace_id -> {root, spans, count} for correlation."""
    by_trace: Dict[str, Dict[str, Any]] = {}
    for s in spans:
        tid = s.get("trace_id")
        if not tid:
            continue
        entry = by_trace.setdefault(tid, {"root": None, "spans": [],
                                          "count": 0})
        entry["count"] += 1
        if s.get("name") not in entry["spans"]:
            entry["spans"].append(s.get("name"))
        if not s.get("parent_id"):
            entry["root"] = s.get("name")
    return by_trace


def _health_verdicts(events: List[Dict[str, Any]]) -> Dict[str, str]:
    """Per-tracker final state from the health.* event stream — the
    'did it recover?' question a point-in-time poll races past."""
    verdicts: Dict[str, str] = {}
    for e in events:
        name = e.get("event")
        if name not in ("health.degraded", "health.ready"):
            continue
        tracker = (e.get("attrs") or {}).get("tracker", "?")
        verdicts[tracker] = ("degraded" if name == "health.degraded"
                            else "ready")
    return verdicts


def build_timeline(flight_path: str,
                   spans_path: Optional[str] = None,
                   journal_path: Optional[str] = None,
                   bench_path: Optional[str] = None,
                   trace_id: Optional[str] = None) -> Dict[str, Any]:
    """The machine-readable incident document (shared by the CLI and
    the acceptance test).  Stable schema: ``events`` (ordered, each
    with ``rel_s`` from the first event's wall clock and a
    ``trace_known`` flag), ``chain`` (the ordered event-name sequence —
    the causal-chain assertion surface), ``traces`` (trace id ->
    correlated span names), ``health`` (per-tracker final verdicts),
    ``counts``, plus optional ``journal`` and ``bench`` sections."""
    events = load_events(flight_path)
    spans: List[Dict[str, Any]] = []
    if spans_path:
        from sparkdl_tpu.obs.export import load_spans

        spans = load_spans(spans_path)
    traces = _span_index(spans)
    # the verdict always rates the WHOLE dump: health.*/slo.* events
    # carry no trace id, so a --trace-narrowed view would otherwise
    # filter the incident out and report a still-degraded dump clean
    all_events = events
    if trace_id is not None:
        events = [e for e in events if e.get("trace_id") == trace_id]
        traces = {k: v for k, v in traces.items() if k == trace_id}
    t0 = events[0].get("t_wall", 0.0) if events else 0.0
    out_events: List[Dict[str, Any]] = []
    counts: Dict[str, int] = {}
    for e in events:
        counts[e["event"]] = counts.get(e["event"], 0) + 1
        ev = dict(e)
        ev["rel_s"] = round(e.get("t_wall", t0) - t0, 6)
        tid = e.get("trace_id")
        ev["trace_known"] = bool(tid and tid in traces)
        out_events.append(ev)
    doc: Dict[str, Any] = {
        "events": out_events,
        "chain": [e["event"] for e in out_events],
        "counts": counts,
        "health": _health_verdicts(all_events),
        "traces": {tid: traces[tid] for tid in sorted(traces)},
        "correlated_events": sum(1 for e in out_events
                                 if e["trace_known"]),
    }
    if journal_path:
        from tools.stream_journal import summarize

        doc["journal"] = summarize(journal_path)
    if bench_path:
        from sparkdl_tpu.utils.jsonl import read_jsonl

        lines, _ = read_jsonl(bench_path)
        doc["bench"] = [{"config": r.get("config"),
                         "metric": r.get("metric"),
                         "faults": r.get("faults"),
                         "slo": (r.get("slo") or {}).get("state")
                         if isinstance(r.get("slo"), dict) else None}
                        for r in lines]
    unresolved = [t for t, v in doc["health"].items() if v == "degraded"]
    replay = bool(doc.get("journal", {}).get("uncommitted"))
    doc["verdict"] = {
        "unrecovered_trackers": sorted(unresolved),
        "journal_replay_pending": replay,
        "clean": not unresolved and not replay,
    }
    return doc


def _render(doc: Dict[str, Any]) -> None:
    print(f"flight events  {len(doc['events'])}  "
          f"(trace-correlated: {doc['correlated_events']})")
    for e in doc["events"]:
        attrs = e.get("attrs") or {}
        attr_s = " ".join(f"{k}={v}" for k, v in attrs.items())
        tid = e.get("trace_id")
        tid_s = (f" trace={tid[:8]}{'*' if e['trace_known'] else ''}"
                 if tid else "")
        print(f"  +{e['rel_s']:9.4f}s [pid {e.get('pid', '?')}] "
              f"{e['event']}{tid_s} {attr_s}".rstrip())
    if doc["traces"]:
        print("correlated traces (* above = spans on file):")
        for tid, t in doc["traces"].items():
            print(f"  {tid[:8]}  root={t['root']}  spans={t['count']} "
                  f"({', '.join(t['spans'])})")
    if doc["health"]:
        print("health verdicts:")
        for tracker, v in sorted(doc["health"].items()):
            print(f"  {tracker}: {v}")
    j = doc.get("journal")
    if j:
        print(f"journal: {j['committed']} committed, "
              f"{len(j['uncommitted'])} replay-pending, "
              f"resume at offset {j['resume_offset']}")
    for b in doc.get("bench", []):
        print(f"bench: {b['config']} faults={b['faults']} "
              f"slo={b['slo']}")
    v = doc["verdict"]
    if v["clean"]:
        print("verdict: clean — every degradation recovered")
    else:
        print(f"verdict: UNRESOLVED — degraded trackers: "
              f"{v['unrecovered_trackers'] or 'none'}, journal replay "
              f"pending: {v['journal_replay_pending']}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="blackbox", description=__doc__.splitlines()[0])
    ap.add_argument("flight", help="flight dump file, or a directory of "
                                   "flight_*.jsonl")
    ap.add_argument("--spans", default=None,
                    help="span JSONL / Chrome trace / trace directory "
                         "to correlate trace ids against")
    ap.add_argument("--journal", default=None,
                    help="streaming commit journal to fold in")
    ap.add_argument("--bench", default=None,
                    help="bench_lines.jsonl artifact to fold in")
    ap.add_argument("--trace", default=None,
                    help="narrow the timeline to one trace id")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable timeline document on stdout")
    args = ap.parse_args(argv)
    from sparkdl_tpu.utils.jsonl import JsonlCorruptionError

    try:
        doc = build_timeline(args.flight, spans_path=args.spans,
                             journal_path=args.journal,
                             bench_path=args.bench, trace_id=args.trace)
    except (JsonlCorruptionError, OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(doc))
    else:
        _render(doc)
    return 0 if doc["verdict"]["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
