#!/usr/bin/env python
"""Inspect a streaming commit journal (read-only).

Usage::

    python tools/stream_journal.py artifacts/stream_journal.jsonl [--json]

Prints the journal's commit state — committed/uncommitted chunks, the
resume offset a restarted :class:`~sparkdl_tpu.streaming.StreamScorer`
would seek to, and whether the tail is torn.  Unlike ``Journal`` (whose
construction TRUNCATES a torn tail so it can reopen for append), this
reader never writes: safe to point at the journal of a live run.

Exit codes: 0 clean (everything committed), 1 uncommitted work pending
(a restart would replay), 2 unreadable/corrupt journal.
"""

from __future__ import annotations

import argparse
import json
import sys


def summarize(path: str) -> dict:
    """Pure-read journal summary (shared by the CLI and tests)."""
    from sparkdl_tpu.utils.jsonl import read_jsonl

    records, valid_bytes = read_jsonl(path)
    intents: dict = {}
    outputs: dict = {}
    committed: dict = {}
    for rec in records:
        kind = rec.get("rec")
        cid = rec.get("chunk_id")
        if kind == "intent":
            intents[cid] = rec.get("offset")
        elif kind == "output":
            outputs[cid] = rec
        elif kind == "commit":
            committed.setdefault(cid, rec.get("offset"))
    done = set(committed.values())
    resume = 0
    while resume in done:
        resume += 1
    uncommitted = [
        {"chunk_id": cid, "offset": off, "has_output": cid in outputs}
        for cid, off in sorted(intents.items(), key=lambda kv: kv[1])
        if cid not in committed
    ]
    import os

    try:
        torn_bytes = max(0, os.path.getsize(path) - valid_bytes)
    except OSError:
        torn_bytes = 0
    return {
        "path": path,
        "records": len(records),
        "committed": len(committed),
        "uncommitted": uncommitted,
        "resume_offset": resume,
        "torn_tail_bytes": torn_bytes,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("journal", help="path to the journal JSONL")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON summary on stdout")
    args = ap.parse_args(argv)
    from sparkdl_tpu.utils.jsonl import JsonlCorruptionError

    try:
        summary = summarize(args.journal)
    except (JsonlCorruptionError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(summary))
    else:
        print(f"journal      {summary['path']}")
        print(f"records      {summary['records']}")
        print(f"committed    {summary['committed']}")
        print(f"resume at    offset {summary['resume_offset']}")
        if summary["torn_tail_bytes"]:
            print(f"torn tail    {summary['torn_tail_bytes']} bytes "
                  f"(truncated on next journal open)")
        for rec in summary["uncommitted"]:
            stage = "output-written" if rec["has_output"] else "intent-only"
            print(f"  replay: offset {rec['offset']} "
                  f"{rec['chunk_id']} ({stage})")
    return 1 if summary["uncommitted"] else 0


if __name__ == "__main__":
    sys.exit(main())
