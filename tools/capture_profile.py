"""Capture an xplane device trace of one zoo-model forward (the round-4
committed artifact's recipe, parameterized) — run when the chip is
reachable to refresh `artifacts/profile_r*/`.

Usage: python tools/capture_profile.py [model] [out_dir] [batch]
       (defaults: InceptionV3 artifacts/profile_r05 128)

Writes `<out_dir>/<model>/...xplane.pb` (XProf/TensorBoard-viewable) plus
any trace.json.gz jax emits, and prints one JSON line with the in-trace
wall time.  The model runs through the bench configuration (bf16 compute,
fused preprocess, batch on device) so the trace matches the headline
program, including the round-5 fused branch heads when the env enables
them (default on).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    model = sys.argv[1] if len(sys.argv) > 1 else "InceptionV3"
    out = sys.argv[2] if len(sys.argv) > 2 else "artifacts/profile_r05"
    batch = int(sys.argv[3]) if len(sys.argv) > 3 else 128

    import jax

    import bench
    from sparkdl_tpu.utils.metrics import Metrics

    fn, variables, (h, w) = bench._zoo_fn(model, featurize=True)
    # no donation: the same device batch is re-dispatched every profile
    # iteration below
    g = jax.jit(fn, donate_argnums=())
    rng = np.random.default_rng(0)
    x = jax.device_put(
        (rng.random((batch, h, w, 3)) * 255).astype(np.uint8))
    jax.block_until_ready(g(variables, x))  # compile outside the trace

    trace_dir = os.path.join(out, model.lower())
    os.makedirs(trace_dir, exist_ok=True)
    m = Metrics()
    t0 = time.perf_counter()
    with m.profile(trace_dir, block_on=None):
        out_dev = g(variables, x)
        jax.block_until_ready(out_dev)
    wall = time.perf_counter() - t0
    print(json.dumps({
        "model": model, "batch": batch, "trace_dir": trace_dir,
        "in_trace_wall_s": round(wall, 4),
        "implied_img_s": round(batch / wall, 1)}))


if __name__ == "__main__":
    main()
