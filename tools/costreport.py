#!/usr/bin/env python
"""costreport — per-tenant / per-program hardware showback from a cost
ledger snapshot (ISSUE 18).

Usage::

    python tools/costreport.py varz.json            # a varz() dump
    python tools/costreport.py cost.json --json     # or a bare snapshot
    python tools/costreport.py varz.json --tenant t7

Accepts either a full ``varz()`` document (the ``cost`` section is
extracted — ``Server``, ``HeadFanoutServer`` and ``Fleet`` dumps all
work) or a bare ``CostLedger.snapshot()``.  Renders the per-tenant
spend table (device seconds, rows, queue wait, analytic FLOPs, HBM
byte-seconds, cache absorption), the per-program sentinel table
(measured vs baseline device-time/row), the shared pad-tax line, and
the conservation check (attributed == metered total).

Exit codes: 0 — no open cost regression; 1 — at least one program's
regression is OPEN (the sentinel's CI hook: a pipeline that dumps varz
and runs costreport fails the build on a perf regression); 2 —
unreadable/corrupt input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_snapshot(path: str) -> Optional[Dict[str, Any]]:
    """The cost snapshot from ``path``: a bare ``snapshot()`` dict, or
    any varz-shaped document carrying a ``cost`` section.  Returns None
    when the document is valid JSON but cost attribution was off
    (``"cost": null``)."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError("expected a JSON object")
    if "totals" in doc and "tenants" in doc:
        return doc
    if "cost" in doc:
        cost = doc["cost"]
        if cost is not None and not (isinstance(cost, dict)
                                     and "totals" in cost):
            raise ValueError("malformed cost section")
        return cost
    raise ValueError("document carries neither a cost snapshot nor a "
                     "varz 'cost' section")


def _fmt_s(v: float) -> str:
    return f"{v * 1e3:.3f}ms" if v < 1.0 else f"{v:.3f}s"


def _fmt_big(v: float) -> str:
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("k", 1e3)):
        if v >= div:
            return f"{v / div:.2f}{unit}"
    return f"{v:.0f}"


def render(snap: Dict[str, Any], tenant: Optional[str] = None) -> None:
    tot = snap["totals"]
    print(f"batches {tot['batches']}  rows {tot['rows']} "
          f"(+{tot['pad_rows']} pad)  device {_fmt_s(tot['device_s'])}  "
          f"queue {_fmt_s(tot['queue_s'])}  attr-errors "
          f"{tot['attr_errors']}")
    dev = tot["device_s"]
    attributed = tot["attributed_device_s"]
    drift = abs(attributed - dev) / dev if dev else 0.0
    print(f"conservation: attributed {_fmt_s(attributed)} vs metered "
          f"{_fmt_s(dev)} (rel drift {drift:.2e})")
    tenants = snap.get("tenants") or {}
    if tenant is not None:
        tenants = {t: v for t, v in tenants.items() if t == tenant}
    if tenants:
        print(f"{'tenant':<16}{'device':>12}{'share':>8}{'rows':>10}"
              f"{'queue':>12}{'flops':>10}{'hbm-B.s':>10}{'hits':>6}")
        total_dev = sum(v["device_s"] for v in tenants.values()) or 1.0
        order = sorted(tenants,
                       key=lambda t: (-tenants[t]["device_s"], t))
        for t in order:
            v = tenants[t]
            hits = v["hits"] + v["coalesced"] + v["feature_hits"]
            print(f"{t:<16}{_fmt_s(v['device_s']):>12}"
                  f"{v['device_s'] / total_dev:>8.1%}{v['rows']:>10}"
                  f"{_fmt_s(v['queue_s']):>12}"
                  f"{_fmt_big(v['flops']):>10}"
                  f"{_fmt_big(v['hbm_bytes_s']):>10}{hits:>6}")
    pad = snap.get("pad") or {}
    if pad:
        print(f"{'__pad__ (shared)':<16}{_fmt_s(pad['device_s']):>12}"
              f"{'':>8}{pad['rows']:>10}")
    programs = snap.get("programs") or {}
    if programs:
        print(f"{'program':<44}{'us/row':>10}{'baseline':>10}"
              f"{'state':>10}")
        for name in sorted(programs):
            p = programs[name]
            m = p.get("measured_s_per_row")
            b = p.get("baseline_s_per_row")
            print(f"{name:<44}"
                  f"{(f'{m * 1e6:.1f}' if m is not None else '-'):>10}"
                  f"{(f'{b * 1e6:.1f}' if b is not None else '-'):>10}"
                  f"{('REGRESSED' if p.get('regressed') else 'ok'):>10}")
    sentinel = snap.get("sentinel") or {}
    for name, rec in sorted((sentinel.get("open") or {}).items()):
        print(f"OPEN regression: {name}  factor {rec.get('factor')}x "
              f"({rec.get('reason')} check, opened at batch "
              f"{rec.get('opened_batch')})")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="costreport",
        description="per-tenant / per-program hardware showback from a "
                    "cost ledger snapshot (varz dump or bare snapshot)")
    ap.add_argument("path", help="JSON file: varz() dump or "
                                 "CostLedger.snapshot()")
    ap.add_argument("--tenant", help="narrow the tenant table to one "
                                     "tenant")
    ap.add_argument("--json", action="store_true",
                    help="emit the snapshot (tenant-filtered) as JSON "
                         "instead of tables")
    args = ap.parse_args(argv)
    try:
        snap = load_snapshot(args.path)
    # graftlint: allow=SDL003 reason=CLI exit-code surface: any unreadable/corrupt input becomes exit 2 with the error printed to stderr, never a stack trace
    except Exception as e:
        print(f"costreport: unreadable input: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    if snap is None:
        print("cost attribution was off for this dump "
              "(varz cost section is null)")
        return 0
    if args.json:
        doc = dict(snap)
        if args.tenant is not None:
            doc["tenants"] = {t: v for t, v in
                              (snap.get("tenants") or {}).items()
                              if t == args.tenant}
        json.dump(doc, sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        render(snap, tenant=args.tenant)
    open_regressions = (snap.get("sentinel") or {}).get("open") or {}
    return 1 if open_regressions else 0


if __name__ == "__main__":
    sys.exit(main())
