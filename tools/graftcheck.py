#!/usr/bin/env python
"""graftcheck — audit the stack's compiled programs against
``PROGRAMS.lock.json`` (rules GC000–GC005; see
``sparkdl_tpu/analysis/program``).

Usage::

    python tools/graftcheck.py                     # audit + verify lockfile
    python tools/graftcheck.py --write-baseline    # regenerate lockfile
    python tools/graftcheck.py --json              # machine-readable findings
    python tools/graftcheck.py --models MobileNetV2 --max-batch 8
    python tools/graftcheck.py --list-rules

Chip-free by construction: the audit pins ``JAX_PLATFORMS=cpu`` and an
8-device virtual CPU topology (the same mesh the test suite uses), and
every program is lowered from abstract avals — no weights load, no XLA
compile, no device memory.  The full zoo x bucket sweep runs in well
under a minute; run-tests.sh wraps it in a wall-clock guard.

Exit status: 0 clean and matching the committed lockfile; 1 findings or
drift (each line names the GC rule); 2 usage/environment errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

#: the audited topology — must match tests/conftest.py's virtual mesh or
#: fingerprints would depend on who ran the audit
AUDIT_DEVICE_COUNT = 8

# Pin the chip-free environment BEFORE jax can initialize a backend.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count"
        f"={AUDIT_DEVICE_COUNT}").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftcheck",
        description="jaxpr/StableHLO program auditor for sparkdl_tpu")
    ap.add_argument("--lockfile", default=None,
                    help="lockfile path (default: repo PROGRAMS.lock.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the audited records as the new baseline "
                         "instead of verifying against it")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output: {findings, programs}")
    ap.add_argument("--models", default=None,
                    help="comma list narrowing the zoo sweep (audits a "
                         "SUBSET: missing-program drift is not checked)")
    ap.add_argument("--max-batch", type=int, default=32,
                    help="serving max batch; buckets are its quarter/"
                         "half/full plan (default 32)")
    ap.add_argument("--compute-dtype", default="bfloat16",
                    choices=("bfloat16", "float32"),
                    help="audited zoo compute dtype (default bfloat16 — "
                         "the bench/serving configuration GC002 guards)")
    ap.add_argument("--no-train", action="store_true",
                    help="skip the train-step programs")
    ap.add_argument("--no-kernels", action="store_true",
                    help="skip the sepconv kernel programs")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the GC rule table and exit")
    args = ap.parse_args(argv)

    from sparkdl_tpu.analysis.program import (GC_RULE_HELP, DEFAULT_LOCKFILE,
                                              audit_inventory, diff_records,
                                              read_lockfile, stack_programs,
                                              write_lockfile)

    if args.list_rules:
        for code in sorted(GC_RULE_HELP):
            print(f"{code}  {GC_RULE_HELP[code]}")
        return 0

    import jax

    if jax.device_count() != AUDIT_DEVICE_COUNT:
        print(f"graftcheck: {jax.device_count()} devices visible; the "
              f"audit is fingerprinted on a {AUDIT_DEVICE_COUNT}-device "
              f"virtual CPU topology (jax initialized before the pin?)",
              file=sys.stderr)
        return 2

    models = ([m.strip() for m in args.models.split(",") if m.strip()]
              if args.models else None)
    # ANY narrowing away from the baseline configuration makes this a
    # subset audit: the missing-program drift check would otherwise
    # report every deliberately-skipped program as "silently left the
    # stack"
    subset = (bool(models) or args.no_train or args.no_kernels
              or args.max_batch != 32 or args.compute_dtype != "bfloat16")
    specs = stack_programs(max_batch_size=args.max_batch, models=models,
                           compute_dtype=args.compute_dtype,
                           include_train=not args.no_train,
                           include_kernels=not args.no_kernels)

    progress = None if args.as_json else (
        lambda line: print(f"  {line}"))
    if not args.as_json:
        print(f"graftcheck: auditing {len(specs)} programs "
              f"({args.compute_dtype}, max_batch={args.max_batch})")
    records, findings = audit_inventory(specs, progress=progress)

    path = args.lockfile or DEFAULT_LOCKFILE
    if args.write_baseline:
        if findings:
            _emit(args.as_json, findings, records,
                  "refusing to baseline a failing audit")
            return 1
        write_lockfile(records, path, meta={
            "jax_version": jax.__version__,
            "device_count": AUDIT_DEVICE_COUNT,
            "compute_dtype": args.compute_dtype,
            "max_batch_size": args.max_batch,
            "generated_by": "tools/graftcheck.py --write-baseline",
        })
        if args.as_json:
            print(json.dumps({"findings": [], "written": path,
                              "programs": len(records)}))
        else:
            print(f"graftcheck: baseline written to {path} "
                  f"({len(records)} programs)")
        return 0

    if not os.path.isfile(path):
        print(f"graftcheck: no lockfile at {path}; run "
              f"tools/graftcheck.py --write-baseline first",
              file=sys.stderr)
        return 2
    committed = read_lockfile(path)
    meta = committed.get("meta", {})
    if meta.get("jax_version") not in (None, jax.__version__):
        print(f"graftcheck: note — lockfile was generated under jax "
              f"{meta.get('jax_version')}, running {jax.__version__}; "
              f"fingerprint drift may be environmental", file=sys.stderr)
    findings.extend(diff_records(committed, records, subset=subset))
    _emit(args.as_json, findings, records, None)
    return 1 if findings else 0


def _emit(as_json: bool, findings, records, note) -> None:
    if as_json:
        print(json.dumps({
            "findings": [{"rule": f.code, "path": f.path, "line": f.line,
                          "message": f.message} for f in findings],
            "programs": {r["name"]: {"fingerprint": r["fingerprint"],
                                     "flops": r["flops"],
                                     "findings": r["findings"]}
                         for r in records},
        }, sort_keys=True))
        return
    if note:
        print(f"graftcheck: {note}", file=sys.stderr)
    for f in findings:
        print(f.render())
    if findings:
        print(f"graftcheck: {len(findings)} finding(s) across "
              f"{len({f.path for f in findings})} program(s)")
    else:
        print(f"graftcheck: clean ({len(records)} programs match the "
              f"committed lockfile)")


if __name__ == "__main__":
    sys.exit(main())
