"""Round-5 perf experiments (VERDICT r4 #1/#2): whole-model A/B runs on
the real chip, one JSON line per experiment.

Levers measured (results recorded in PERF.md):
  * Xception entry-flow row-tiled pallas kernel (SPARKDL_XC_TILED=1 vs 0)
  * InceptionV3 fused branch heads (SPARKDL_FUSED_HEADS=1 vs 0)
  * InceptionV3 batch sweep (128 / 256 / 512)
  * ResNet50 fused downsample shortcut (SPARKDL_RN_FUSED_SHORTCUT=1 vs 0)
  * MobileNetV2 fused inverted-residual tail (SPARKDL_MNV2_FUSED=1 vs 0)

Method: ``bench.measure_scan`` (steps-in-one-program, relay-artifact-free);
models build fresh per run so the env knobs bind at build time.

Run: python tools/perf_experiments.py [xception|inception|resnet|mobilenet|batch]...
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def run(name, featurize, batch, steps, **env):
    old = {}
    for k, v in env.items():
        old[k] = os.environ.get(k)
        os.environ[k] = v
    try:
        fn, variables, (h, w) = bench._zoo_fn(name, featurize=featurize)
        ips = bench.measure_scan(fn, variables, h, w, batch, steps)
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    print(json.dumps({"model": name, "batch": batch, "env": env,
                      "ips": round(ips, 1)}), flush=True)
    return ips


def xception_ab(batch=128, steps=40):
    a = run("Xception", False, batch, steps, SPARKDL_XC_TILED="1")
    b = run("Xception", False, batch, steps, SPARKDL_XC_TILED="0")
    print(json.dumps({"experiment": "xception_tiled_entry",
                      "tiled": round(a, 1), "xla_entry": round(b, 1),
                      "delta_pct": round((a / b - 1) * 100, 1)}), flush=True)


def inception_ab(batch=128, steps=40):
    a = run("InceptionV3", True, batch, steps, SPARKDL_FUSED_HEADS="1")
    b = run("InceptionV3", True, batch, steps, SPARKDL_FUSED_HEADS="0")
    print(json.dumps({"experiment": "inception_fused_heads",
                      "fused": round(a, 1), "per_branch": round(b, 1),
                      "delta_pct": round((a / b - 1) * 100, 1)}), flush=True)


def resnet_ab(batch=128, steps=40):
    a = run("ResNet50", False, batch, steps, SPARKDL_RN_FUSED_SHORTCUT="1")
    b = run("ResNet50", False, batch, steps, SPARKDL_RN_FUSED_SHORTCUT="0")
    print(json.dumps({"experiment": "resnet_fused_shortcut",
                      "fused": round(a, 1), "per_conv": round(b, 1),
                      "delta_pct": round((a / b - 1) * 100, 1)}), flush=True)


def mobilenet_ab(batch=256, steps=40):
    a = run("MobileNetV2", False, batch, steps, SPARKDL_MNV2_FUSED="1")
    b = run("MobileNetV2", False, batch, steps, SPARKDL_MNV2_FUSED="0")
    print(json.dumps({"experiment": "mobilenet_fused_tail",
                      "fused": round(a, 1), "xla": round(b, 1),
                      "delta_pct": round((a / b - 1) * 100, 1)}), flush=True)


def inception_batch_sweep(steps=40):
    out = {}
    for batch in (128, 256, 512):
        out[batch] = round(run("InceptionV3", True, batch,
                               max(10, steps // (batch // 128))), 1)
    print(json.dumps({"experiment": "inception_batch_sweep", **{
        str(k): v for k, v in out.items()}}), flush=True)


if __name__ == "__main__":
    wanted = sys.argv[1:] or ["xception", "inception", "batch"]
    if "xception" in wanted:
        xception_ab()
    if "inception" in wanted:
        inception_ab()
    if "resnet" in wanted:
        resnet_ab()
    if "mobilenet" in wanted:
        mobilenet_ab()
    if "batch" in wanted:
        inception_batch_sweep()
