"""Crash-safe continuous scoring: source -> engine -> journal (ISSUE 8).

:class:`StreamScorer` drives a replayable :class:`~sparkdl_tpu.
streaming.source.StreamSource` through the engine's ``map_batches``
pipelined path (or a ``serving.Server``-shaped sink), journaling every
chunk through intent -> output-artifact -> commit so a SIGKILL at any
instant resumes to exactly-once, bit-identical output:

* chunk payloads flow through ONE ``map_batches`` call via a generator,
  so host prepare of chunk ``k+1`` overlaps scoring of ``k`` exactly as
  the offline path does (the generator is pulled on the pipeline's
  prepare thread when ``pipeline=True``);
* each scored chunk's output is written ATOMICALLY (tmp + fsync +
  rename) to ``out-<chunk_id>.npy`` — content-addressed names make the
  replay rewrite idempotent — then journaled and committed;
* a restart builds the journal index (torn tail truncated), seeks the
  source to the contiguous committed prefix, REPLAYS the uncommitted
  suffix (counted as ``stream.redeliveries``), and suppresses by id any
  chunk the journal already committed (``stream.duplicates_suppressed``);
* a source that stops yielding past ``stall_deadline_s`` flips
  :meth:`health` to ``degraded`` (same live/ready/degraded contract and
  transitions deque as ``Server.health()``) while the runner keeps
  re-polling with seeded jittered backoff; the next chunk recovers it.

Fault sites: ``stream.source`` fires per poll (a ``sleep`` rule is a
stalled source the watchdog must catch; a transient ``error`` is a
flaky feed the backoff absorbs — other kinds propagate),
``stream.commit`` sits in the window between output write and journal
commit (the exactly-once crash point), and ``stream.resume`` fires when
a restart replays a chunk a previous run left uncommitted.
"""

from __future__ import annotations

import os
import random
import time
from collections import deque
from typing import Any, Dict, Iterator, Optional

import numpy as np

from sparkdl_tpu.analysis.lockcheck import named_lock
from sparkdl_tpu.faults import InjectedTransientError, inject
from sparkdl_tpu.obs.flight import emit as flight_emit
from sparkdl_tpu.obs.trace import get_tracer
from sparkdl_tpu.streaming.journal import Journal
from sparkdl_tpu.streaming.source import Chunk, StreamSource
from sparkdl_tpu.utils.digest import array_digest
from sparkdl_tpu.utils.health import HealthTracker
from sparkdl_tpu.utils.logging import get_logger
from sparkdl_tpu.utils.metrics import Metrics
from sparkdl_tpu.utils.retry import backoff_delay

logger = get_logger(__name__)


class StreamStallError(RuntimeError):
    """What ``health()["last_error"]`` records while the source is
    stalled past the watchdog deadline (never raised by the runner —
    the policy is degrade + keep re-polling, not crash)."""


# the one digest core (utils.digest, ISSUE 11) — the journal's artifact
# digests are byte-identical to what the local sha256 here produced
# before the move, so pre-move journals still verify
_array_digest = array_digest


def _write_artifact_atomic(path: str, arr: np.ndarray) -> None:
    """tmp + fsync + atomic rename: the artifact either exists whole or
    not at all — a SIGKILL can never leave a torn .npy for the resumed
    run (or the assembler) to trip over."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.save(f, arr, allow_pickle=False)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, path)


class StreamScorer:
    """Exactly-once continuous scorer; see the module docstring.

    ``sink`` is an :class:`~sparkdl_tpu.parallel.engine.InferenceEngine`
    (anything with ``map_batches`` — the pipelined default) or a
    ``serving.Server``-shaped object (anything with ``submit`` returning
    per-row futures; each chunk's rows ride the online queue and are
    re-stacked in order).  Payloads and outputs are single numpy arrays
    (one ``map_batches`` host batch per chunk).
    """

    def __init__(self, sink: Any, source: StreamSource, *,
                 journal_path: str, out_dir: str,
                 stall_deadline_s: float = 5.0,
                 poll_backoff_s: float = 0.005,
                 max_poll_backoff_s: float = 0.25,
                 seed: int = 0,
                 window: int = 2,
                 pipeline: Optional[bool] = None,
                 slos: Optional[Any] = None,
                 cache: Any = None,
                 cache_namespace: Optional[Any] = None,
                 metrics: Optional[Metrics] = None):
        if not (hasattr(sink, "map_batches") or hasattr(sink, "submit")):
            raise TypeError(
                f"sink {type(sink).__name__} has neither map_batches "
                f"(engine) nor submit (server)")
        self._sink = sink
        self._source = source
        self._journal = Journal(journal_path)
        self._out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self._stall_deadline_s = float(stall_deadline_s)
        self._poll_backoff_s = float(poll_backoff_s)
        self._max_poll_backoff_s = float(max_poll_backoff_s)
        self._rng = random.Random(f"stream:{seed}")
        self._window = int(window)
        self._pipeline = pipeline
        self.metrics = metrics if metrics is not None else Metrics()
        self._health = HealthTracker("stream.health")
        # Declarative objectives (ISSUE 9): e.g. watermark lag against a
        # freshness deadline, commit availability — evaluated on every
        # health() poll; a burn-rate breach degrades the same tracker
        # the stall watchdog does.
        self._slo_engine = None
        if slos:
            from sparkdl_tpu.obs.slo import SLOEngine

            self._slo_engine = SLOEngine(self.metrics, slos,
                                         health=self._health)
        # Result cache (ISSUE 11): a journal replay of a chunk a
        # previous run in THIS process already scored hits the cache
        # instead of re-dispatching (keys ride the chunk's content-
        # addressed id — same digest core, so replay identity is free).
        # None (the default) falls back to the SPARKDL_CACHE process
        # default; pass an explicit InferenceCache to share one with a
        # serving sink, or cache=False to force uncached.  An anon
        # namespace is OWNED and reclaimed by close(); pass an explicit
        # cache_namespace to share replay state across runner instances
        # (the crash-resume idiom).
        from sparkdl_tpu.serving.cache import resolve_cache

        self._cache, self._cache_ns, self._cache_ns_owned = resolve_cache(
            cache, cache_namespace, "stream")
        self._state_lock = named_lock("stream.state")
        # serializes commits + summary accounting between the consumer
        # thread and the delivery generator's replay short-circuit
        # (which runs on the pipeline's prepare thread when pipelined)
        self._commit_lock = named_lock("stream.commit_path")
        self._closed = False
        self._finished = False
        self._stalled = False
        self._watermark = 0
        self._last_progress = time.monotonic()

    # -- journal / source plumbing -----------------------------------------
    @property
    def journal(self) -> Journal:
        return self._journal

    def close(self) -> None:
        """Stop the run loop at the next chunk boundary (commits already
        journaled stay committed — close is not rollback)."""
        with self._state_lock:
            first_close = not self._closed
            self._closed = True
        self._journal.close()
        if first_close and self._cache is not None and self._cache_ns_owned:
            # the anon replay namespace dies with this scorer — reclaim
            # its bytes from the (possibly shared) store
            self._cache.invalidate(self._cache_ns)

    def _note_progress(self) -> None:
        with self._state_lock:
            self._last_progress = time.monotonic()
            self._stalled = False

    def _lag_s(self) -> float:
        with self._state_lock:
            if self._finished:
                return 0.0
            return time.monotonic() - self._last_progress

    # -- watchdog poll loop ------------------------------------------------
    def _next_chunk(self, begun: int,
                    max_chunks: Optional[int]) -> Optional[Chunk]:
        """Poll until a chunk, clean exhaustion, or close.  A silent
        source past ``stall_deadline_s`` degrades health and keeps
        re-polling with seeded jittered backoff (``utils.retry.
        backoff_delay`` — the fleet-wide de-synchronization policy);
        the next chunk flips health back to ready."""
        attempt = 0
        while True:
            with self._state_lock:
                if self._closed:
                    return None
            if max_chunks is not None and begun >= max_chunks:
                return None
            chunk = None
            try:
                inject("stream.source")
                chunk = self._source.poll()
            except InjectedTransientError as e:
                # a flaky feed: count it, degrade, let backoff absorb it
                self.metrics.incr("stream.source_errors")
                self._health.note_failure(e)
            if chunk is not None:
                recovered = False
                with self._state_lock:
                    recovered = self._stalled
                if recovered:
                    self.metrics.incr("stream.stall_recoveries")
                    flight_emit("stream.stall_recovered",
                                offset=chunk.offset)
                self._note_progress()
                self._health.note_success()
                self.metrics.gauge("stream.lag_seconds", self._lag_s())
                return chunk
            if self._source.exhausted():
                with self._state_lock:
                    self._finished = True
                return None
            lag = self._lag_s()
            self.metrics.gauge("stream.lag_seconds", lag)
            newly_stalled = False
            if lag > self._stall_deadline_s:
                with self._state_lock:
                    newly_stalled = not self._stalled
                    self._stalled = True
            if newly_stalled:
                self.metrics.incr("stream.stalls")
                flight_emit("stream.stall", lag_s=round(lag, 4),
                            deadline_s=self._stall_deadline_s)
                self._health.note_failure(StreamStallError(
                    f"source silent for {lag:.3f}s (deadline "
                    f"{self._stall_deadline_s:.3f}s); re-polling"))
                logger.warning("stream source stalled (%.3fs > %.3fs)",
                               lag, self._stall_deadline_s)
            time.sleep(backoff_delay(
                attempt, self._poll_backoff_s,
                max_backoff_seconds=self._max_poll_backoff_s,
                jitter=0.5, rng=self._rng))
            attempt += 1

    # -- the commit path ---------------------------------------------------
    def _commit_chunk(self, chunk: Chunk, out: Any, t_recv: float,
                      from_cache: bool = False) -> None:
        """Output-artifact write -> output record -> [crash window] ->
        commit.  Artifact names are the content-addressed chunk id, so
        a replayed chunk REWRITES the identical file instead of adding a
        second one — the no-duplicate half of exactly-once."""
        arr = np.asarray(out)
        if self._cache is not None and not from_cache:
            # record the scored output so a journal replay (a sink
            # failure mid-run, a second run() in this process) can
            # skip the re-dispatch — keyed on the content-addressed
            # chunk id, inserted BEFORE the crash-window inject below
            # so the replay that follows an injected commit fault
            # finds it
            self._cache.put(self._cache_ns + (chunk.chunk_id,), arr)
        name = f"out-{chunk.chunk_id}.npy"
        _write_artifact_atomic(os.path.join(self._out_dir, name), arr)
        self._journal.record_output(chunk.chunk_id, chunk.offset, name,
                                    _array_digest(arr))
        inject("stream.commit")
        if self._journal.commit(chunk.chunk_id, chunk.offset):
            self.metrics.incr("stream.commits")
            flight_emit("stream.commit", chunk_id=chunk.chunk_id,
                        offset=chunk.offset)
        with self._state_lock:
            self._stalled = False
            self._last_progress = time.monotonic()
        self._watermark_update()
        self.metrics.record_time("stream.chunk_latency",
                                 time.monotonic() - t_recv)

    def _watermark_update(self) -> None:
        wm = self._journal.resume_offset()
        with self._state_lock:
            self._watermark = wm
        self.metrics.gauge("stream.watermark", wm)
        self.metrics.gauge("stream.lag_seconds", self._lag_s())

    # -- run ---------------------------------------------------------------
    def run(self, max_chunks: Optional[int] = None) -> Dict[str, Any]:
        """Score the stream until the source is exhausted (or
        ``max_chunks`` chunks have been scored, or :meth:`close`).

        Resume-first: seeks the source to the journal's contiguous
        committed prefix, replays uncommitted chunks (``stream.resume``
        fires per replayed chunk), suppresses committed duplicates by
        id, then streams new chunks through the sink.  Returns a
        summary dict; raises on sink failure, non-transient source
        faults, or a journal append that cannot reach disk (wrapped in
        ``PipelineStageError`` naming the prepare stage when the
        pipelined path is on).
        """
        resume_offset = self._journal.resume_offset()
        summary: Dict[str, Any] = {
            "resume_offset": resume_offset,
            "recovered_torn_bytes": self._journal.recovered_torn_bytes,
            "chunks_scored": 0,
            "redeliveries": 0,
            "duplicates_suppressed": 0,
            "cache_hits": 0,
        }
        self._source.seek(resume_offset)
        with self._state_lock:
            self._watermark = resume_offset
            self._last_progress = time.monotonic()
        self.metrics.gauge("stream.watermark", resume_offset)
        tracer = get_tracer()
        with tracer.span("stream.run", resume_offset=resume_offset):
            try:
                if hasattr(self._sink, "map_batches"):
                    self._run_engine(summary, max_chunks)
                else:
                    self._run_serving(summary, max_chunks)
                self._health.note_success()
            except BaseException as e:
                # the crash the journal exists for: record it for
                # health()/post-mortem, then let the caller see it
                self._health.note_failure(e)
                raise
        summary["watermark"] = self._journal.resume_offset()
        summary["committed_total"] = self._journal.committed_count()
        return summary

    def _deliveries(self, summary: Dict[str, Any], pending: deque,
                    max_chunks: Optional[int]) -> Iterator[Any]:
        """The delivery generator both sink paths share: poll (with
        watchdog), suppress committed duplicates, journal intent, track
        the pending chunk, yield its payload.  Runs on the pipeline's
        prepare thread when the engine path is pipelined."""
        begun = 0
        while True:
            chunk = self._next_chunk(begun, max_chunks)
            if chunk is None:
                return
            if self._journal.is_committed(chunk.chunk_id):
                summary["duplicates_suppressed"] += 1
                self.metrics.incr("stream.duplicates_suppressed")
                continue
            if self._journal.seen(chunk.chunk_id):
                # a previous run began this chunk and died before commit
                summary["redeliveries"] += 1
                self.metrics.incr("stream.redeliveries")
                flight_emit("stream.redelivery", chunk_id=chunk.chunk_id,
                            offset=chunk.offset)
                inject("stream.resume")
                if self._cache is not None:
                    cached = self._cache.get(
                        self._cache_ns + (chunk.chunk_id,))
                    if cached is not None:
                        # replay short-circuit (ISSUE 11): a previous
                        # run in this process already scored these
                        # bytes — the chunk id IS the content digest,
                        # so commit the cached output IMMEDIATELY
                        # (deferring to the consumer would leave a
                        # replayed-then-quiet stream with journaled
                        # intents but no commits: watermark stuck, lag
                        # growing, a restart re-replaying everything).
                        # ``_commit_and_count`` serializes against the
                        # consumer's commits, so running here — on the
                        # pipeline's prepare thread when pipelined — is
                        # race-free.  Exactly-once is untouched: the
                        # intent -> output -> commit chain runs exactly
                        # as it would post-dispatch.
                        self._journal.begin(chunk.chunk_id, chunk.offset)
                        self.metrics.incr("stream.chunks")
                        self.metrics.incr("stream.cache_hits")
                        begun += 1
                        self._commit_and_count(chunk, cached,
                                               time.monotonic(), summary,
                                               cached=True)
                        continue
            self._journal.begin(chunk.chunk_id, chunk.offset)
            self.metrics.incr("stream.chunks")
            pending.append((chunk, time.monotonic()))
            begun += 1
            yield chunk.payload

    def _commit_and_count(self, chunk: Chunk, out: Any, t_recv: float,
                          summary: Dict[str, Any],
                          cached: bool = False) -> None:
        """One commit + its summary accounting, serialized under the
        commit lock: the consumer thread (live outputs) and the
        delivery generator's replay short-circuit (the pipeline's
        prepare thread) both route through here, so the exactly-once
        bookkeeping can never race itself."""
        with self._commit_lock:
            with get_tracer().span("stream.chunk", offset=chunk.offset,
                                   chunk_id=chunk.chunk_id,
                                   cached=cached):
                # from_cache: a cached value was just READ from its key
                # — re-putting it would only pay a second copy + sha256
                self._commit_chunk(chunk, out, t_recv, from_cache=cached)
            if cached:
                summary["cache_hits"] += 1
            summary["chunks_scored"] += 1

    def _run_engine(self, summary: Dict[str, Any],
                    max_chunks: Optional[int]) -> None:
        """One ``map_batches`` call over the delivery generator: chunk
        k+1's poll/journal/prepare overlaps chunk k's dispatch+gather
        on the pipelined path, while outputs — yielded strictly in
        order — are committed on this thread."""
        pending: deque = deque()
        for out in self._sink.map_batches(
                self._deliveries(summary, pending, max_chunks),
                window=self._window, pipeline=self._pipeline):
            chunk, t_recv = pending.popleft()
            self._commit_and_count(chunk, out, t_recv, summary)

    def _run_serving(self, summary: Dict[str, Any],
                     max_chunks: Optional[int]) -> None:
        """Server-sink path: each chunk's rows ride the online admission
        queue as individual requests and are re-stacked in row order —
        the journal neither knows nor cares which sink scored a chunk."""
        pending: deque = deque()
        for payload in self._deliveries(summary, pending, max_chunks):
            chunk, t_recv = pending.popleft()
            futs = [self._sink.submit(row) for row in payload]
            out = np.stack([np.asarray(f.result()) for f in futs])
            self._commit_and_count(chunk, out, t_recv, summary)

    # -- health ------------------------------------------------------------
    def health(self) -> Dict[str, Any]:
        """``Server.health()``'s live/ready/degraded contract for the
        stream, built through the ONE :meth:`~sparkdl_tpu.utils.health.
        HealthTracker.payload` schema every ``health()`` in the stack
        shares (ISSUE 9): ``state`` is ``degraded`` while the watermark
        lag exceeds the watchdog deadline (or after an unrecovered
        failure / SLO breach), with the same bounded ``transitions``
        deque, plus the stream's own ``watermark``/``lag_s``/
        ``source_exhausted`` extras (and ``slo`` when objectives were
        configured — each poll takes one burn-rate sample)."""
        extra: Dict[str, Any] = {}
        if self._slo_engine is not None:
            # evaluate BEFORE the snapshot: a breach crossing on this
            # very poll must already show as degraded
            extra["slo"] = self._slo_engine.evaluate()
        with self._state_lock:
            closed = self._closed
            finished = self._finished
            watermark = self._watermark
            lag = (0.0 if finished
                   else time.monotonic() - self._last_progress)
        state_override = None
        if not finished and lag > self._stall_deadline_s:
            state_override = "degraded"
        if closed:
            state_override = "closed"
        return self._health.payload(
            live=not closed, state_override=state_override,
            watermark=watermark, lag_s=round(lag, 3),
            source_exhausted=finished, **extra)


def assemble_outputs(journal_path: str, out_dir: str) -> np.ndarray:
    """Fold the committed artifacts into one array, offset order —
    the stream-side half of the exactly-once acceptance check (compare
    against a batch ``map_batches`` oracle over the same chunks).

    Verifies the journal's digests against the artifact bytes and that
    committed offsets are dense (0..n-1): a gap or a duplicate offset
    would be an at-most/at-least-once bug, so both raise.
    """
    j = Journal(journal_path)
    try:
        ids = j.committed_ids()
        offsets = j.committed_offsets()
        if offsets != list(range(len(offsets))):
            raise ValueError(
                f"committed offsets not dense: {offsets[:10]}... — "
                f"exactly-once violated (gap or duplicate)")
        parts = []
        for cid in ids:
            rec = j.output_record(cid)
            if rec is None:
                raise ValueError(f"committed chunk {cid} has no output "
                                 f"record")
            arr = np.load(os.path.join(out_dir, rec["artifact"]),
                          allow_pickle=False)
            if _array_digest(arr) != rec["digest"]:
                raise ValueError(f"artifact {rec['artifact']} digest "
                                 f"mismatch — torn or foreign file")
            parts.append(arr)
    finally:
        j.close()
    if not parts:
        return np.empty((0,), np.float32)
    return np.concatenate(parts, axis=0)
