"""sparkdl_tpu.streaming — exactly-once continuous scoring (ISSUE 8).

Closes ROADMAP item 5: the stack scored batch DataFrames and served
requests; this package makes it safe to sit on LIVE traffic.  A
bounded, replayable :class:`StreamSource` yields ordered
content-addressed chunks; :class:`StreamScorer` drives them through
``map_batches``'s pipelined path (or a ``serving.Server`` sink) while a
durable fsync'd :class:`Journal` records intent -> output-artifact ->
commit per chunk — so a SIGKILL at ANY instant (including the window
between output write and commit) restarts into a replay that is
exactly-once and bit-identical to the batch oracle.  A stalled source
degrades :meth:`StreamScorer.health` (the ``Server.health()`` contract)
while seeded-backoff re-polling waits it out.

Quick use::

    from sparkdl_tpu import streaming

    src = streaming.MemorySource([x0, x1, x2], finished=True)
    scorer = streaming.StreamScorer(
        engine, src, journal_path="j.jsonl", out_dir="out/")
    scorer.run()                       # crash here? run() again: resumes
    y = streaming.assemble_outputs("j.jsonl", "out/")
"""

from sparkdl_tpu.streaming.journal import (COMMIT, INTENT, OUTPUT, Journal,
                                           JournalFormatError,
                                           JournalWriteError)
from sparkdl_tpu.streaming.runner import (StreamScorer, StreamStallError,
                                          assemble_outputs)
from sparkdl_tpu.streaming.source import (Chunk, DirectorySource,
                                          MemorySource, StreamSource,
                                          content_chunk_id,
                                          finish_directory_stream,
                                          write_directory_chunk)

__all__ = [
    "Chunk",
    "StreamSource",
    "MemorySource",
    "DirectorySource",
    "content_chunk_id",
    "write_directory_chunk",
    "finish_directory_stream",
    "Journal",
    "JournalWriteError",
    "JournalFormatError",
    "INTENT",
    "OUTPUT",
    "COMMIT",
    "StreamScorer",
    "StreamStallError",
    "assemble_outputs",
]
