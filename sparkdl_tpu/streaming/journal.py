"""Durable fsync'd JSONL commit journal — the exactly-once core (ISSUE 8).

Three record kinds per chunk, appended strictly in this order through
one :class:`~sparkdl_tpu.utils.jsonl.CrashSafeJsonlWriter` (one
``write`` + ``fsync`` per record, so a record on disk is a record the
kernel acked)::

    {"rec": "intent", "chunk_id": "...", "offset": N}
    {"rec": "output", "chunk_id": "...", "offset": N,
     "artifact": "out-<id>.npy", "digest": "<sha256>"}
    {"rec": "commit", "chunk_id": "...", "offset": N}

The exactly-once argument, case by crash point:

* killed before ``intent`` — the chunk was never scored; the replayable
  source re-yields it on restart.  No output exists: **no loss**.
* killed between ``intent``/``output`` and ``commit`` — an output
  artifact may exist on disk, but artifacts are named by content-
  addressed chunk id and written atomically, so the restart's replay
  REWRITES the same path with the same bytes and then commits once.
  **No duplicate** is possible: one id, one artifact, one commit.
* killed mid-append — the torn trailing line is truncated by
  :func:`~sparkdl_tpu.utils.jsonl.recover_jsonl` at reopen (a tear can
  only ever eat the tail under the crash-safe write contract), leaving
  the chunk in the previous case.
* ``commit`` on disk — the chunk is done forever: restarts skip it by
  id (:meth:`Journal.is_committed`), so re-delivery by a rewound source
  is suppressed, and :meth:`Journal.commit` itself is idempotent (a
  second commit for an id is a no-op, never a second record).

Unlike the bench artifact (a rider on the real work), the journal IS
the work: an append that cannot reach disk raises
:class:`JournalWriteError` instead of silently disabling.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from sparkdl_tpu.analysis.lockcheck import named_lock
from sparkdl_tpu.utils.jsonl import CrashSafeJsonlWriter, recover_jsonl

INTENT = "intent"
OUTPUT = "output"
COMMIT = "commit"
_KINDS = (INTENT, OUTPUT, COMMIT)


class JournalWriteError(RuntimeError):
    """A journal append did not reach disk — the run must stop, because
    progress past this point could neither resume nor dedupe."""


class JournalFormatError(ValueError):
    """A fully-written journal record has the wrong shape — version
    drift or foreign data, not crash damage."""


class Journal:
    """One journal file == one stream's commit history (append-only;
    restarts REPLAY the log into memory, they never rewrite it).

    Construction recovers: the existing file is read through
    ``recover_jsonl`` (torn tail truncated in place, fsync'd), every
    record replays into the in-memory index, and the writer reopens in
    append mode.  ``recovered_torn_bytes`` reports how much tail a
    crash tore, for operators and tests.
    """

    def __init__(self, path: str):
        self.path = path
        records, self.recovered_torn_bytes = recover_jsonl(path)
        self._lock = named_lock("stream.journal")
        self._intents: Dict[str, int] = {}
        self._outputs: Dict[str, Dict[str, Any]] = {}
        self._committed: Dict[str, int] = {}
        for rec in records:
            self._index(rec)
        self._writer = CrashSafeJsonlWriter(path)

    # -- replay ------------------------------------------------------------
    def _index(self, rec: Dict[str, Any]) -> None:
        kind = rec.get("rec")
        cid = rec.get("chunk_id")
        off = rec.get("offset")
        if kind not in _KINDS or not isinstance(cid, str) \
                or not isinstance(off, int):
            raise JournalFormatError(
                f"{self.path}: bad journal record {rec!r}")
        if kind == INTENT:
            self._intents[cid] = off
        elif kind == OUTPUT:
            self._outputs[cid] = dict(rec)
        else:
            self._committed.setdefault(cid, off)

    # -- append ------------------------------------------------------------
    def _append(self, rec: Dict[str, Any]) -> None:
        if not self._writer.write_line(json.dumps(rec)):
            raise JournalWriteError(
                f"journal append to {self.path} failed (disk full or "
                f"read-only?) — cannot guarantee exactly-once past this "
                f"point")

    def begin(self, chunk_id: str, offset: int) -> None:
        """Intent record: the chunk is about to be scored."""
        with self._lock:
            self._append({"rec": INTENT, "chunk_id": chunk_id,
                          "offset": int(offset)})
            self._intents[chunk_id] = int(offset)

    def record_output(self, chunk_id: str, offset: int, artifact: str,
                      digest: str) -> None:
        """Output record: the artifact file is durably on disk (the
        caller wrote + fsync'd + renamed it BEFORE this append)."""
        with self._lock:
            rec = {"rec": OUTPUT, "chunk_id": chunk_id,
                   "offset": int(offset), "artifact": artifact,
                   "digest": digest}
            self._append(rec)
            self._outputs[chunk_id] = rec

    def commit(self, chunk_id: str, offset: int) -> bool:
        """Commit record: the chunk is done forever.  Idempotent — a
        duplicate commit (replay racing a recovered journal) returns
        False and appends NOTHING, so the log carries at most one
        commit per id."""
        with self._lock:
            if chunk_id in self._committed:
                return False
            self._append({"rec": COMMIT, "chunk_id": chunk_id,
                          "offset": int(offset)})
            self._committed[chunk_id] = int(offset)
            return True

    # -- queries -----------------------------------------------------------
    def is_committed(self, chunk_id: str) -> bool:
        with self._lock:
            return chunk_id in self._committed

    def seen(self, chunk_id: str) -> bool:
        """An intent or output record exists — a restart processing this
        chunk is a REDELIVERY, not first delivery (drives the
        ``stream.redeliveries`` metric and the ``stream.resume`` fault
        site)."""
        with self._lock:
            return chunk_id in self._intents or chunk_id in self._outputs

    def output_record(self, chunk_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._outputs.get(chunk_id)
            return dict(rec) if rec else None

    def committed_ids(self) -> List[str]:
        """Committed chunk ids in offset order."""
        with self._lock:
            return sorted(self._committed, key=self._committed.get)

    def committed_count(self) -> int:
        with self._lock:
            return len(self._committed)

    def committed_offsets(self) -> List[int]:
        """Sorted committed offsets — the assembler's density check
        (dense 0..n-1 == no gap, no duplicate)."""
        with self._lock:
            return sorted(self._committed.values())

    def resume_offset(self) -> int:
        """First offset NOT covered by the contiguous committed prefix —
        where a restarted, in-order run seeks its source.  Chunks beyond
        it that ARE committed (out-of-order history from a hand-built
        journal) are suppressed by id at delivery, so a hole never
        double-scores its neighbors."""
        with self._lock:
            done = set(self._committed.values())
            n = 0
            while n in done:
                n += 1
            return n

    def uncommitted(self) -> List[Dict[str, Any]]:
        """Chunks with an intent/output record but no commit — exactly
        the replay set a restart owes the stream."""
        with self._lock:
            out: List[Dict[str, Any]] = []
            for cid, off in sorted(self._intents.items(),
                                   key=lambda kv: kv[1]):
                if cid in self._committed:
                    continue
                rec = {"chunk_id": cid, "offset": off,
                       "has_output": cid in self._outputs}
                out.append(rec)
            return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "path": self.path,
                "intents": len(self._intents),
                "outputs": len(self._outputs),
                "committed": len(self._committed),
                "recovered_torn_bytes": self.recovered_torn_bytes,
            }

    def close(self) -> None:
        self._writer.close()
