"""Bounded, replayable stream sources (ISSUE 8 / ROADMAP item 5).

The contract every :class:`StreamSource` implementation owes the
runner:

* **Ordered** — chunk ``k`` is always yielded before chunk ``k+1``;
  offsets are dense (0, 1, 2, ...).
* **Content-addressed** — every chunk carries a stable
  :func:`content_chunk_id` derived from its offset + payload bytes, so
  the SAME chunk re-read after a crash has the SAME id.  The journal's
  exactly-once guarantee keys on this: duplicate deliveries are
  suppressed by id, never by guesswork about timing.
* **Replayable** — :meth:`~StreamSource.seek` rewinds to any offset not
  yet garbage-collected by the producer; a restarted run seeks to the
  journal's resume offset and re-reads the uncommitted suffix,
  yielding bit-identical payloads.
* **Bounded** — the producer can mark the stream finished;
  :meth:`~StreamSource.exhausted` turning true (with no chunk pending)
  ends the run.  An unbounded live feed simply never finishes.

``poll()`` is non-blocking (``None`` = nothing available yet); the
runner owns the wait policy (seeded-backoff re-poll + stall watchdog),
so sources stay trivially simple and deterministic.
"""

from __future__ import annotations

import fnmatch
import os
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence

import numpy as np

from sparkdl_tpu.analysis.lockcheck import named_lock
# The sha256-over-dtype/shape/bytes core moved to utils.digest (ISSUE
# 11) so the serving result cache keys on the SAME digest; re-exported
# here because every source implementation and test has imported the id
# from this module since ISSUE 8 — the id string itself is unchanged,
# so journals written before the move replay cleanly.
from sparkdl_tpu.utils.digest import array_digest, content_chunk_id

__all__ = [
    "content_chunk_id",
    "array_digest",
    "Chunk",
    "StreamSource",
    "MemorySource",
    "DirectorySource",
    "write_directory_chunk",
    "finish_directory_stream",
]


@dataclass(frozen=True)
class Chunk:
    """One unit of stream delivery: a dense ``offset``, the stable
    content-addressed ``chunk_id``, and the host payload (a numpy batch
    shaped like one ``map_batches`` input)."""

    offset: int
    chunk_id: str
    payload: Any


class StreamSource:
    """Interface; see the module docstring for the four contract
    clauses (ordered / content-addressed / replayable / bounded)."""

    def poll(self) -> Optional[Chunk]:
        """The next chunk, or ``None`` when nothing is available YET
        (the runner re-polls with seeded backoff)."""
        raise NotImplementedError

    def exhausted(self) -> bool:
        """True once the stream is finished AND every chunk has been
        yielded past the current position — the run's clean end."""
        raise NotImplementedError

    def seek(self, offset: int) -> None:
        """Rewind/advance so the next ``poll`` yields ``offset`` —
        crash-resume replay positioning."""
        raise NotImplementedError


class MemorySource(StreamSource):
    """In-memory feed: tests and live producers ``feed()`` payloads
    (thread-safe) and ``finish()`` to bound the stream.  Chunk ids are
    computed once at feed time and survive any number of seeks."""

    def __init__(self, payloads: Sequence[Any] = (), *,
                 finished: bool = False):
        self._lock = named_lock("stream.source.feed")
        self._payloads: List[np.ndarray] = []
        self._ids: List[str] = []
        self._finished = False
        self._next = 0
        for p in payloads:
            self.feed(p)
        if finished:
            self.finish()

    def feed(self, payload: Any) -> str:
        """Append one chunk payload; returns its content-addressed id."""
        arr = np.asarray(payload)
        with self._lock:
            if self._finished:
                raise ValueError("cannot feed a finished MemorySource")
            cid = content_chunk_id(len(self._payloads), arr)
            self._payloads.append(arr)
            self._ids.append(cid)
            return cid

    def finish(self) -> None:
        """Mark the stream bounded: after the remaining chunks drain,
        ``exhausted()`` turns true and the run ends cleanly."""
        with self._lock:
            self._finished = True

    def poll(self) -> Optional[Chunk]:
        with self._lock:
            if self._next >= len(self._payloads):
                return None
            off = self._next
            self._next = off + 1
            return Chunk(off, self._ids[off], self._payloads[off])

    def exhausted(self) -> bool:
        with self._lock:
            return self._finished and self._next >= len(self._payloads)

    def seek(self, offset: int) -> None:
        with self._lock:
            if not 0 <= offset <= len(self._payloads):
                raise ValueError(
                    f"seek offset {offset} outside [0, "
                    f"{len(self._payloads)}]")
            self._next = int(offset)

    def __len__(self) -> int:
        with self._lock:
            return len(self._payloads)


class DirectorySource(StreamSource):
    """Directory-watch source: each ``pattern`` file (default
    ``*.npy``) is one chunk; lexicographic file order IS stream order,
    so producers must name monotonically (``chunk-00000042.npy``) and
    write atomically (tmp file + ``os.rename`` — a half-written file
    must never match the pattern).  The stream is bounded by dropping
    an ``end_marker`` file (default ``_END``) once the last chunk is
    renamed in.

    Replay is free: the files are still on disk, so ``seek`` just moves
    the cursor and re-reads — same bytes, same content-addressed ids.
    Single-consumer by design (the runner polls from one thread).
    """

    def __init__(self, path: str, pattern: str = "*.npy",
                 end_marker: str = "_END"):
        self._dir = path
        self._pattern = pattern
        self._end_marker = end_marker
        self._next = 0

    def _listing(self) -> List[str]:
        try:
            names = os.listdir(self._dir)
        except FileNotFoundError:
            return []
        return sorted(n for n in names
                      if fnmatch.fnmatch(n, self._pattern)
                      and n != self._end_marker)

    def poll(self) -> Optional[Chunk]:
        names = self._listing()
        if self._next >= len(names):
            return None
        off = self._next
        payload = np.load(os.path.join(self._dir, names[off]),
                          allow_pickle=False)
        self._next = off + 1
        return Chunk(off, content_chunk_id(off, payload), payload)

    def exhausted(self) -> bool:
        if not os.path.exists(os.path.join(self._dir, self._end_marker)):
            return False
        return self._next >= len(self._listing())

    def seek(self, offset: int) -> None:
        if offset < 0:
            raise ValueError(f"seek offset {offset} negative")
        # seeking past the current listing is legal mid-stream: the
        # journal may have committed chunks whose files the producer
        # will only rename in later replays of a partially-fed directory
        self._next = int(offset)


def write_directory_chunk(path: str, offset: int, payload: Any) -> str:
    """Producer-side helper honoring :class:`DirectorySource`'s naming +
    atomicity contract: ``np.save`` to a tmp name (which does NOT match
    the ``*.npy`` poll pattern until renamed), fsync, then one atomic
    ``os.rename`` to ``chunk-<offset>.npy``.  Returns the final path."""
    os.makedirs(path, exist_ok=True)
    final = os.path.join(path, f"chunk-{offset:08d}.npy")
    tmp = final + ".tmp"
    with open(tmp, "wb") as f:
        np.save(f, np.asarray(payload), allow_pickle=False)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, final)
    return final


def finish_directory_stream(path: str, end_marker: str = "_END") -> None:
    """Drop the end marker: the producer's ``finish()`` for a
    :class:`DirectorySource` (write after the LAST chunk's rename)."""
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, end_marker), "wb") as f:
        f.flush()
        os.fsync(f.fileno())
