"""HBM-aware placement planner — the twin's control-plane half (b).

Given the fleet's registered models (name -> param pytree, or the
shape-only ``jax.ShapeDtypeStruct`` skeleton — the planner never needs
real bytes), a per-chip HBM budget, and a total chip budget, decide for
EVERY model:

* which slice shape it runs on (``model_parallel`` ∈ ``slice_chips``) —
  the smallest tensor-parallel degree whose per-chip footprint (from
  the REAL ``param_sharding_stats`` under the REAL partition rules)
  fits the per-chip budget after ``reserve_fraction`` is held back for
  activations/runtime;
* whether that choice actually shards (``partition_digest`` ≠
  ``"replicated"``) or degenerates to replication (tiny models on a
  1-chip slice — the cheap, classic layout);

then first-fit-decreasing bin-pack the chosen slices onto hosts of
``slice_chips[-1]`` chips so same-degree models share hosts, and verify
the whole plan against the chip budget.  Infeasible demands (a model
that fits no allowed slice, or a plan needing more chips than the
budget) raise :class:`PlacementError` loudly — a silent overcommit is
an OOM at 3am.

The planner runs CHIPLESS: mesh geometry enters only through
:class:`MeshSlice`, a shape-only stand-in exposing exactly the
``.shape[axis]`` / ``.axis_names`` surface the mesh helpers read, so
the same code paths that drive real device placement
(``default_partition_rules`` → ``match_partition_rules`` →
``spec_shards_leaf`` → ``param_sharding_stats`` → ``partition_digest``)
are exercised without a single device — which is what lets the twin's
tier-1 tests hold the HBM-budget acceptance bar on a CPU box.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from sparkdl_tpu.obs.flight import emit as flight_emit
from sparkdl_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS,
                                       match_partition_rules,
                                       default_partition_rules,
                                       param_sharding_stats,
                                       partition_digest)

__all__ = ["MeshSlice", "ModelPlacement", "PlacementPlan",
           "PlacementError", "plan_placement"]


class PlacementError(RuntimeError):
    """A model or plan that cannot fit the declared budgets."""


class MeshSlice:
    """Shape-only mesh stand-in: ``shape[axis]`` + ``axis_names`` is the
    whole surface the partition-rule/stats helpers consume, so planning
    math runs device-free and identically to a real ``Mesh`` of the
    same geometry."""

    def __init__(self, data: int = 1, model: int = 1):
        if data < 1 or model < 1:
            raise ValueError(f"mesh axes must be >= 1, got "
                             f"data={data} model={model}")
        self.shape = {DATA_AXIS: int(data), MODEL_AXIS: int(model)}
        self.axis_names = (DATA_AXIS, MODEL_AXIS)

    @property
    def chips(self) -> int:
        return self.shape[DATA_AXIS] * self.shape[MODEL_AXIS]

    def __repr__(self) -> str:
        return (f"MeshSlice(data={self.shape[DATA_AXIS]}, "
                f"model={self.shape[MODEL_AXIS]})")


@dataclass
class ModelPlacement:
    """One model's resolved slot in the plan."""

    model: str
    model_parallel: int
    chips: int
    host: int
    replicated: bool
    partition_digest: str
    stats: Dict[str, Any]

    def as_dict(self) -> Dict[str, Any]:
        return {"model": self.model,
                "model_parallel": self.model_parallel,
                "chips": self.chips, "host": self.host,
                "replicated": self.replicated,
                "partition_digest": self.partition_digest,
                "param_bytes_per_chip":
                    self.stats["param_bytes_per_chip"]}


@dataclass
class PlacementPlan:
    """The whole fleet's placement + the budget it was proven under."""

    placements: List[ModelPlacement]
    chip_hbm_bytes: int
    usable_hbm_bytes: int
    total_chip_budget: int
    chips_used: int
    hosts: List[List[str]] = field(default_factory=list)

    def digest(self) -> str:
        """Content digest of the plan — two runs of one seeded day must
        agree on it byte-for-byte."""
        doc = {"budget": [self.chip_hbm_bytes, self.total_chip_budget],
               "placements": [p.as_dict() for p in self.placements]}
        blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()

    def as_dict(self) -> Dict[str, Any]:
        return {"chip_hbm_bytes": self.chip_hbm_bytes,
                "usable_hbm_bytes": self.usable_hbm_bytes,
                "total_chip_budget": self.total_chip_budget,
                "chips_used": self.chips_used,
                "hosts": [list(h) for h in self.hosts],
                "digest": self.digest(),
                "placements": [p.as_dict() for p in self.placements]}


def _fit_slice(name: str, params: Any, usable: int,
               slice_chips: Sequence[int], rules
               ) -> Tuple[int, bool, str, Dict[str, Any]]:
    """Smallest allowed ``model_parallel`` degree whose per-chip bytes
    (REAL stats under the REAL rules) fit ``usable``."""
    last_stats: Optional[Dict[str, Any]] = None
    for m in slice_chips:
        mesh = MeshSlice(data=1, model=m)
        rule_list = rules(mesh) if callable(rules) else rules
        if rule_list is None:
            rule_list = default_partition_rules(mesh)
        specs = match_partition_rules(rule_list, params)
        stats = param_sharding_stats(mesh, params, specs)
        last_stats = stats
        if stats["param_bytes_per_chip"] <= usable:
            digest = partition_digest(specs)
            return m, digest == "replicated", digest, stats
    assert last_stats is not None
    raise PlacementError(
        f"model {name!r} fits no allowed slice: per-chip "
        f"{last_stats['param_bytes_per_chip']}B at model_parallel="
        f"{slice_chips[-1]} exceeds usable {usable}B "
        f"(largest replicated leaf "
        f"{last_stats['largest_replicated_leaf_bytes']}B — a finer "
        f"partition rule may unlock a deeper split)")


def plan_placement(entries: Dict[str, Any], *,
                   chip_hbm_bytes: int,
                   total_chip_budget: int,
                   slice_chips: Sequence[int] = (1, 2, 4, 8),
                   rules=None,
                   reserve_fraction: float = 0.25) -> PlacementPlan:
    """Plan the fleet onto mesh slices under the declared budgets.

    ``entries`` — model name -> param pytree (arrays or
    ``ShapeDtypeStruct`` leaves).  ``slice_chips`` — the allowed
    tensor-parallel degrees, ascending.  ``rules`` — partition rules
    (or ``mesh -> rules`` factory); default
    :func:`default_partition_rules`.  ``reserve_fraction`` of each
    chip's HBM is held back for activations, the compiled program, and
    runtime scratch.

    Packing: models group by chosen degree and first-fit-decreasing
    (by per-chip bytes) into hosts of ``max(slice_chips)`` chips —
    co-resident models on one host share its chips, so their per-chip
    footprints ADD and the sum must stay under the usable budget.
    """
    if chip_hbm_bytes <= 0 or total_chip_budget <= 0:
        raise ValueError("chip_hbm_bytes and total_chip_budget must be "
                         "positive")
    if not entries:
        raise ValueError("no models to place")
    slice_chips = sorted(int(m) for m in slice_chips)
    if slice_chips[0] < 1:
        raise ValueError(f"slice_chips must be >= 1, got {slice_chips}")
    if not 0.0 <= reserve_fraction < 1.0:
        raise ValueError(f"reserve_fraction must be in [0, 1), got "
                         f"{reserve_fraction}")
    usable = int(chip_hbm_bytes * (1.0 - reserve_fraction))

    chosen: List[ModelPlacement] = []
    for name in sorted(entries):
        m, replicated, digest, stats = _fit_slice(
            name, entries[name], usable, slice_chips, rules)
        chosen.append(ModelPlacement(
            model=name, model_parallel=m, chips=m, host=-1,
            replicated=replicated, partition_digest=digest, stats=stats))

    # First-fit-decreasing within each degree group: a host is
    # max(slice_chips) chips; a model of degree m claims m of them and
    # co-residents stack their per-chip bytes on the shared chips.
    host_chips = slice_chips[-1]
    hosts: List[Dict[str, Any]] = []  # {free_chips, per_chip_used, models}
    for p in sorted(chosen,
                    key=lambda p: (-p.model_parallel,
                                   -p.stats["param_bytes_per_chip"],
                                   p.model)):
        need = p.stats["param_bytes_per_chip"]
        placed = False
        for i, h in enumerate(hosts):
            if (h["free_chips"] >= p.chips
                    and h["per_chip_used"] + need <= usable):
                h["free_chips"] -= p.chips
                h["per_chip_used"] += need
                h["models"].append(p.model)
                p.host = i
                placed = True
                break
        if not placed:
            hosts.append({"free_chips": host_chips - p.chips,
                          "per_chip_used": need, "models": [p.model]})
            p.host = len(hosts) - 1

    chips_used = len(hosts) * host_chips
    if chips_used > total_chip_budget:
        raise PlacementError(
            f"plan needs {chips_used} chips ({len(hosts)} hosts x "
            f"{host_chips}) but the budget is {total_chip_budget}; "
            f"raise the budget, allow deeper slices, or drop models")

    chosen.sort(key=lambda p: p.model)
    plan = PlacementPlan(
        placements=chosen, chip_hbm_bytes=int(chip_hbm_bytes),
        usable_hbm_bytes=usable,
        total_chip_budget=int(total_chip_budget),
        chips_used=chips_used,
        hosts=[list(h["models"]) for h in hosts])
    flight_emit("placement.plan", models=len(chosen),
                chips_used=chips_used, hosts=len(hosts),
                digest=plan.digest()[:16])
    return plan
