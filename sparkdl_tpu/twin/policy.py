"""Closed-loop capacity policy — the twin's control-plane half (a).

A :class:`Policy` looks at ONE deterministic per-tick observation
(:class:`TickObservation`, distilled by the simulator from ``varz()``
and the fleet SLO engine's burn rates) and returns a
:class:`PolicyDecision` — a list of lever adjustments the simulator
applies to the REAL fleet before the next tick's arrivals:

* ``quota``   — a tenant's token-bucket ``rate_per_s``/``burst``
  (applied via ``AdmissionController.set_quota``; the re-seeded bucket
  gives raised tenants instant burst headroom);
* ``deadline``— the submit ``timeout_ms`` for the next tick's traffic
  (the ragged-deadline knob);
* ``canary``  — the live rollout's traffic ``fraction`` (or a
  ``promote`` once it has soaked clean);
* ``bucket_plan`` — an ADVISORY compiled-bucket recommendation from
  the observed flush sizes (recorded in the decision; recompiling a
  live server mid-day is exactly the thing real fleets schedule for
  the next rollout, so the twin records rather than applies it).

Determinism contract: ``decide`` must be a pure function of the
observation stream (plus its own accumulated state) — no RNG, no wall
clock — so two runs of one seed produce identical decisions and the
decision record can be byte-compared across runs.

Policies are scored (sim.py) on SLO-minutes burned, goodput, and
per-tenant fairness; :class:`StaticPolicy` is the do-nothing baseline
every adaptive policy must beat.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from sparkdl_tpu.serving.fleet.admission import TenantQuota

__all__ = ["TickObservation", "PolicyDecision", "Policy", "StaticPolicy",
           "QuotaAutoscaler"]


@dataclass
class TickObservation:
    """What a policy may legally see: the deterministic distillation of
    one tick (racy diagnostics like queue depths stay in ``varz`` and
    out of here — the determinism contract above)."""

    tick: int
    vt: float                       # virtual time at tick END
    arrivals: int
    admitted: int
    completed: int
    shed_total: int
    shed_by_reason: Dict[str, int]
    shed_by_tenant: Dict[str, int]  # tenant name -> sheds this tick
    slo_state: str                  # "ok" | "breach" | "no_data"
    burn_short: Optional[float]
    burn_long: Optional[float]
    canary_active: bool = False
    canary_fraction: float = 0.0
    flush_sizes: Dict[int, int] = field(default_factory=dict)
    #: cumulative per-tenant measured cost (ISSUE 18): deterministic
    #: cost units — completed rows weighted by the lockfile's analytic
    #: FLOPs where the program is covered, plain rows otherwise — so
    #: fairness is scored on what tenants actually burned, not on
    #: request counts, while the byte-compared event stream stays free
    #: of wall-clock values (the determinism contract above)
    cost_by_tenant: Dict[str, float] = field(default_factory=dict)


@dataclass
class PolicyDecision:
    """An ordered list of lever adjustments (canonical dicts — the
    simulator applies them in order and folds them verbatim into the
    byte-compared event record)."""

    adjustments: List[Dict[str, Any]] = field(default_factory=list)

    def add(self, lever: str, **kv: Any) -> None:
        self.adjustments.append({"lever": lever, **kv})

    def __bool__(self) -> bool:
        return bool(self.adjustments)


class Policy:
    """Base policy: fixed deadline, no adjustments."""

    name = "static"

    def __init__(self, *, deadline_ms: float = 750_000.0):
        #: submit timeout for the next tick's traffic, in VIRTUAL ms
        self.deadline_ms = float(deadline_ms)

    def decide(self, obs: TickObservation) -> PolicyDecision:
        return PolicyDecision()


class StaticPolicy(Policy):
    """The scored baseline: whatever quotas the fleet was born with."""


class QuotaAutoscaler(Policy):
    """Burn-rate-driven quota autoscaler + canary shepherd.

    Control law, evaluated once per tick on the PREVIOUS tick's
    observation:

    * while the availability SLO is burning (breach, or short-window
      burn at/above ``burn_trigger``) every tenant shed for quota last
      tick gets its rate and burst multiplied by ``step`` (capped at
      ``max_scale`` × base) — shed traffic under burn means the quota,
      not capacity, is the bottleneck (the twin's no-race envelope
      keeps real queue pressure far from saturation, mirroring a fleet
      with chip headroom);
    * once the burn clears, scaled tenants decay by ``step`` per clean
      tick back toward 1× (quota hygiene: the crowd's grant must not
      become the new normal);
    * deadlines widen ``deadline_stretch`` × while burning (trade tail
      latency for goodput), and relax back when clean;
    * a live canary holds its fraction during burn, grows by
      ``canary_step`` per clean tick, and is promoted after it reaches
      1.0 — so an incident freezes the rollout instead of riding it;
    * every tick it re-derives an advisory ``bucket_plan`` from the
      observed flush-size histogram (largest power of two covering the
      p95 flush, plus the baseline residual buckets).
    """

    name = "quota-autoscaler"

    def __init__(self, base_quota: TenantQuota, *,
                 deadline_ms: float = 750_000.0,
                 step: float = 2.0, max_scale: float = 8.0,
                 burn_trigger: float = 14.4,
                 deadline_stretch: float = 1.5,
                 canary_step: float = 0.25,
                 cost_share_cap: Optional[float] = None):
        super().__init__(deadline_ms=deadline_ms)
        if base_quota.rate_per_s is None:
            raise ValueError("QuotaAutoscaler needs a rate-limited "
                             "base quota to scale")
        self.base_quota = base_quota
        self.step = float(step)
        self.max_scale = float(max_scale)
        self.burn_trigger = float(burn_trigger)
        self.deadline_stretch = float(deadline_stretch)
        self.canary_step = float(canary_step)
        # cost-aware grants (ISSUE 18): a tenant already holding more
        # than this share of the fleet's MEASURED cost
        # (obs.cost_by_tenant) is denied quota scale-ups — shed-count
        # pressure alone must not let the biggest spender crowd the
        # grant loop.  None (default) preserves the pre-cost law.
        self.cost_share_cap = (None if cost_share_cap is None
                               else float(cost_share_cap))
        self._base_deadline_ms = self.deadline_ms
        self._scale: Dict[str, float] = {}
        self._promoted = False

    # -- the control law ---------------------------------------------------
    def _burning(self, obs: TickObservation) -> bool:
        if obs.slo_state == "breach":
            return True
        return (obs.burn_short is not None
                and obs.burn_short >= self.burn_trigger)

    def _quota_for(self, scale: float) -> TenantQuota:
        b = self.base_quota
        return TenantQuota(
            rate_per_s=b.rate_per_s * scale,
            burst=int(round(b.effective_burst() * scale)),
            max_inflight=b.max_inflight, priority=b.priority)

    def decide(self, obs: TickObservation) -> PolicyDecision:
        d = PolicyDecision()
        burning = self._burning(obs)
        quota_sheds = {t: n for t, n in sorted(obs.shed_by_tenant.items())
                       if n > 0}
        if burning and quota_sheds:
            total_cost = sum(obs.cost_by_tenant.values())
            for t in quota_sheds:
                if (self.cost_share_cap is not None and total_cost > 0
                        and (obs.cost_by_tenant.get(t, 0.0) / total_cost
                             > self.cost_share_cap)):
                    # over the measured-cost cap: record the denial so
                    # the decision stream explains the missing grant
                    d.add("quota_denied", tenant=t, reason="cost_share",
                          share=round(obs.cost_by_tenant[t] / total_cost,
                                      6), cap=self.cost_share_cap)
                    continue
                cur = self._scale.get(t, 1.0)
                new = min(self.max_scale, cur * self.step)
                if new != cur:
                    self._scale[t] = new
                    q = self._quota_for(new)
                    d.add("quota", tenant=t, scale=new,
                          rate_per_s=round(q.rate_per_s, 6),
                          burst=int(q.effective_burst()))
        elif not burning:
            for t in sorted(self._scale):
                if quota_sheds.get(t):
                    continue  # still shedding: hold the grant
                new = max(1.0, self._scale[t] / self.step)
                if new != self._scale[t]:
                    self._scale[t] = new
                    q = self._quota_for(new)
                    d.add("quota", tenant=t, scale=new,
                          rate_per_s=round(q.rate_per_s, 6),
                          burst=int(q.effective_burst()))
                if new == 1.0:
                    del self._scale[t]
        # deadline lever
        want_deadline = (self._base_deadline_ms * self.deadline_stretch
                         if burning else self._base_deadline_ms)
        if want_deadline != self.deadline_ms:
            self.deadline_ms = want_deadline
            d.add("deadline", timeout_ms=round(want_deadline, 3))
        # canary shepherd
        if obs.canary_active and not self._promoted:
            if burning:
                pass  # freeze the rollout while the fleet burns
            elif obs.canary_fraction >= 1.0:
                self._promoted = True
                d.add("canary", action="promote")
            else:
                frac = min(1.0, round(obs.canary_fraction
                                      + self.canary_step, 6))
                d.add("canary", fraction=frac)
        # advisory bucket plan from the flush histogram
        plan = self._bucket_recommendation(obs.flush_sizes)
        if plan is not None:
            d.add("bucket_plan", buckets=plan, advisory=True)
        return d

    @staticmethod
    def _bucket_recommendation(flush_sizes: Dict[int, int]
                               ) -> Optional[List[int]]:
        if not flush_sizes:
            return None
        sizes = sorted(flush_sizes)
        total = sum(flush_sizes.values())
        acc = 0
        p95 = sizes[-1]
        for s in sizes:
            acc += flush_sizes[s]
            if acc >= 0.95 * total:
                p95 = s
                break
        top = 1
        while top < p95:
            top *= 2
        plan = sorted({max(1, top // 4), max(1, top // 2), top})
        return plan
