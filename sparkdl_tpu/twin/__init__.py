"""Traffic twin (ISSUE 16): deterministic virtual-time load simulation
plus closed-loop capacity and placement control over a REAL fleet.

Public surface::

    from sparkdl_tpu.twin import (ScenarioConfig, run_day,
                                  StaticPolicy, QuotaAutoscaler,
                                  plan_placement)

    result = run_day(ScenarioConfig(seed=16),
                     policy=QuotaAutoscaler(DEFAULT_TENANT_QUOTA))
    result.scores["slo_minutes"]     # what the day cost
    result.event_digest              # byte-identical across runs
"""

from sparkdl_tpu.twin.clock import VirtualClock
from sparkdl_tpu.twin.placement import (MeshSlice, ModelPlacement,
                                        PlacementError, PlacementPlan,
                                        plan_placement)
from sparkdl_tpu.twin.policy import (Policy, PolicyDecision,
                                     QuotaAutoscaler, StaticPolicy,
                                     TickObservation)
from sparkdl_tpu.twin.scenario import Arrivals, Scenario, ScenarioConfig
from sparkdl_tpu.twin.sim import (DEFAULT_TENANT_QUOTA, TrafficTwin,
                                  TwinResult, run_day)

__all__ = [
    "VirtualClock",
    "MeshSlice", "ModelPlacement", "PlacementError", "PlacementPlan",
    "plan_placement",
    "Policy", "PolicyDecision", "QuotaAutoscaler", "StaticPolicy",
    "TickObservation",
    "Arrivals", "Scenario", "ScenarioConfig",
    "DEFAULT_TENANT_QUOTA", "TrafficTwin", "TwinResult", "run_day",
]
