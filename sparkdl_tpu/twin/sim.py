"""The traffic twin: a seeded day of load replayed against a REAL fleet
on virtual time.

Not a mock: every arrival is a real ``Fleet.submit`` through the real
admission controller, dynamic batcher, single-flight inference cache,
rollout router, and SLO engine — only TIME is simulated.  One
:class:`~sparkdl_tpu.twin.clock.VirtualClock` drives every ``clock=``
injection point ISSUE 16 threaded through the serving stack, so a
24-hour day of token-bucket refills, wait-window flushes, and SLO burn
windows plays out in the seconds the actual inference work takes.

Per-tick protocol (the order is load-bearing):

1. ``inject("twin.tick")`` — the chaos hook (a sleep rule stretches
   wall time; virtual time, and therefore every event byte, must not
   move);
2. apply the policy decision computed from the PREVIOUS tick's
   observation (quotas/deadline/canary) — control acts one tick behind
   its signal, like every real control loop;
3. submit the tick's seeded arrivals (clock FROZEN: every request in a
   tick shares one admission timestamp) — quota sheds raise
   synchronously on this thread and are scored, ``twin.arrival`` error
   rules drop arrivals at the door;
4. advance virtual time one tick and ``Fleet.wake()`` the dispatchers
   (a frozen clock satisfies wait windows only when something
   re-evaluates them);
5. drip the slow-loris stream chunk, if due, through a real
   ``StreamScorer`` whose sink submits with a tiny VIRTUAL deadline —
   inside the batcher's deadline guard, so rows flush without another
   clock advance;
6. drain: wait every future, then spin until the fleet's settle
   callbacks and admission releases have all landed (counter barrier)
   — nothing from tick N may bleed into tick N+1's accounting;
7. take ONE ``Fleet.varz()`` — the tick's SLO evaluation at an exact
   virtual timestamp — distill the :class:`TickObservation`, ask the
   policy for next tick's decision, and append the canonical event
   line.

Determinism (the two-runs-byte-identical bar) holds because the driver
thread is the ONLY submitter (admission order, canary routing order,
and shed order are sequential program order), all randomness is seeded
per-(seed, stream, tick), and the no-race envelope keeps every racy
mechanism out of the scored numbers: arrivals per tick are clipped so
queue pressure stays under the lowest shed threshold (pressure sheds
never fire), deadlines span multiple ticks (expiry sheds never fire),
the digest universe fits the cache (evictions never fire), and event
lines carry only race-free aggregates (``cache.hits + cache.coalesced``
— the split depends on flush timing; the sum does not).
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from sparkdl_tpu.faults import InjectedFault, inject
from sparkdl_tpu.obs.flight import emit as flight_emit
from sparkdl_tpu.obs.slo import SLO
from sparkdl_tpu.serving.errors import (QueueFullError, QuotaExceededError,
                                        ServiceUnavailableError)
from sparkdl_tpu.serving.fleet import Fleet
from sparkdl_tpu.serving.fleet.admission import TenantQuota
from sparkdl_tpu.twin.clock import VirtualClock
from sparkdl_tpu.twin.placement import PlacementPlan, plan_placement
from sparkdl_tpu.twin.policy import (Policy, PolicyDecision, StaticPolicy,
                                     TickObservation)
from sparkdl_tpu.twin.scenario import Scenario, ScenarioConfig
from sparkdl_tpu.utils.metrics import Metrics

__all__ = ["TwinResult", "TrafficTwin", "run_day"]

#: admission envelope: quota a tenant starts the day with (refills 180
#: tokens per 300 s tick — clears the diurnal peak of the Zipf head,
#: sheds hard under a 6x flash crowd; the policy's whole story)
DEFAULT_TENANT_QUOTA = TenantQuota(rate_per_s=0.6, burst=200)

#: barrier limits (WALL seconds — liveness only, never part of scoring)
_FUTURE_WAIT_S = 120.0
_BARRIER_WAIT_S = 60.0


def _model_fn(variables, x):
    import jax.numpy as jnp

    return jnp.tanh(x @ variables["w"])


@dataclass
class TwinResult:
    """One simulated day, fully scored and byte-comparable."""

    policy: str
    config: ScenarioConfig
    event_lines: List[str]
    event_digest: str
    scores: Dict[str, Any]
    placement: Optional[Dict[str, Any]] = None
    final_varz: Dict[str, Any] = field(default_factory=dict)

    @property
    def slo_minutes(self) -> float:
        return self.scores["slo_minutes"]


class _FleetSink:
    """Server-shaped stream sink: each row rides the REAL fleet door as
    tenant ``stream``.  The tiny VIRTUAL deadline is the trick that
    makes streaming work under a frozen clock: ``deadline - now``
    lands inside the batcher's 10 ms deadline guard, so the dispatcher
    flushes the rows immediately instead of waiting for a clock
    advance that cannot happen while ``StreamScorer.run`` blocks this
    thread."""

    def __init__(self, fleet: Fleet, model: str,
                 timeout_ms: float = 5.0):
        self._fleet = fleet
        self._model = model
        self._timeout_ms = float(timeout_ms)

    def submit(self, row):
        return self._fleet.submit(self._model, row, tenant="stream",
                                  timeout_ms=self._timeout_ms)


class TrafficTwin:
    """One (config, policy) pair -> one :class:`TwinResult`.

    ``workdir`` holds the stream journal/artifacts (a throwaway temp
    dir by default); ``chip_hbm_bytes``/``total_chip_budget`` feed the
    placement planner run over the fleet's real entries before traffic
    starts (``None`` skips planning)."""

    def __init__(self, config: Optional[ScenarioConfig] = None, *,
                 policy: Optional[Policy] = None,
                 workdir: Optional[str] = None,
                 default_quota: Optional[TenantQuota] = None,
                 chip_hbm_bytes: Optional[int] = 64 * 1024,
                 total_chip_budget: int = 16):
        self.config = config if config is not None else ScenarioConfig()
        self.policy = policy if policy is not None else StaticPolicy()
        self.scenario = Scenario(self.config)
        self.default_quota = (default_quota if default_quota is not None
                              else DEFAULT_TENANT_QUOTA)
        self._workdir = workdir
        self._chip_hbm_bytes = chip_hbm_bytes
        self._total_chip_budget = int(total_chip_budget)

    # -- fleet under test --------------------------------------------------
    def _variables(self, stream: int) -> Dict[str, np.ndarray]:
        c = self.config
        rng = np.random.default_rng([c.seed, stream])
        return {"w": rng.standard_normal(
            (c.feature_dim, c.feature_dim)).astype(np.float32)}

    def _build_fleet(self, clock: VirtualClock, metrics: Metrics) -> Fleet:
        from sparkdl_tpu.parallel.mesh import get_mesh
        from sparkdl_tpu.serving.cache import InferenceCache

        c = self.config
        slo = SLO("fleet-availability", "availability",
                  good="fleet.completed", total="fleet.requests",
                  objective=0.999)
        # The twin's mesh pin is LOAD-BEARING beyond this file: a head
        # fan-out entry deployed under the twin hands this same mesh to
        # its HeadBank, whose stacked weights are replicated per device
        # — on the 1-device pin the bank costs exactly one copy of HBM
        # and the engine's jit cache keys (id(fn), mesh devices) stay
        # stable across ticks.  Assert the pin rather than trust it.
        twin_mesh = get_mesh(num_devices=1)
        assert len(twin_mesh.devices.flat) == 1, (
            "twin harness requires the single-device mesh pin")
        from sparkdl_tpu.obs.cost import CostLedger

        fleet = Fleet(
            default_quota=self.default_quota,
            # the stream tenant is infrastructure, not a customer: no
            # rate cap, or the slow-loris leg would poison quota scores
            quotas={"stream": TenantQuota()},
            slos=[slo],
            cache=InferenceCache(metrics=metrics),
            # measured cost attribution (ISSUE 18): the policy's
            # cost_by_tenant axis reads this ledger's ROWS/FLOPS units
            # (deterministic — integer rows x lockfile constants, never
            # wall seconds), and max_tenants is sized past any twin day
            # so top-K folding (ranked by wall-measured spend) can
            # never perturb the byte-compared event stream
            cost=CostLedger(max_tenants=max(256, c.tenants + 8)),
            metrics=metrics,
            clock=clock,
            max_batch_size=64,
            max_wait_ms=50.0,
            # no-race envelope: max tick arrivals (3400) must stay
            # under the LOW shed threshold (0.5) of this queue
            max_queue=8192,
            bucket_sizes=(16, 64),
            # single-device dispatch: the twin studies admission/SLO
            # control, not data parallelism — and concurrent multi-
            # model batches over a shared virtual-device mesh would
            # contend on the same collective rendezvous
            mesh=twin_mesh,
        )
        for i, name in enumerate(c.traffic_models):
            fleet.add_model(name, _model_fn, self._variables(31 + i))
        fleet.add_model("scorer", _model_fn, self._variables(47))
        return fleet

    def _plan_placement(self, fleet: Fleet) -> Optional[PlacementPlan]:
        if self._chip_hbm_bytes is None:
            return None
        entries = {name: self._variables(31 + i)
                   for i, name in enumerate(self.config.traffic_models)}
        entries["scorer"] = self._variables(47)
        return plan_placement(entries,
                              chip_hbm_bytes=self._chip_hbm_bytes,
                              total_chip_budget=self._total_chip_budget)

    # -- the drain barrier -------------------------------------------------
    @staticmethod
    def _barrier(fleet: Fleet, expected_completed: int,
                 expected_failed: int) -> None:
        """Spin until every settle callback and admission release from
        this tick has landed — ``f.result()`` returning only proves the
        result is set, not that the done-callbacks ran."""
        deadline = time.monotonic() + _BARRIER_WAIT_S
        while True:
            stats = fleet.stats()
            done = (int(stats.get("fleet.completed", 0))
                    >= expected_completed
                    and int(stats.get("fleet.request_failures", 0))
                    >= expected_failed)
            if done:
                snap = fleet.admission.snapshot()
                inflight = sum(t["inflight"]
                               for t in snap["tenants"].values())
                if inflight == 0:
                    return
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"twin barrier: fleet never quiesced "
                    f"(completed={stats.get('fleet.completed')} "
                    f"expected={expected_completed})")
            fleet.wake()
            time.sleep(0.0005)

    # -- one day -----------------------------------------------------------
    def run_day(self) -> TwinResult:
        c = self.config
        clock = VirtualClock()
        metrics = Metrics()
        owns_workdir = self._workdir is None
        workdir = (tempfile.mkdtemp(prefix="twin-")
                   if owns_workdir else self._workdir)
        os.makedirs(workdir, exist_ok=True)
        fleet = self._build_fleet(clock, metrics)
        try:
            return self._run_day(fleet, clock, workdir)
        finally:
            fleet.close(drain=False)
            if owns_workdir:
                import shutil

                shutil.rmtree(workdir, ignore_errors=True)

    def _run_day(self, fleet: Fleet, clock: VirtualClock,
                 workdir: str) -> TwinResult:
        from sparkdl_tpu.streaming.runner import StreamScorer
        from sparkdl_tpu.streaming.source import MemorySource

        c = self.config
        placement = self._plan_placement(fleet)
        source = MemorySource()
        scorer = StreamScorer(
            _FleetSink(fleet, "scorer"), source,
            journal_path=os.path.join(workdir, "journal.jsonl"),
            out_dir=os.path.join(workdir, "out"),
            stall_deadline_s=60.0)

        canary_model = c.traffic_models[0]
        rollout = None
        decision = PolicyDecision()
        retry_counts: Dict[int, int] = {}
        shed_tenant_cum = np.zeros(c.tenants, dtype=np.int64)
        offered_tenant = np.zeros(c.tenants, dtype=np.int64)
        completed_tenant = np.zeros(c.tenants, dtype=np.int64)
        submitted_total = 0
        offered_total = 0
        shed_total = 0
        fault_drops = 0
        stream_commits = 0
        breach_ticks = 0
        last_phase = None
        event_lines: List[str] = []
        digest = hashlib.sha256()
        decisions_applied: List[Dict[str, Any]] = []

        try:
            for tick in range(c.ticks):
                inject("twin.tick")
                phase = self.scenario.phase(tick)
                if phase != last_phase:
                    flight_emit("twin.scenario", tick=tick, phase=phase,
                                vt=round(clock.now, 3))
                    last_phase = phase

                # (2) control acts on the PREVIOUS tick's observation
                applied = self._apply_decision(fleet, decision, rollout,
                                               canary_model, tick)
                decisions_applied.extend(applied)
                if rollout is not None and not rollout.active:
                    rollout = None  # promoted: the fleet owns v2 now
                if c.canary_tick is not None and tick == c.canary_tick:
                    fleet.add_version(canary_model,
                                      self._variables(37))
                    rollout = fleet.start_rollout(canary_model,
                                                  canary_fraction=0.1)

                # (3) the tick's seeded arrivals, clock frozen
                arr = self.scenario.arrivals(tick, retry_counts)
                futures = []
                shed_reason = {"quota": 0, "pressure": 0, "queue": 0}
                shed_tenant_tick: Dict[int, int] = {}
                admitted_tenant_tick: Dict[int, List[int]] = {}
                for i in range(len(arr)):
                    t_idx = int(arr.tenant[i])
                    offered_tenant[t_idx] += 1
                    offered_total += 1
                    tenant = self.scenario.tenant_name(t_idx)
                    model = c.traffic_models[int(arr.model[i])]
                    payload = self.scenario.payloads[int(arr.digest[i])]
                    try:
                        inject("twin.arrival")
                        fut = fleet.submit(
                            model, payload, tenant=tenant,
                            timeout_ms=self.policy.deadline_ms)
                    except InjectedFault:
                        fault_drops += 1
                        shed_tenant_tick[t_idx] = \
                            shed_tenant_tick.get(t_idx, 0) + 1
                        continue
                    except QuotaExceededError:
                        shed_reason["quota"] += 1
                        shed_tenant_tick[t_idx] = \
                            shed_tenant_tick.get(t_idx, 0) + 1
                        continue
                    except ServiceUnavailableError:
                        shed_reason["pressure"] += 1
                        shed_tenant_tick[t_idx] = \
                            shed_tenant_tick.get(t_idx, 0) + 1
                        continue
                    except QueueFullError:
                        shed_reason["queue"] += 1
                        shed_tenant_tick[t_idx] = \
                            shed_tenant_tick.get(t_idx, 0) + 1
                        continue
                    futures.append(fut)
                    admitted_tenant_tick.setdefault(t_idx, []).append(i)
                admitted = len(futures)
                submitted_total += admitted
                tick_shed = len(arr) - admitted
                shed_total += tick_shed
                for t_idx, n in shed_tenant_tick.items():
                    shed_tenant_cum[t_idx] += n

                # (4) one tick of virtual time, then re-arm the flush
                # triggers the jump just satisfied
                clock.advance(c.tick_s)
                fleet.wake()

                # (5) slow-loris drip through the real scorer
                chunk = self.scenario.stream_payload(tick)
                if chunk is not None:
                    source.feed(chunk)
                    summary = scorer.run(max_chunks=1)
                    submitted_total += int(chunk.shape[0])
                    stream_commits += int(summary["chunks_scored"])

                # (6) drain to a quiesced fleet
                for fut in futures:
                    fut.result(timeout=_FUTURE_WAIT_S)
                self._barrier(fleet, submitted_total, 0)
                for t_idx in admitted_tenant_tick:
                    completed_tenant[t_idx] += len(
                        admitted_tenant_tick[t_idx])

                # (7) the tick's ONE observation -> next tick's decision
                varz = fleet.varz()
                obs = self._observe(varz, tick, clock.now, arr,
                                    admitted, tick_shed, shed_reason,
                                    shed_tenant_tick, rollout)
                if obs.slo_state == "breach":
                    breach_ticks += 1
                decision = self.policy.decide(obs)
                if decision:
                    flight_emit("policy.adjust", tick=tick,
                                policy=self.policy.name,
                                levers=[a["lever"]
                                        for a in decision.adjustments])
                retry_counts = dict(shed_tenant_tick)

                line = self._event_line(obs, varz, decision, phase)
                event_lines.append(line)
                digest.update(line.encode())
                digest.update(b"\n")
        finally:
            scorer.close()

        cache_hits = self._cache_hits(fleet)
        scores = self._scores(
            breach_ticks=breach_ticks, offered=offered_total,
            submitted=submitted_total, shed=shed_total,
            fault_drops=fault_drops, cache_hits=cache_hits,
            stream_commits=stream_commits,
            offered_tenant=offered_tenant,
            completed_tenant=completed_tenant,
            cost_by_tenant=(fleet.cost.tenant_costs()
                            if fleet.cost is not None else {}))
        final_varz = fleet.varz()
        flight_emit("twin.scenario", tick=c.ticks, phase="done",
                    vt=round(clock.now, 3),
                    slo_minutes=scores["slo_minutes"],
                    goodput=scores["goodput"])
        return TwinResult(
            policy=self.policy.name, config=c,
            event_lines=event_lines,
            event_digest=digest.hexdigest(), scores=scores,
            placement=placement.as_dict() if placement else None,
            final_varz=final_varz)

    # -- decision application ----------------------------------------------
    def _apply_decision(self, fleet: Fleet, decision: PolicyDecision,
                        rollout, canary_model: str,
                        tick: int) -> List[Dict[str, Any]]:
        applied: List[Dict[str, Any]] = []
        for adj in decision.adjustments:
            lever = adj["lever"]
            if lever == "quota":
                fleet.admission.set_quota(
                    adj["tenant"],
                    TenantQuota(rate_per_s=adj["rate_per_s"],
                                burst=adj["burst"]))
            elif lever == "deadline":
                self.policy.deadline_ms = float(adj["timeout_ms"])
            elif lever == "canary":
                if rollout is None or not rollout.active:
                    continue  # decision raced the rollout's end
                if adj.get("action") == "promote":
                    fleet.promote(canary_model)
                else:
                    rollout.set_fraction(float(adj["fraction"]))
            # bucket_plan is advisory: recorded, never applied mid-day
            applied.append(dict(adj, tick=tick))
        return applied

    # -- observation / scoring ---------------------------------------------
    def _observe(self, varz: Dict[str, Any], tick: int, vt: float,
                 arr, admitted: int, tick_shed: int,
                 shed_reason: Dict[str, int],
                 shed_tenant_tick: Dict[int, int],
                 rollout) -> TickObservation:
        slo = varz["health"].get("slo") or {}
        objectives = slo.get("objectives") or [{}]
        avail = objectives[0]
        c = self.config
        # deterministic IDEALIZED flush histogram (admitted volume cut
        # at max_batch_size) — the realized one depends on dispatcher
        # timing and would break the byte-identity contract
        flush: Dict[int, int] = {}
        model_counts = np.bincount(
            arr.model[:len(arr)], minlength=len(c.traffic_models))
        for n in model_counts:
            n = int(n)
            full, rem = divmod(n, 64)
            if full:
                flush[64] = flush.get(64, 0) + full
            if rem:
                flush[rem] = flush.get(rem, 0) + 1
        return TickObservation(
            tick=tick, vt=round(vt, 3), arrivals=len(arr),
            admitted=admitted,
            completed=admitted,  # barrier proved every admit settled
            shed_total=tick_shed, shed_by_reason=dict(shed_reason),
            shed_by_tenant={self.scenario.tenant_name(t): n
                            for t, n in sorted(shed_tenant_tick.items())},
            slo_state=slo.get("state", "no_data"),
            burn_short=avail.get("burn_short"),
            burn_long=avail.get("burn_long"),
            canary_active=rollout is not None and rollout.active,
            canary_fraction=(rollout.fraction
                             if rollout is not None and rollout.active
                             else 0.0),
            flush_sizes=flush,
            cost_by_tenant=self._cost_units(varz))

    @staticmethod
    def _cost_units(varz: Dict[str, Any]) -> Dict[str, float]:
        """Cumulative per-tenant MEASURED cost in deterministic units:
        the ledger's attributed lockfile FLOPs where covered, attributed
        rows otherwise.  Safe inside the byte-compared observation: the
        tick barrier settles every charge first, and rows x analytic
        constants carry no wall-clock component (unlike the ledger's
        device_s axis, which stays out of here)."""
        out: Dict[str, float] = {}
        for t, v in ((varz.get("cost") or {}).get("tenants") or {}).items():
            out[t] = (v["flops"] if v["flops"] > 0 else float(v["rows"]))
        return out

    def _event_line(self, obs: TickObservation, varz: Dict[str, Any],
                    decision: PolicyDecision, phase: str) -> str:
        counters = varz["metrics"]["counters"]
        hits_coalesced = (int(counters.get("cache.hits", 0))
                          + int(counters.get("cache.coalesced", 0)))
        doc = {
            "tick": obs.tick, "vt": obs.vt, "phase": phase,
            "arrivals": obs.arrivals, "admitted": obs.admitted,
            "shed": obs.shed_by_reason,
            "shed_tenants": obs.shed_by_tenant,
            "slo": {"state": obs.slo_state,
                    "burn_short": obs.burn_short,
                    "burn_long": obs.burn_long},
            "requests_total": int(counters.get("fleet.requests", 0)),
            "completed_total": int(counters.get("fleet.completed", 0)),
            "cache_hits_coalesced_total": hits_coalesced,
            "canary": {"active": obs.canary_active,
                       "fraction": obs.canary_fraction},
            "cost_by_tenant": obs.cost_by_tenant,
            "decision": decision.adjustments,
        }
        return json.dumps(doc, sort_keys=True, separators=(",", ":"))

    def _cache_hits(self, fleet: Fleet) -> int:
        stats = fleet.metrics.subset("cache.")
        return int(stats.get("cache.hits", 0)
                   + stats.get("cache.coalesced", 0))

    def _scores(self, *, breach_ticks: int, offered: int, submitted: int,
                shed: int, fault_drops: int, cache_hits: int,
                stream_commits: int, offered_tenant: np.ndarray,
                completed_tenant: np.ndarray,
                cost_by_tenant: Optional[Dict[str, float]] = None
                ) -> Dict[str, Any]:
        c = self.config
        active = offered_tenant > 0
        ratios = (completed_tenant[active]
                  / offered_tenant[active].astype(np.float64))
        n = int(ratios.size)
        fairness = (float((ratios.sum() ** 2)
                          / (n * float((ratios ** 2).sum())))
                    if n and float((ratios ** 2).sum()) > 0 else 1.0)
        # Jain over MEASURED cost units (ISSUE 18) across customer
        # tenants — the request-count fairness above can read 1.0 while
        # one tenant burns all the hardware; this axis can't
        costs = np.asarray([v for t, v in sorted(
            (cost_by_tenant or {}).items()) if t != "stream"],
            dtype=np.float64)
        sq = float((costs ** 2).sum())
        cost_fairness = (float((costs.sum() ** 2) / (costs.size * sq))
                         if costs.size and sq > 0 else 1.0)
        return {
            "cost_fairness": round(cost_fairness, 6),
            "slo_minutes": round(breach_ticks * c.tick_s / 60.0, 3),
            "breach_ticks": breach_ticks,
            "goodput": (round((offered - shed) / offered, 6)
                        if offered else 1.0),
            "fairness": round(fairness, 6),
            "cache_hit_rate": (round(cache_hits / submitted, 6)
                               if submitted else 0.0),
            "offered": offered, "submitted": submitted,
            "shed": shed, "fault_drops": fault_drops,
            "stream_commits": stream_commits,
            "tenants_active": n,
        }


def run_day(config: Optional[ScenarioConfig] = None, *,
            policy: Optional[Policy] = None,
            workdir: Optional[str] = None,
            default_quota: Optional[TenantQuota] = None,
            chip_hbm_bytes: Optional[int] = 64 * 1024,
            total_chip_budget: int = 16) -> TwinResult:
    """One seeded day against a real fleet — the module's front door."""
    return TrafficTwin(config, policy=policy, workdir=workdir,
                       default_quota=default_quota,
                       chip_hbm_bytes=chip_hbm_bytes,
                       total_chip_budget=total_chip_budget).run_day()
