"""Seeded traffic-day generator — the twin's deterministic workload.

One :class:`ScenarioConfig` seed expands into a full simulated "day"
of tenant traffic in the ``faults.FaultPlan`` style: every random draw
comes from a per-(seed, stream, tick) ``numpy.random.default_rng``, so
the same config produces the byte-identical arrival sequence on every
run, on every machine — the precondition for the twin's two-runs-
byte-identical acceptance bar.

The day's shape (all knobs on the config):

* **heavy-tailed tenants** — tenant identity is Zipf-distributed, so a
  few head tenants carry most of the traffic and a long tail trickles
  (the "millions of users behind tens of tenants" shape);
* **diurnal ramp** — a sinusoid over the day scales the per-tick
  arrival rate between night trough and evening peak;
* **flash crowd** — for ``[flash_start, flash_end)`` ticks the head
  ``flash_tenants`` tenants multiply their traffic ``flash_multiplier``
  times (the incident the policy engine is scored on);
* **retry storm** — every shed arrival re-presents next tick amplified
  by ``retry_factor`` (capped), so shedding feeds back exactly the way
  real retrying clients make a bad tick worse;
* **Zipfian content** — each arrival's payload is drawn from a fixed
  ``digest_universe`` of feature vectors with Zipf popularity, so the
  REAL content-addressed inference cache sees a realistic hit curve;
* **slow-loris stream** — every ``stream_every`` ticks one small chunk
  drips into a ``MemorySource`` feeding a real ``StreamScorer``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["ScenarioConfig", "Scenario", "Arrivals"]


@dataclass(frozen=True)
class ScenarioConfig:
    """Knobs for one simulated day.  Defaults are the canonical seeded
    day the bench stamps: 288 five-minute virtual ticks, 64 tenants,
    ~110k virtual requests."""

    seed: int = 16
    ticks: int = 288                 # 24h of 5-minute ticks
    tick_s: float = 300.0            # virtual seconds per tick
    tenants: int = 64
    feature_dim: int = 8
    mean_arrivals_per_tick: float = 360.0
    #: hard per-tick clip — keeps worst-case queue pressure below every
    #: admission shed threshold (the twin's no-race envelope; sim.py
    #: module docstring)
    max_arrivals_per_tick: int = 3400
    tenant_zipf: float = 1.1
    digest_universe: int = 512
    digest_zipf: float = 1.05
    diurnal_amplitude: float = 0.45
    flash_start: int = 150
    flash_end: int = 170             # exclusive
    flash_multiplier: float = 6.0
    flash_tenants: int = 8           # the crowd hits the head tenants
    retry_factor: float = 1.5
    retry_cap_per_tick: int = 1200
    canary_tick: Optional[int] = 60  # None = no rollout leg
    stream_every: int = 6            # slow-loris cadence (0 = no stream)
    stream_rows: int = 16
    traffic_models: Tuple[str, ...] = ("ranker", "detector")
    model_mix: Tuple[float, ...] = (0.65, 0.35)

    def __post_init__(self):
        if self.tenants < 1 or self.ticks < 1:
            raise ValueError("tenants and ticks must be >= 1")
        if len(self.traffic_models) != len(self.model_mix):
            raise ValueError("model_mix must pair 1:1 with traffic_models")
        if abs(sum(self.model_mix) - 1.0) > 1e-9:
            raise ValueError(f"model_mix must sum to 1, got "
                             f"{self.model_mix}")
        if not 0 <= self.flash_start <= self.flash_end:
            raise ValueError("need 0 <= flash_start <= flash_end")


@dataclass
class Arrivals:
    """One tick's arrival batch (parallel arrays, one row each)."""

    tenant: np.ndarray   # int32 tenant index
    model: np.ndarray    # int32 index into traffic_models
    digest: np.ndarray   # int32 index into the payload universe
    retry: np.ndarray    # bool — re-presented after a shed last tick
    clipped: int = 0     # arrivals dropped by max_arrivals_per_tick

    def __len__(self) -> int:
        return int(self.tenant.shape[0])


def _zipf_weights(n: int, s: float) -> np.ndarray:
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), s)
    return w / w.sum()


class Scenario:
    """Expands a :class:`ScenarioConfig` into per-tick arrivals.

    Stateless across ticks except for precomputed weight tables — the
    retry-storm feedback (shed counts) is OWNED by the simulator and
    passed back in, so arrival randomness never depends on outcomes
    and the per-tick RNG streams stay independent."""

    def __init__(self, config: ScenarioConfig):
        self.config = config
        c = config
        self._tenant_w = _zipf_weights(c.tenants, c.tenant_zipf)
        self._digest_w = _zipf_weights(c.digest_universe, c.digest_zipf)
        self._model_w = np.asarray(c.model_mix, dtype=np.float64)
        # the fixed content universe: payload i IS digest index i —
        # submitting it exercises the real content-addressed cache
        rng = np.random.default_rng([c.seed, 101])
        self.payloads = rng.standard_normal(
            (c.digest_universe, c.feature_dim)).astype(np.float32)

    # -- shape of the day ---------------------------------------------------
    def diurnal(self, tick: int) -> float:
        c = self.config
        phase = 2.0 * np.pi * (tick / max(1, c.ticks))
        return float(1.0 + c.diurnal_amplitude * np.sin(phase - np.pi / 2))

    def in_flash(self, tick: int) -> bool:
        return self.config.flash_start <= tick < self.config.flash_end

    def phase(self, tick: int) -> str:
        if self.in_flash(tick):
            return "flash_crowd"
        if self.config.canary_tick is not None \
                and tick >= self.config.canary_tick:
            return "canary"
        return "steady"

    # -- per-tick draws -----------------------------------------------------
    def arrivals(self, tick: int,
                 retry_counts: Optional[Dict[int, int]] = None) -> Arrivals:
        """The tick's arrival batch.  ``retry_counts`` (tenant index ->
        sheds last tick) drives the retry storm: each shed re-presents
        ``retry_factor`` times, capped at ``retry_cap_per_tick`` total.
        Fresh randomness comes from the per-tick stream
        ``default_rng([seed, 7, tick])`` only."""
        c = self.config
        rng = np.random.default_rng([c.seed, 7, tick])
        lam = c.mean_arrivals_per_tick * self.diurnal(tick)
        n_base = int(rng.poisson(lam))
        n_flash = 0
        if self.in_flash(tick):
            n_flash = int(rng.poisson(lam * (c.flash_multiplier - 1.0)))
        tenant = [rng.choice(c.tenants, size=n_base, p=self._tenant_w)
                  .astype(np.int32)]
        if n_flash:
            tenant.append(rng.integers(
                0, min(c.flash_tenants, c.tenants), size=n_flash,
                dtype=np.int32))
        retry_list = []
        if retry_counts:
            budget = c.retry_cap_per_tick
            for t in sorted(retry_counts):
                n_retry = min(budget,
                              int(np.ceil(retry_counts[t]
                                          * c.retry_factor)))
                budget -= n_retry
                if n_retry > 0:
                    retry_list.append(np.full(n_retry, t, dtype=np.int32))
                if budget <= 0:
                    break
        n_fresh = n_base + n_flash
        tenant_arr = np.concatenate(tenant + retry_list)
        retry_arr = np.zeros(tenant_arr.size, dtype=bool)
        retry_arr[n_fresh:] = True
        total = tenant_arr.size
        digest = rng.choice(c.digest_universe, size=total,
                            p=self._digest_w).astype(np.int32)
        model = rng.choice(len(c.traffic_models), size=total,
                           p=self._model_w).astype(np.int32)
        # interleave fresh and retry traffic, then clip: the permutation
        # is part of the seeded stream, so the clip (and everything
        # downstream) is deterministic
        order = rng.permutation(total)
        clipped = max(0, total - c.max_arrivals_per_tick)
        keep = order[:c.max_arrivals_per_tick]
        return Arrivals(tenant=tenant_arr[keep], model=model[keep],
                        digest=digest[keep], retry=retry_arr[keep],
                        clipped=clipped)

    def stream_payload(self, tick: int) -> Optional[np.ndarray]:
        """The slow-loris drip: one small chunk every ``stream_every``
        ticks (None otherwise)."""
        c = self.config
        if c.stream_every <= 0 or tick % c.stream_every != 0:
            return None
        rng = np.random.default_rng([c.seed, 23, tick])
        return rng.standard_normal(
            (c.stream_rows, c.feature_dim)).astype(np.float32)

    def tenant_name(self, idx: int) -> str:
        return f"t{int(idx):03d}"
