"""Virtual monotonic time — the axis the traffic twin replays a day on.

A :class:`VirtualClock` is a zero-argument callable returning monotonic
seconds, shaped exactly like ``time.monotonic`` so it plugs into every
``clock=`` injection point ISSUE 16 threaded through the serving stack
(``Fleet``/``Server``/``DynamicBatcher``/``AdmissionController``/
``SLOEngine``).  It only moves when :meth:`advance` is called, so a
simulated day of token-bucket refills, wait-window flushes, deadline
expiries, and SLO burn windows plays out in however little WALL time
the underlying work takes — and identically on every run.

Starting at ``0.0`` (not some process-relative monotonic offset) makes
every virtual timestamp scenario-relative, which is what lets two runs
of the same seed produce byte-identical event sequences.
"""

from __future__ import annotations

from sparkdl_tpu.analysis.lockcheck import named_lock


class VirtualClock:
    """Injectable monotonic clock that advances only on demand.

    Thread-safe: the serving stack reads it from submitter, dispatcher,
    and worker threads while the twin's driver thread advances it.
    Reads are lock-protected so a reader can never observe a torn
    float (and the lock is a ``named_lock`` so SPARKDL_LOCKCHECK
    audits its ordering against the serving locks it nests inside).
    """

    def __init__(self, start: float = 0.0):
        self._lock = named_lock("twin.clock")
        self._now = float(start)

    def __call__(self) -> float:
        with self._lock:
            return self._now

    @property
    def now(self) -> float:
        return self()

    def advance(self, dt: float) -> float:
        """Move virtual time forward by ``dt`` seconds (never backward —
        the clock keeps ``time.monotonic``'s contract) and return the
        new now.  The caller is responsible for waking anything whose
        wait windows the jump may have satisfied (``Fleet.wake``)."""
        if dt < 0:
            raise ValueError(f"virtual time cannot move backward "
                             f"(dt={dt})")
        with self._lock:
            self._now += float(dt)
            return self._now
