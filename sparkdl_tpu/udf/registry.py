"""UDF registry + builders.

``registerKerasImageUDF(name, model, preprocessor)`` keeps the reference's
composition contract (``udf/keras_image_model.py``): [image-struct
converter] ∘ [optional preprocessor] ∘ [model] fused into ONE program — here
one XLA program instead of one merged GraphDef.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


# Declared return types -> arrow types for apply()/pandas_udf emission.
_RETURN_TYPES = {
    "array<float>": pa.list_(pa.float32()),
    "array<double>": pa.list_(pa.float64()),
    "float": pa.float32(),
    "double": pa.float64(),
    "int": pa.int64(),
    "bigint": pa.int64(),
    "string": pa.string(),
    "boolean": pa.bool_(),
}


class RegisteredUDF:
    """A vectorized function column -> column with engine caching."""

    def __init__(self, name: str, fn: Callable[[Sequence], List],
                 returns: str = "array<float>"):
        if returns not in _RETURN_TYPES:
            raise ValueError(f"Unsupported UDF return type {returns!r}; "
                             f"supported: {sorted(_RETURN_TYPES)}")
        self.name = name
        self.fn = fn
        self.returns = returns

    @property
    def arrow_type(self) -> pa.DataType:
        return _RETURN_TYPES[self.returns]

    def __call__(self, column) -> List:
        """column: sequence / pyarrow Array / pandas Series of row values.

        Arrow-aware UDFs (``fn.accepts_arrow``) receive the Arrow column
        as-is — the image hot path reads struct buffers zero-copy instead
        of round-tripping every row through a Python dict (``to_pylist``).
        """
        if isinstance(column, (pa.Array, pa.ChunkedArray)):
            if getattr(self.fn, "accepts_arrow", False):
                return self.fn(column)
            column = column.to_pylist()
        elif hasattr(column, "tolist") and not isinstance(column, list):
            column = column.tolist()
        return self.fn(list(column))


class UDFRegistry:
    """Process-wide name -> UDF map (the stand-in for Spark's SQL function
    registry; ``spark.sql`` is replaced by ``apply`` over our frames)."""

    def __init__(self):
        self._udfs: Dict[str, RegisteredUDF] = {}

    def register(self, name: str, fn: Callable, returns: str = "array<float>"
                 ) -> RegisteredUDF:
        udf = fn if isinstance(fn, RegisteredUDF) else RegisteredUDF(
            name, fn, returns)
        self._udfs[name] = udf
        logger.info("registered UDF %r", name)
        return udf

    def get(self, name: str) -> RegisteredUDF:
        if name not in self._udfs:
            raise KeyError(f"No UDF named {name!r}; registered: "
                           f"{sorted(self._udfs)}")
        return self._udfs[name]

    def names(self) -> List[str]:
        return sorted(self._udfs)

    def apply(self, name: str, dataset, inputCol: str, outputCol: str):
        """SELECT name(inputCol) AS outputCol equivalent over a DataFrame."""
        udf = self.get(name)
        values = udf(dataset.table.column(inputCol))
        return dataset.withColumn(outputCol, pa.array(
            values, type=udf.arrow_type))

    def to_pandas_udf(self, name: str):
        """Bind to pyspark's pandas_udf when pyspark is installed (the
        reference's [D->J] registration step; optional here)."""
        try:
            import pandas as pd
            from pyspark.sql.functions import pandas_udf
        except ImportError as e:
            raise ImportError(
                "pyspark is not installed; to_pandas_udf requires it "
                f"({e})") from e
        udf = self.get(name)

        @pandas_udf(udf.returns)
        def _udf(col: "pd.Series") -> "pd.Series":
            return pd.Series(udf(col))

        return _udf


udf_registry = UDFRegistry()
register_udf = udf_registry.register


def _first_valid_hw(column) -> Optional[Tuple[int, int]]:
    """(height, width) of the first non-null struct row, scanning chunk by
    chunk (no combine_chunks — its int32 offsets overflow past 2 GB)."""
    chunks = (column.chunks if isinstance(column, pa.ChunkedArray)
              else [column])
    for ch in chunks:
        valid = np.asarray(ch.is_valid()) if len(ch) else np.zeros(0, bool)
        if valid.any():
            i0 = int(np.nonzero(valid)[0][0])
            return (int(ch.field("height")[i0].as_py()),
                    int(ch.field("width")[i0].as_py()))
    return None


def _model_input_hw(keras_model) -> Optional[Tuple[int, int]]:
    shape = getattr(keras_model, "input_shape", None)
    if shape and len(shape) == 4 and shape[1] and shape[2]:
        return int(shape[1]), int(shape[2])
    return None


def register_image_udf(name: str, model_function, *,
                       input_size: Optional[Sequence[int]] = None,
                       preprocessor: Optional[Callable] = None,
                       batch_size: int = 32,
                       registry: Optional[UDFRegistry] = None) -> RegisteredUDF:
    """Register a ModelFunction as an image-column UDF.

    Pipeline per call: decode/resize image structs on the host (null rows
    stay null) -> [optional jax ``preprocessor``] ∘ model in one jit program
    on the mesh.  Scoring rides the engine's pipelined execution path
    (``SPARKDL_PIPELINE``): a multi-batch column overlaps H2D, compute,
    and gather across chunks, and the output matrix is preallocated and
    streamed into rather than accumulated per chunk.
    """
    from sparkdl_tpu.graph.function import ModelFunction
    from sparkdl_tpu.image.io import arrowStructsToBatch, structsToBatch
    from sparkdl_tpu.parallel.engine import get_cached_engine

    # Host batches are uint8 **BGR** (the struct's native byte order — host
    # packing stays a pure memcpy); the struct-converter stage swaps to RGB
    # and casts to float ([0,255]) INSIDE the fused program, exactly where
    # the reference's buildSpImageConverter subgraph did both.  The user
    # preprocessor / model sees RGB floats.
    converter = ModelFunction.from_callable(
        lambda x: x[..., ::-1].astype("float32"))
    if preprocessor is not None:
        converter = converter.compose(
            ModelFunction.from_callable(preprocessor))
    model_function = converter.compose(model_function)
    holder = _EngineHolder()  # one engine cache per registration

    def _score(batch: np.ndarray, valid_idx, n: int) -> List[Optional[list]]:
        out: List[Optional[list]] = [None] * n
        if batch.shape[0] == 0:
            return out
        eng = get_cached_engine(holder, model_function,
                                device_batch_size=batch_size)
        # pipelined __call__: pad of chunk k+1 overlaps compute of k and
        # gather of k-1, streaming into one preallocated [n_valid, ...]
        res = np.asarray(eng(batch))
        flat = res.reshape(res.shape[0], -1).astype(np.float32)
        for row_list, i in zip(flat.tolist(), valid_idx):
            out[i] = row_list
        return out

    def fn(rows) -> List[Optional[list]]:
        if isinstance(rows, (pa.Array, pa.ChunkedArray)):
            # Zero-copy hot path: struct buffers -> batch, no dict per row.
            if input_size is not None:
                h, w = int(input_size[0]), int(input_size[1])
            else:
                hw = _first_valid_hw(rows)
                if hw is None:
                    return [None] * len(rows)
                h, w = hw
            batch, ok = arrowStructsToBatch(rows, h, w,
                                            channel_order="bgr",
                                            compact=True)
            return _score(batch, np.nonzero(ok)[0], len(rows))
        valid_idx = [i for i, r in enumerate(rows) if r is not None]
        if not valid_idx:
            return [None] * len(rows)
        if input_size is not None:
            h, w = int(input_size[0]), int(input_size[1])
        else:
            first = rows[valid_idx[0]]
            h, w = int(first["height"]), int(first["width"])
        # legacy list-of-dicts path: structsToBatch emits RGB; the fused
        # converter expects BGR, so flip back (off the Arrow hot path)
        batch = structsToBatch([rows[i] for i in valid_idx], h, w)
        return _score(np.ascontiguousarray(batch[..., ::-1]),
                      valid_idx, len(rows))

    fn.accepts_arrow = True

    registry = registry if registry is not None else udf_registry
    return registry.register(name, fn)


class _EngineHolder:
    """Plain object whose __dict__ hosts get_cached_engine's cache."""


def register_serving_udf(name: str, server, *, returns: str = "array<float>",
                         max_admission_retries: int = 100,
                         timeout_ms: float = float("inf"),
                         registry: Optional[UDFRegistry] = None
                         ) -> RegisteredUDF:
    """Register a running ``serving.Server`` as a column UDF.

    Each row becomes ONE request on the server's admission queue, so
    offline column scoring and any concurrent online traffic share the
    same dynamic micro-batches, deadlines, and metrics — the offline API
    riding the online path.  All rows are submitted asynchronously before
    any result is awaited, letting the batcher fill micro-batches instead
    of ping-ponging one row at a time.

    Backpressure is honored, not bypassed: a ``QueueFullError`` sleeps the
    server's ``retry_after_s`` hint and resubmits, up to
    ``max_admission_retries`` per row.  Null rows stay null.

    Offline rows carry NO deadline by default (``timeout_ms=inf``
    overrides the server's ``default_timeout_ms``): a bulk column submit
    parks most rows deep in the queue, where an online-sized deadline
    would shed the tail and fail the whole apply — offline flow control
    is the backpressure loop above, not deadlines.  Pass a finite
    ``timeout_ms`` to opt back in to shedding.
    """
    import time as _time

    from sparkdl_tpu.serving.errors import QueueFullError

    def _submit_with_backoff(value):
        for _ in range(max(1, int(max_admission_retries))):
            try:
                return server.submit(value, timeout_ms=timeout_ms)
            except QueueFullError as e:
                _time.sleep(max(1e-3, e.retry_after_s))
        # final attempt: let rejection raise
        return server.submit(value, timeout_ms=timeout_ms)

    def fn(rows) -> List[Optional[list]]:
        if isinstance(rows, (pa.Array, pa.ChunkedArray)):
            rows = rows.to_pylist()
        out: List[Optional[list]] = [None] * len(rows)
        futures = []
        for i, r in enumerate(rows):
            if r is None:
                continue
            if isinstance(r, (list, tuple)):
                # arrow list rows arrive as Python lists; submit() treats
                # a list as a PYTREE of scalars, so densify here (struct
                # rows stay dicts — the server's host_preprocess owns those)
                r = np.asarray(r, dtype=np.float32)
            futures.append((i, _submit_with_backoff(r)))
        for i, fut in futures:
            res = np.asarray(fut.result())
            out[i] = [float(v) for v in res.reshape(-1)]
        return out

    registry = registry if registry is not None else udf_registry
    return registry.register(name, fn, returns=returns)


def registerKerasImageUDF(name: str, model_or_file, preprocessor=None,
                          registry: Optional[UDFRegistry] = None
                          ) -> RegisteredUDF:
    """Reference-parity entry (``udf/keras_image_model.py``): register a
    Keras model (object or saved file) as an image UDF, composing the
    optional ``preprocessor`` (jax-traceable ``batch -> batch``) in front.
    """
    import keras

    from sparkdl_tpu.graph.function import ModelFunction

    if isinstance(model_or_file, (str, bytes)):
        model = keras.models.load_model(model_or_file, compile=False)
    else:
        model = model_or_file
    mf = ModelFunction.from_keras(model)
    return register_image_udf(
        name, mf, input_size=_model_input_hw(model),
        preprocessor=preprocessor, registry=registry)
