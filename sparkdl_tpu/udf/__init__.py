"""UDF deployment layer.

Replaces the reference's SQL-UDF path (``python/sparkdl/udf/
keras_image_model.py — registerKerasImageUDF`` + ``graph/tensorframes_udf.py
— makeGraphUDF``): a registered UDF is a vectorized callable over an
image-struct (or tensor) column, backed by the same jit-compiled mesh engine
the transformers use.  Standalone it applies to our Arrow DataFrame; when
pyspark is importable, ``to_pandas_udf`` emits a real
``pyspark.sql.functions.pandas_udf`` so ``SELECT my_udf(image) FROM ...``
works on a Spark cluster with TPU-backed execution.
"""

from sparkdl_tpu.udf.registry import (UDFRegistry, register_image_udf,
                                      register_serving_udf, register_udf,
                                      registerKerasImageUDF, udf_registry)

__all__ = [
    "UDFRegistry", "register_image_udf", "register_serving_udf",
    "register_udf", "registerKerasImageUDF", "udf_registry",
]
