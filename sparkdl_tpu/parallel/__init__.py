"""Device-mesh parallel execution layer.

Replaces the reference's L0 execution engines (Spark task dispatch +
TensorFrames JNI + per-partition ``tf.Session`` — SURVEY.md §1 L0, §3 hot
loops) with XLA:TPU: a ``jax.sharding.Mesh`` over chips, jit-compiled
programs with batch-axis ``NamedSharding``, and XLA collectives over ICI
instead of Spark shuffle/broadcast.
"""

from sparkdl_tpu.parallel.mesh import (batch_sharding, get_mesh,
                                       replicated_sharding)
from sparkdl_tpu.parallel.engine import (CircuitOpenError,
                                         DispatchCircuitBreaker,
                                         InferenceEngine)
from sparkdl_tpu.parallel.pipeline import (PipelinedRunner,
                                           PipelineStageError,
                                           PipelineStageFatalError,
                                           pipeline_enabled_from_env)
from sparkdl_tpu.parallel import distributed

__all__ = [
    "CircuitOpenError",
    "DispatchCircuitBreaker",
    "InferenceEngine",
    "PipelinedRunner",
    "PipelineStageError",
    "PipelineStageFatalError",
    "batch_sharding",
    "distributed",
    "get_mesh",
    "pipeline_enabled_from_env",
    "replicated_sharding",
]
