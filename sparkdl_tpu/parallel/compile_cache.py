"""Persistent XLA compilation cache keyed on the program lockfile.

Every fleet deploy and serving cold-start re-jits each bucket's
dispatch program from scratch — seconds per bucket for real models,
paid again on every process restart even though ``PROGRAMS.lock.json``
proves the programs have not changed since the last audit.  This
module wires JAX's persistent compilation cache (an on-disk executable
store, content-addressed by the compiled program) under a
``SPARKDL_COMPILE_CACHE`` gate and adds the lockfile keying the raw
jax knob lacks: the cache directory carries a manifest recording the
committed lockfile's program records (StableHLO fingerprints, dtype
mixes, donation maps, ...), and a manifest that no longer matches the
live lockfile invalidates the population CLEANLY — stale entries are
purged before a single executable is served, and the drift is
classified back to the graftcheck rule whose invariant moved
(:func:`~sparkdl_tpu.analysis.program.lockfile.diff_records` — a
dropped donation is GC001, an f32 upcast is GC002, and so on), so an
operator reading the ``compile.invalidate`` flight event knows WHY the
cold-start got slow again.

Gate: ``SPARKDL_COMPILE_CACHE`` (the ``SPARKDL_BLACKBOX`` grammar)
  * ``""``/``0``/``false``/``off``/``no`` — DISABLED (the default:
    nothing about compilation changes, and the per-engine probe is one
    module-global read).
  * ``1``/``true``/``on``/``yes`` — enabled at the default directory
    (``~/.cache/sparkdl_tpu/compile``).
  * anything else — treated as the cache DIRECTORY.

Resolution is the faults-pattern process singleton: the first
:class:`~sparkdl_tpu.parallel.engine.InferenceEngine` construction
consults the env exactly once (:func:`ensure_from_env`, serialized
under the configure lock) and every later engine sees the resolved
state.  Configuration failures — unwritable directory, corrupt
manifest, the injected ``compile.cache`` fault — degrade to DISABLED
(fresh compiles, a warning, never a serving outage): the cache is an
optimization, not a dependency.

Hit/miss accounting rides ``jax.monitoring``'s compilation-cache
events into :func:`stats`, which is what the cross-process proof in
run-tests.sh / tests asserts: process A compiles and populates, and a
restarted process B serving the same lockfile-pinned programs reports
ZERO fresh compiles (``misses == 0``) with bit-identical outputs; a
tampered manifest fingerprint forces a purge + clean recompile instead
of ever serving a stale executable.

Sharing contract (ISSUE 14): one cache directory serves ONE
deployment configuration.  The manifest's ``sharding_policies`` set
accumulates every engine policy the deployment's processes note
(restart-order-independent reuse), but a process whose FIRST policy
the set has never held purges the whole population — so two
*unrelated* deployments with different sharding policies pointing at
the same directory would purge each other's executables on every
cold start.  Give them separate directories.  ``note_policy``'s
manifest union is atomic per write but not cross-process-locked: two
processes adding different NEW policies at the same instant can drop
one addition, which costs at most one later purge + repopulation,
never a stale executable.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from sparkdl_tpu.analysis.lockcheck import named_lock
from sparkdl_tpu.faults import inject
from sparkdl_tpu.obs.flight import emit as flight_emit
from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = [
    "MANIFEST_NAME",
    "DEFAULT_DIR",
    "dir_from_env",
    "configure",
    "configure_from_env",
    "ensure_from_env",
    "state",
    "stats",
    "enabled",
]

#: the lockfile-keyed manifest written next to jax's cache entries;
#: upper-cased so it can never collide with a jax ``jit_*`` entry name
MANIFEST_NAME = "SPARKDL_COMPILE_CACHE_MANIFEST.json"
MANIFEST_SCHEMA = 1

DEFAULT_DIR = os.path.join(os.path.expanduser("~"), ".cache",
                           "sparkdl_tpu", "compile")

_OFF = ("", "0", "false", "off", "no")
_ON = ("1", "true", "on", "yes")

# -- process singleton (the faults.inject / serving.cache pattern) ---------
_UNSET = object()
_state: Any = _UNSET    # None = disabled; dict = the resolved snapshot
_lock = named_lock("parallel.compile_cache")
_counts = {"hits": 0, "misses": 0}
_listener = [False]


def dir_from_env() -> Optional[str]:
    """The cache directory per the ``SPARKDL_COMPILE_CACHE`` grammar
    (module docstring), or None when the knob is off/unset."""
    raw = os.environ.get("SPARKDL_COMPILE_CACHE", "").strip()
    low = raw.lower()
    if low in _OFF:
        return None
    if low in _ON:
        return DEFAULT_DIR
    return os.path.expanduser(raw)


def _install_listener() -> None:
    """Count jax's compilation-cache monitoring events into
    :func:`stats` (registered once; the events only fire while the
    persistent cache is active, so an idle listener costs nothing)."""
    if _listener[0]:
        return
    import jax.monitoring as monitoring

    def _count(name: str, **kwargs: Any) -> None:
        if name == "/jax/compilation_cache/cache_hits":
            _counts["hits"] += 1
        elif name == "/jax/compilation_cache/cache_misses":
            _counts["misses"] += 1

    monitoring.register_event_listener(_count)
    _listener[0] = True


def _norm(value: Any) -> Any:
    return json.loads(json.dumps(value, sort_keys=True))


def _purge(dir_path: str) -> int:
    """Drop every cache entry (the manifest is rewritten by the caller)
    so nothing stale can ever be served after an invalidation; returns
    the number of entries removed."""
    removed = 0
    for name in os.listdir(dir_path):
        if name == MANIFEST_NAME:
            continue
        try:
            os.unlink(os.path.join(dir_path, name))
            removed += 1
        except OSError:
            logger.warning("compile cache: could not purge stale entry "
                           "%s", name)
            raise  # a stale executable we cannot remove must disable
    return removed


def _validate_manifest(dir_path: str,
                       lockfile_path: Optional[str],
                       policy: Optional[str] = None
                       ) -> Tuple[Dict[str, Any], List[Tuple[str, dict]]]:
    """Compare the cache directory's manifest against the live
    committed lockfile AND the process's mesh/partition-rule policy
    (ISSUE 14 — ``InferenceEngine.compile_policy()``); purge + classify
    on drift.  The manifest records the SET of policies the populating
    deployment's engines used (``sharding_policies`` — every engine
    notes its policy via :func:`note_policy`, so a fleet mixing
    sharded and replicated entries reuses across restarts regardless
    of engine-construction order); a restart whose first policy is NOT
    in the stored set — same programs, different weight sharding —
    purges cleanly, classified GC005 (sharding layout changed),
    instead of serving/accumulating executables compiled for a layout
    this deployment no longer uses.  ``policy=None`` (test/CLI
    configures) is a wildcard: it never invalidates a populated set.
    Returns the state fields and the flight events to emit AFTER the
    configure lock is released (the recorder never runs under the locks
    it observes)."""
    import jax

    from sparkdl_tpu.analysis.program.lockfile import (DEFAULT_LOCKFILE,
                                                       diff_records,
                                                       read_lockfile)

    lock_path = lockfile_path or DEFAULT_LOCKFILE
    programs: Dict[str, Any] = {}
    if os.path.isfile(lock_path):
        programs = read_lockfile(lock_path).get("programs", {})
    manifest_path = os.path.join(dir_path, MANIFEST_NAME)
    env = {"jax_version": jax.__version__,
           "backend": jax.default_backend()}
    reused = False
    invalidated = False
    drift_rules: List[str] = []
    purged = 0
    events: List[Tuple[str, dict]] = []
    policies: List[str] = [policy] if policy else []
    if os.path.isfile(manifest_path):
        stored: Optional[Dict[str, Any]] = None
        try:
            with open(manifest_path, "r", encoding="utf-8") as fh:
                stored = json.load(fh)
        except (OSError, json.JSONDecodeError):
            stored = None  # corrupt manifest == unprovable population
        stored_policies = (list(stored.get("sharding_policies") or [])
                           if stored is not None else [])
        policy_ok = policy is None or policy in stored_policies
        if (stored is not None
                and stored.get("schema_version") == MANIFEST_SCHEMA
                and stored.get("jax_version") == env["jax_version"]
                and stored.get("backend") == env["backend"]
                and policy_ok
                and _norm(stored.get("programs", {})) == _norm(programs)):
            reused = True
            policies = sorted(set(stored_policies)
                              | ({policy} if policy else set()))
        else:
            invalidated = True
            if stored is not None and isinstance(
                    stored.get("programs"), dict):
                current = [{"name": n, **rec}
                           for n, rec in sorted(programs.items())]
                findings = diff_records(
                    {"programs": stored["programs"]}, current)
                drift_rules = sorted({f.code for f in findings})
                if not drift_rules and not policy_ok:
                    # same programs, different weight-sharding policy:
                    # the executables were compiled for layouts this
                    # deployment no longer uses
                    drift_rules = ["GC005"]
            purged = _purge(dir_path)
            events.append(("compile.invalidate", {
                "dir": dir_path, "purged_entries": purged,
                "drift_rules": drift_rules or ["manifest"],
            }))
            logger.warning(
                "persistent compile cache at %s invalidated: %s; purged "
                "%d stale entries (fresh compiles ahead)", dir_path,
                (f"lockfile drift classified {drift_rules}"
                 if drift_rules else "unreadable/foreign manifest"),
                purged)
    doc = {"schema_version": MANIFEST_SCHEMA, **env,
           "sharding_policies": policies, "programs": programs}
    tmp = manifest_path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, sort_keys=True)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, manifest_path)
    fields = {"reused": reused, "invalidated": invalidated,
              "drift_rules": drift_rules, "purged_entries": purged,
              "lockfile_programs": len(programs),
              "sharding_policy": policy,
              "sharding_policies": policies, **env}
    events.append(("compile.persist", {
        "dir": dir_path, "reused": reused,
        "lockfile_programs": len(programs)}))
    return fields, events


def _configure_locked(dir_path: Optional[str],
                      lockfile_path: Optional[str],
                      policy: Optional[str] = None
                      ) -> Tuple[Optional[Dict[str, Any]],
                                 List[Tuple[str, dict]]]:
    """Resolve the cache state (called under the configure lock);
    returns (state, flight events to emit after release).  Any failure
    degrades to DISABLED — the cache must never take down serving."""
    if dir_path is None:
        return None, []
    try:
        # chaos hook: an injected error here is a corrupt cache
        # dir/manifest the configure path must absorb (degrade to
        # fresh compiles), never propagate into engine construction
        inject("compile.cache")
        os.makedirs(dir_path, exist_ok=True)
        fields, events = _validate_manifest(dir_path, lockfile_path, policy)
        import jax

        jax.config.update("jax_enable_compilation_cache", True)
        jax.config.update("jax_compilation_cache_dir", dir_path)
        # cold-start elimination wants EVERY dispatch program persisted,
        # not only the slow-to-compile ones jax's defaults target
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _install_listener()
        return {"dir": dir_path, **fields}, events
    # graftlint: allow=SDL003 reason=the cache is an optimization: any configure failure (unwritable dir, corrupt manifest, injected fault) is logged and degrades to fresh compiles
    except Exception as e:  # noqa: BLE001
        logger.warning("persistent compile cache disabled: %s: %s "
                       "(serving continues with fresh compiles)",
                       type(e).__name__, e)
        return None, []


def configure(dir_path: Optional[str],
              lockfile_path: Optional[str] = None,
              policy: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Install (or disable, with ``None``) the persistent compile cache
    at ``dir_path``, validating its manifest against ``lockfile_path``
    (default: the committed ``PROGRAMS.lock.json``) and the process's
    mesh/partition-rule ``policy`` (ISSUE 14; ``None`` = no policy
    recorded — a later engine-driven configure with a real policy
    invalidates such a manifest once, classified GC005)."""
    global _state
    with _lock:
        st, events = _configure_locked(dir_path, lockfile_path, policy)
        _state = st
    for name, attrs in events:
        flight_emit(name, **attrs)
    return st


def configure_from_env() -> Optional[Dict[str, Any]]:
    """(Re-)configure from ``SPARKDL_COMPILE_CACHE``."""
    return configure(dir_from_env())


def ensure_from_env(policy: Optional[str] = None
                    ) -> Optional[Dict[str, Any]]:
    """The per-engine probe: resolve ``SPARKDL_COMPILE_CACHE`` exactly
    once per process (first engine construction), then one
    module-global read (plus a policy-set membership check) forever
    after.  Every engine passes its ``compile_policy()`` string: the
    first one validates the manifest against the stored policy SET,
    and later engines with NEW policies join the set via
    :func:`note_policy` — so a deployment mixing sharded and
    replicated engines reuses across restarts regardless of which
    engine constructs first, while a policy the deployment never used
    still purges."""
    global _state
    st = _state
    if st is not _UNSET:
        if policy is not None:
            note_policy(policy)
        return _state if isinstance(_state, dict) else None
    with _lock:
        if _state is _UNSET:
            st, events = _configure_locked(dir_from_env(), None, policy)
            _state = st
        else:
            st, events = _state, []
    for name, attrs in events:
        flight_emit(name, **attrs)
    if policy is not None:
        note_policy(policy)
    return _state if isinstance(_state, dict) else None


def note_policy(policy: str) -> None:
    """Record one engine's mesh/partition policy in the manifest's
    policy SET (no purge — adding a layout to a live deployment only
    widens what a restart may reuse).  No-op while disabled or when
    the policy is already recorded (the per-engine fast path)."""
    global _state
    st = _state
    if (not isinstance(st, dict)
            or policy in st.get("sharding_policies", [])):
        return
    with _lock:
        st = _state
        if (not isinstance(st, dict)
                or policy in st.get("sharding_policies", [])):
            return
        manifest_path = os.path.join(st["dir"], MANIFEST_NAME)
        try:
            with open(manifest_path, "r", encoding="utf-8") as fh:
                doc = json.load(fh)
            policies = sorted(set(doc.get("sharding_policies") or [])
                              | {policy})
            doc["sharding_policies"] = policies
            tmp = manifest_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, sort_keys=True)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, manifest_path)
            _state = dict(st, sharding_policies=policies)
        except (OSError, json.JSONDecodeError) as e:
            logger.warning(
                "compile cache: could not record sharding policy in "
                "manifest (%s: %s); a restart constructing this "
                "layout's engine first will purge once",
                type(e).__name__, e)


def state() -> Optional[Dict[str, Any]]:
    """The resolved cache state (None while disabled/unresolved) —
    JSON-serializable; bench lines and the subprocess proof read it."""
    st = _state
    return dict(st) if isinstance(st, dict) else None


def stats() -> Dict[str, int]:
    """Persistent-cache hit/miss counters (jax.monitoring events) for
    THIS process: a warm restart serving lockfile-pinned programs shows
    ``misses == 0`` — the zero-fresh-compiles proof."""
    return dict(_counts)


def enabled() -> bool:
    return isinstance(_state, dict)


def _reset_for_tests() -> None:
    """Forget the resolved state (tests re-resolve under a different
    env); jax's own cache-dir config is cleared too so later engines
    in this process stop persisting."""
    global _state
    with _lock:
        _state = _UNSET
        _counts["hits"] = 0
        _counts["misses"] = 0
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:  # noqa: BLE001 — best-effort test cleanup
        logger.info("compile cache reset: could not clear jax cache dir")
