"""Mesh construction + sharding helpers.

The TPU analog of the reference's cluster topology: where Spark mapped
DataFrame partitions onto executor JVMs (SURVEY.md §2 "parallelism-strategy
inventory"), we map batch rows onto chips through a ``jax.sharding.Mesh``.
Axis names:

  * ``data``  — batch-parallel axis (inference + gradient data parallelism).
    ICI collectives (psum for gradients) ride this axis.
  * ``model`` — reserved for tensor-parallel sharding of oversized heads;
    size 1 for every model in the zoo (<=25M params need no TP).

Multi-host note: ``get_mesh`` uses ``jax.devices()`` which spans all hosts
under multi-controller jax.distributed initialization, so the same code
scales from 1 chip to a pod slice; per-host data feeding belongs to the IO
layer (``jax.make_array_from_process_local_data``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

DATA_AXIS = "data"
MODEL_AXIS = "model"


def get_mesh(num_devices: Optional[int] = None, model_parallel: int = 1,
             devices: Optional[Sequence] = None):
    """Build a (data, model) mesh over the available chips.

    ``num_devices`` limits the mesh to the first N devices (useful for
    carving a tuning fan-out into independent slices); default = all.
    """
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    if num_devices is not None:
        if num_devices > len(devs):
            raise ValueError(
                f"Requested {num_devices} devices; only {len(devs)} present")
        devs = devs[:num_devices]
    n = len(devs)
    if n % model_parallel:
        raise ValueError(
            f"model_parallel={model_parallel} does not divide {n} devices")
    grid = np.asarray(devs).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def batch_sharding(mesh, ndim: int = 1):
    """NamedSharding that splits axis 0 (the batch) across the data axis and
    replicates everything else."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated_sharding(mesh):
    """NamedSharding that replicates (model params on every chip — the TPU
    replacement for Spark's torrent-broadcast of the model GraphDef)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())
