"""Mesh construction + sharding helpers.

The TPU analog of the reference's cluster topology: where Spark mapped
DataFrame partitions onto executor JVMs (SURVEY.md §2 "parallelism-strategy
inventory"), we map batch rows onto chips through a ``jax.sharding.Mesh``.
Axis names:

  * ``data``  — batch-parallel axis (inference + gradient data parallelism).
    ICI collectives (psum for gradients) ride this axis.
  * ``model`` — tensor-parallel axis for WEIGHT sharding: dense/conv
    kernels split their output dimension across it (ISSUE 14), so the
    per-chip HBM cost of the params is ``bytes / model_axis`` instead of
    one full copy per chip.  Size 1 keeps everything replicated (the
    zoo's <=25M-param models need no TP on real chips, but the same rules
    scale a head that does not fit one chip).

Weight-sharding policy (ISSUE 14): :func:`match_partition_rules` maps
regex rules over ``/``-joined param paths to ``PartitionSpec``s (the
SNIPPETS [2] shape: scalars always replicated, no-match is a loud
error), :func:`default_partition_rules` is the per-zoo-family default
(kernels/embeddings split their last dim on the ``model`` axis iff the
axis is >1 and the dim divides — the SNIPPETS [3] divisibility
fallback; everything else replicated), and
:func:`resolve_param_shardings` turns either into the per-leaf
``NamedSharding`` pytree the inference engine device_puts weights under
and compiles against.  On a model-axis-1 mesh every rule resolves to
replicated and the engine collapses the policy to the classic
replicate-everything layout — byte-identical programs, same executable
cache keys.

Multi-host note: ``get_mesh`` uses ``jax.devices()`` which spans all hosts
under multi-controller jax.distributed initialization, so the same code
scales from 1 chip to a pod slice; per-host data feeding belongs to the IO
layer (``jax.make_array_from_process_local_data``).
"""

from __future__ import annotations

import hashlib
import re
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

DATA_AXIS = "data"
MODEL_AXIS = "model"


def get_mesh(num_devices: Optional[int] = None, model_parallel: int = 1,
             devices: Optional[Sequence] = None):
    """Build a (data, model) mesh over the available chips.

    ``num_devices`` limits the mesh to the first N devices (useful for
    carving a tuning fan-out into independent slices); default = all.
    """
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    if num_devices is not None:
        if num_devices > len(devs):
            raise ValueError(
                f"Requested {num_devices} devices; only {len(devs)} present")
        devs = devs[:num_devices]
    n = len(devs)
    if n % model_parallel:
        raise ValueError(
            f"model_parallel={model_parallel} does not divide {n} devices")
    grid = np.asarray(devs).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def batch_sharding(mesh, ndim: int = 1):
    """NamedSharding that splits axis 0 (the batch) across the data axis and
    replicates everything else."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated_sharding(mesh):
    """NamedSharding that replicates (model params on every chip — the TPU
    replacement for Spark's torrent-broadcast of the model GraphDef)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


# ---------------------------------------------------------------------------
# tensor-parallel weight sharding: partition rules (ISSUE 14)

def param_path_str(path) -> str:
    """``/``-joined name of one param leaf from a
    ``tree_flatten_with_path`` key path — THE spelling every rule regex
    matches against (shared with ``parallel.train.resolve_param_specs``
    and the program auditor's sharding summary, so a rule written for
    the engine audits identically)."""
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def match_partition_rules(rules, params):
    """Pytree of ``PartitionSpec`` for ``params`` according to ``rules``
    (the SNIPPETS [2] ``match_partition_rules`` shape).

    ``rules`` is an ordered sequence of ``(regex, spec)`` pairs; the
    FIRST rule whose regex ``re.search``-matches the leaf's ``/``-joined
    path wins.  ``spec`` is a ``PartitionSpec`` or a callable
    ``(leaf) -> PartitionSpec`` (how the default rules make the split
    shape- and divisibility-aware).  Scalars (rank 0 or one element)
    are never partitioned; a leaf no rule matches raises ``ValueError``
    naming it — a silent replicate there would un-shard a param the
    policy meant to split, and the HBM math would quietly break.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def get_spec(path, leaf):
        name = param_path_str(path)
        shape = tuple(getattr(leaf, "shape", ()))
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()  # never partition scalar values
        for pat, spec in compiled:
            if pat.search(name) is not None:
                return spec(leaf) if callable(spec) else spec
        raise ValueError(
            f"Partition rule not found for param: {name!r} "
            f"(shape {shape}); add a rule (a catch-all (r'.*', "
            f"PartitionSpec()) replicates the rest)")

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    return jax.tree_util.tree_unflatten(
        treedef, [get_spec(p, l) for p, l in flat])


def default_partition_rules(mesh) -> List[Tuple[str, Any]]:
    """The per-zoo-family default rule set: dense/conv ``kernel`` (and
    ``embedding``) leaves split their LAST dimension — output features /
    channels, so no cross-shard reduction enters the math and sharded
    outputs stay bit-identical to replicated ones — across the mesh's
    ``model`` axis, iff that axis is >1 and the dim divides it (the
    SNIPPETS [3] divisibility fallback); everything else (biases, BN
    scales/stats, scalars) stays replicated."""
    from jax.sharding import PartitionSpec as P

    model = int(mesh.shape[MODEL_AXIS])

    def split_last_dim(leaf):
        shape = tuple(leaf.shape)
        if (model > 1 and len(shape) >= 2 and shape[-1] % model == 0):
            return P(*([None] * (len(shape) - 1)), MODEL_AXIS)
        return P()

    return [
        (r"(^|/)(kernel|embedding)$", split_last_dim),
        (r".*", P()),
    ]


def _axis_shards(mesh, spec) -> int:
    """How many ways ``spec`` splits a leaf on ``mesh`` (product of the
    named axis sizes; 1 = replicated)."""
    shards = 1
    for entry in tuple(spec):
        if entry is None:
            continue
        for axis in (entry if isinstance(entry, (tuple, list)) else (entry,)):
            shards *= int(mesh.shape[axis])
    return shards


def spec_shards_leaf(mesh, spec, shape) -> bool:
    """True iff ``spec`` actually divides a leaf of ``shape`` on
    ``mesh`` — per-dim divisibility, the check behind the resolution
    fallback and GC005's sharded-leaf audit."""
    for dim, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        n = 1
        for axis in axes:
            n *= int(mesh.shape[axis])
        if dim >= len(shape) or shape[dim] % n:
            return False
    return True


def resolve_param_shardings(params, mesh, rules=None, specs=None):
    """``(shardings, specs)`` pytrees for ``params``: per-leaf
    ``NamedSharding`` (what the engine device_puts and compiles against)
    and the matched ``PartitionSpec``s (what digests/audits record).

    ``rules`` — a rule list for :func:`match_partition_rules`, or a
    callable ``mesh -> rule list`` (the :func:`default_partition_rules`
    factory form the zoo serving bundle passes); ``None`` uses the
    default rules.  ``specs`` — an EXPLICIT per-leaf pytree mirroring
    ``params`` (``PartitionSpec`` or ``NamedSharding`` leaves; a
    structure mismatch raises rather than pairing specs with the wrong
    leaves) — takes precedence over ``rules``.  Either way, any spec
    that does NOT divide its leaf on this mesh falls back to
    replicated for that leaf (the SNIPPETS [3] shape, THE one spelling
    of the fallback contract) — a spec never turns into a lowering
    crash."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def _is_spec(s):
        return isinstance(s, (P, NamedSharding))

    if specs is not None:
        params_def = jax.tree_util.tree_structure(params)
        specs_def = jax.tree_util.tree_structure(specs, is_leaf=_is_spec)
        if specs_def != params_def:
            raise ValueError(
                f"param shardings must mirror the params pytree "
                f"structure (specs {specs_def} vs params {params_def}) "
                f"— a flat or reordered spec tree would silently pair "
                f"specs with the wrong leaves")
        flat_s = [s.spec if isinstance(s, NamedSharding) else s
                  for s in jax.tree_util.tree_leaves(specs,
                                                     is_leaf=_is_spec)]
        treedef = params_def
    else:
        if rules is None:
            rules = default_partition_rules(mesh)
        elif callable(rules):
            rules = rules(mesh)
        matched = match_partition_rules(rules, params)
        flat_s, treedef = jax.tree_util.tree_flatten(
            matched, is_leaf=_is_spec)
    flat_p = jax.tree_util.tree_leaves(params)
    resolved = []
    for leaf, spec in zip(flat_p, flat_s):
        shape = tuple(getattr(leaf, "shape", ()))
        if tuple(spec) and not spec_shards_leaf(mesh, spec, shape):
            spec = P()  # indivisible on this mesh: replicate the leaf
        resolved.append(spec)
    out_specs = jax.tree_util.tree_unflatten(treedef, resolved)
    shardings = jax.tree_util.tree_unflatten(
        treedef, [NamedSharding(mesh, s) for s in resolved])
    return shardings, out_specs


def spec_is_replicated(spec) -> bool:
    """True iff ``spec`` names no mesh axis — ``P()`` and its
    semantically-identical spellings like ``P(None, None)`` both
    replicate."""
    return all(entry is None for entry in tuple(spec))


def specs_all_replicated(specs) -> bool:
    """True iff every matched spec replicates — the engine then
    collapses the policy to the classic replicate-everything layout,
    keeping the lowered programs and executable cache keys
    byte-identical to the pre-ISSUE-14 stack (the model-axis-1
    compatibility contract).  ``P(None, None)`` counts as replicated:
    it names no axis, so it must not fork a second compilation of the
    byte-identical program."""
    import jax

    return all(spec_is_replicated(s) for s in jax.tree_util.tree_leaves(
        specs, is_leaf=lambda s: isinstance(s, jax.sharding.PartitionSpec)))


def spec_to_json(spec) -> list:
    """A ``PartitionSpec`` as a JSON-able per-dim list (``None`` |
    axis name | list of axis names) — the lockfile/manifest spelling."""
    out: list = []
    for entry in tuple(spec):
        if isinstance(entry, (tuple, list)):
            out.append([str(a) for a in entry])
        else:
            out.append(None if entry is None else str(entry))
    return out


def partition_digest(specs=None) -> str:
    """Canonical digest of a resolved sharding policy: sha256 over the
    sorted ``path=spec`` lines (``"replicated"`` for the no-policy /
    all-replicated case).  Keys the engine's jit cache and the
    persistent compile-cache manifest, so two processes (or two engines)
    agree on "same policy" by content, not object identity."""
    import jax

    if specs is None:
        return "replicated"
    flat, _ = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda s: isinstance(s, jax.sharding.PartitionSpec))
    # canonical per-leaf rendering: every replicated spelling (P(),
    # P(None), P(None, None)) digests identically — two processes whose
    # layouts are semantically equal must agree on "same policy"
    lines = sorted(
        f"{param_path_str(p)}="
        f"{[] if spec_is_replicated(s) else spec_to_json(s)}"
        for p, s in flat)
    if all(line.endswith("=[]") for line in lines):
        return "replicated"
    return hashlib.sha256("\n".join(lines).encode()).hexdigest()


def param_sharding_stats(mesh, params, specs=None) -> dict:
    """HBM accounting for a (possibly sharded) param pytree: total
    logical bytes, per-chip bytes under the specs (``None`` = all
    replicated), largest replicated leaf, and the sharded/replicated
    ratio — the numbers the bench rider and ``Server.varz`` stamp."""
    import jax

    leaves = jax.tree_util.tree_leaves(params)
    if specs is None:
        flat_s = [None] * len(leaves)
    else:
        flat_s = jax.tree_util.tree_leaves(
            specs,
            is_leaf=lambda s: isinstance(s, jax.sharding.PartitionSpec))
    total = 0
    per_chip = 0
    largest_replicated = 0
    sharded_leaves = 0
    for leaf, spec in zip(leaves, flat_s):
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = np.dtype(getattr(leaf, "dtype", np.float64))
        size = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        total += size
        shards = 1 if spec is None else _axis_shards(mesh, spec)
        if shards > 1:
            sharded_leaves += 1
            per_chip += size // shards
        else:
            per_chip += size
            largest_replicated = max(largest_replicated, size)
    return {
        "mesh_shape": {str(n): int(mesh.shape[n]) for n in mesh.axis_names},
        "param_bytes_total": total,
        "param_bytes_per_chip": per_chip,
        "largest_replicated_leaf_bytes": largest_replicated,
        "sharded_leaves": sharded_leaves,
        "total_leaves": len(leaves),
        "sharded_vs_replicated_ratio": (round(per_chip / total, 4)
                                        if total else 1.0),
    }
