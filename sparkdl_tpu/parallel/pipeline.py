"""Pipelined host/device execution for the scoring stack.

PERF.md's round-5 ledger shows the end-to-end configs are HOST-bound, not
device-bound: the device idles while the host decodes/packs the next
batch, and the host idles while a blocking dispatch+fetch round trip
(~120 ms on the relayed link) completes.  This module is the tf.data/
prefetch analog for the engine: a bounded-depth stage graph

    host prepare (decode/pack/pad)  ->  H2D + device dispatch
                                    ->  D2H gather + host cast

run on overlapping worker threads with backpressure queues, so batch k+1
decodes while batch k computes and batch k-1 gathers.  ``jax``'s async
dispatch provides the device-side overlap; this layer provides the
host-side one.

Contracts:
  * BIT-IDENTICAL outputs to the serial path — the stages call the exact
    same engine methods (``_pad``/``run_padded``/``_stack_group``/
    ``_dispatch_group``/``_trim``) in the exact same per-piece order; the
    FIFO queues only move them onto threads.
  * bounded residency — every inter-stage queue is bounded, so host prep
    runs at most ``depth`` items ahead and at most ``window`` dispatched
    batches (groups under ``batches_per_dispatch``) are device-resident,
    exactly the serial path's in-flight window.
  * per-stage queue-depth / stall metrics land in the engine's
    ``utils.metrics.Metrics`` registry under ``pipeline.*`` (surfaced by
    ``bench.py`` per-config JSON lines and ``Server.stats``).

``SPARKDL_PIPELINE=0`` is the escape hatch: every scoring surface
(``InferenceEngine.map_batches``/``__call__``, the zoo/image/tensor
transformers, image UDFs, and serving) then runs the serial path.

Failure domain (ISSUE 4): each stage loop carries a fault-injection
site (``pipeline.prepare`` / ``pipeline.dispatch`` / ``pipeline.gather``
— :mod:`sparkdl_tpu.faults`), and a stage crash — injected or real —
cancels the graph, joins every worker with a bounded timeout, and
re-raises consumer-side as :class:`PipelineStageError` naming the stage
and piece index, with the original exception chained.  No queue is left
with a blocked producer/consumer and no thread outlives the run.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Dict, Iterable, Iterator, Optional

import numpy as np

from sparkdl_tpu.faults import inject
from sparkdl_tpu.obs.trace import get_tracer
from sparkdl_tpu.utils.logging import get_logger
from sparkdl_tpu.utils.metrics import Metrics

logger = get_logger(__name__)

_DONE = object()    # end-of-stream marker flowing through every queue
_ABORT = object()   # returned by queue helpers when the run was cancelled


class PipelineStageError(RuntimeError):
    """A pipeline worker stage crashed.  Carries the failure DOMAIN —
    ``stage`` (``prepare``/``dispatch``/``gather``) and ``piece`` (the
    0-based piece index the stage was working when it died; -1 when it
    crashed before touching one) — so a production incident names the
    failing layer instead of surfacing a bare exception from an anonymous
    daemon thread.  The original exception is chained as ``__cause__``
    (and echoed in the message, so existing ``pytest.raises(...,
    match=...)`` callers keep matching); the run is guaranteed to have
    drained: all three stage threads observed the stop flag and exited
    before this raises."""

    def __init__(self, stage: str, piece: int, cause: BaseException):
        super().__init__(
            f"pipeline {stage} stage failed at piece {piece}: "
            f"{type(cause).__name__}: {cause}")
        self.stage = stage
        self.piece = piece


class PipelineStageFatalError(PipelineStageError, ValueError):
    """The DETERMINISTIC variant: raised when the stage's underlying
    cause sits in ``utils.retry.NON_RETRYABLE`` (shape/param validation,
    NaN fail-fast).  Subclassing ``ValueError`` keeps it non-retryable
    through every ``utils.retry`` wrapper — wrapping a deterministic
    model bug in a plain RuntimeError would silently re-classify it as
    transient and burn whole retry budgets reproducing it."""


def wrap_stage_error(stage: str, piece: int,
                     cause: BaseException) -> BaseException:
    """The consumer-side re-raise policy for a crashed stage: wrap into
    the structured :class:`PipelineStageError` family — EXCEPT the
    engine's typed fail-fast signal.  ``CircuitOpenError`` must reach
    callers unwrapped (its ``retry_after_s``/``last_error`` drive
    serving shed decisions, and wrapping it in a RuntimeError would turn
    the breaker's fail-fast back into retryable noise)."""
    # runtime-only import: engine imports this module at load time
    from sparkdl_tpu.parallel.engine import CircuitOpenError
    from sparkdl_tpu.utils.retry import NON_RETRYABLE

    if isinstance(cause, CircuitOpenError):
        return cause
    cls = (PipelineStageFatalError if isinstance(cause, NON_RETRYABLE)
           else PipelineStageError)
    return cls(stage, piece, cause)


def pipeline_enabled_from_env() -> bool:
    """``SPARKDL_PIPELINE`` (default ON) — the one parser every
    pipeline-aware call site shares.  ``0``/``false``/``off``/``no``
    disable the threaded stages and restore the serial path everywhere."""
    raw = os.environ.get("SPARKDL_PIPELINE", "").strip().lower()
    return raw not in ("0", "false", "off", "no")


class PipelinedRunner:
    """Runs an :class:`~sparkdl_tpu.parallel.engine.InferenceEngine` over
    an iterator of host batches with host prepare, H2D+dispatch, and
    D2H gather on three overlapping threads.

    ``window`` bounds dispatched-but-ungathered device batches (scaled to
    groups under ``batches_per_dispatch``, mirroring the serial path);
    ``depth`` bounds how far host prepare runs ahead of dispatch and how
    many gathered host outputs wait for the consumer.  Peak residency is
    therefore O(depth) prepared + O(window) device + O(depth) gathered
    batches regardless of input size.
    """

    def __init__(self, engine, window: int = 2, depth: int = 2,
                 metrics: Optional[Metrics] = None):
        self.engine = engine
        bpd = engine.batches_per_dispatch
        w = max(1, int(window))
        # same scaling as the serial path: with grouped dispatch the
        # in-flight unit is a k-batch GROUP, so the window counts groups
        self.window = max(1, w // bpd) if bpd > 1 else w
        self.depth = max(1, int(depth))
        self.metrics = metrics if metrics is not None else engine.metrics

    # -- internals ---------------------------------------------------------
    def _put(self, q: "queue.Queue", item, stop: threading.Event,
             stage: str, qname: str) -> bool:
        """Bounded put with backpressure accounting.  Gives up (False)
        when the run was cancelled — a consumer that abandoned the output
        iterator must not leak a producer blocked on a full queue."""
        t0 = time.perf_counter()
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
            except queue.Full:
                continue
            stall = time.perf_counter() - t0
            if stall > 1e-4:
                self.metrics.incr(f"pipeline.{stage}_out_stall_s", stall)
            self.metrics.observe(f"pipeline.{qname}_depth", q.qsize())
            return True
        return False

    def _get(self, q: "queue.Queue", stop: threading.Event, stage: str):
        """Bounded get with starvation accounting; ``_ABORT`` on cancel."""
        t0 = time.perf_counter()
        while not stop.is_set():
            try:
                item = q.get(timeout=0.05)
            except queue.Empty:
                continue
            stall = time.perf_counter() - t0
            if stall > 1e-4:
                self.metrics.incr(f"pipeline.{stage}_in_stall_s", stall)
            return item
        return _ABORT

    # -- the stage graph ---------------------------------------------------
    def run(self, batches: Iterable[Any]) -> Iterator[Any]:
        """Yield per-piece host outputs, bit-identical to (and in the same
        order as) the serial path."""
        eng = self.engine
        m = self.metrics
        stop = threading.Event()
        errors: list = []

        # Observability: one "pipeline.run" span brackets the whole
        # stage graph (parented to the consumer thread's current span,
        # e.g. engine.call); each stage emits one child span per piece.
        # Disabled tracing costs one enabled-check per piece — the
        # stage code paths are otherwise byte-identical.
        tracer = get_tracer()
        run_span = (tracer.start_span("pipeline.run",
                                      parent=tracer.current())
                    if tracer.enabled else None)

        prep_q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        disp_q: "queue.Queue" = queue.Queue(maxsize=self.window)
        out_q: "queue.Queue" = queue.Queue(maxsize=self.depth)

        def fail(stage: str, piece: int, e: BaseException) -> None:
            # first failure wins (later stage crashes are usually the
            # stop-flag cascade of the first); the consumer re-raises it
            # as a structured PipelineStageError naming stage + piece
            errors.append((stage, piece, e))
            stop.set()

        def prepare() -> None:
            # the engine's OWN piece iterator (the serial path consumes
            # the same one), so dispatch order is shared by construction
            idx = 0
            try:
                src = eng._iter_pieces(batches)
                while True:
                    inject("pipeline.prepare", piece=idx)
                    with tracer.span("pipeline.prepare", parent=run_span,
                                     piece=idx) as sp:
                        item = next(src, _DONE)
                        if item is _DONE:
                            sp.annotate(eos=True)
                    if item is _DONE:
                        self._put(prep_q, _DONE, stop, "prepare",
                                  "prep_q")
                        return
                    idx += 1
                    if not self._put(prep_q, item, stop, "prepare",
                                     "prep_q"):
                        return
            # graftlint: allow=SDL003 reason=recorded via fail() and re-raised consumer-side as PipelineStageError
            except BaseException as e:
                fail("prepare", idx, e)

        def dispatch() -> None:
            idx = -1
            try:
                while True:
                    item = self._get(prep_q, stop, "dispatch")
                    if item is _ABORT:
                        return
                    if item is _DONE:
                        break
                    idx += 1
                    kind, ns, host = item
                    inject("pipeline.dispatch", piece=idx)
                    # H2D + async launch: returns as soon as the transfer
                    # is enqueued; the device computes while we loop
                    with tracer.span("pipeline.dispatch",
                                     parent=run_span, kind=kind):
                        dev = (eng.run_padded(host) if kind == "plain"
                               else eng._dispatch_group(host))
                    m.incr("pipeline.dispatches")
                    if not self._put(disp_q, (kind, ns, dev), stop,
                                     "dispatch", "inflight_q"):
                        return
                self._put(disp_q, _DONE, stop, "dispatch", "inflight_q")
            # graftlint: allow=SDL003 reason=recorded via fail() and re-raised consumer-side as PipelineStageError
            except BaseException as e:
                fail("dispatch", idx, e)

        def gather() -> None:
            idx = -1
            try:
                while True:
                    item = self._get(disp_q, stop, "gather")
                    if item is _ABORT:
                        return
                    if item is _DONE:
                        break
                    idx += 1
                    kind, ns, dev = item
                    inject("pipeline.gather", piece=idx)
                    # span covers device wait + D2H + trim, NOT the
                    # downstream puts (backpressure is a separate story
                    # told by pipeline.gather_out_stall_s); when tracing
                    # is on, block_until_ready splits device wait
                    # (device_us) from the host-side copy/cast.  The
                    # force itself is the engine's OWN shared
                    # _force_parts (identical to the serial drain, and
                    # the point where force-time device errors charge
                    # the breaker/health accounting).
                    with tracer.span("pipeline.gather", parent=run_span,
                                     kind=kind) as sp:
                        parts = eng._force_parts(
                            ns, dev, block=sp.block_until_ready)
                    for part in parts:
                        if not self._put(out_q, part, stop, "gather",
                                         "out_q"):
                            return
                    m.incr("pipeline.gathers")
                self._put(out_q, _DONE, stop, "gather", "out_q")
            # graftlint: allow=SDL003 reason=recorded via fail() and re-raised consumer-side as PipelineStageError
            except BaseException as e:
                fail("gather", idx, e)

        threads = [
            threading.Thread(target=prepare, daemon=True,
                             name="sparkdl-pipeline-prepare"),
            threading.Thread(target=dispatch, daemon=True,
                             name="sparkdl-pipeline-dispatch"),
            threading.Thread(target=gather, daemon=True,
                             name="sparkdl-pipeline-gather"),
        ]
        for t in threads:
            t.start()
        try:
            while True:
                try:
                    item = out_q.get(timeout=0.05)
                except queue.Empty:
                    if stop.is_set():
                        break
                    continue
                if item is _DONE:
                    break
                yield item
        finally:
            # cancels every stage whether we finished, raised, or the
            # consumer closed the iterator early, then ALWAYS joins with
            # a bounded timeout: a crashed run must hand back a drained
            # stage graph (no thread blocked on a queue, nothing left to
            # wedge a later run), not just a stop flag — and when tracing
            # is on the join also closes stage spans BEFORE their parent
            # (the child-within-parent invariant tests rely on).  Threads
            # exit within one 50 ms queue-poll of stop; a thread still
            # alive after the timeout is a bug worth a loud log line.
            stop.set()
            for t in threads:
                t.join(timeout=2.0)
                if t.is_alive():
                    logger.warning("pipeline stage thread %s did not exit "
                                   "within 2s of cancellation", t.name)
            if run_span is not None:
                run_span.finish()
        if errors:
            stage, piece, cause = errors[0]
            self.metrics.incr(f"pipeline.{stage}_crashes")
            err = wrap_stage_error(stage, piece, cause)
            if err is cause:
                raise err  # typed pass-through (CircuitOpenError)
            raise err from cause


def pipeline_stage_summary(metrics: Metrics) -> Dict[str, float]:
    """Compact per-stage stall/occupancy snapshot for bench JSON lines:
    stall-second counters, dispatch/gather counts, and mean queue depths
    (a stage's ``_in_stall_s`` is time starved for input; ``_out_stall_s``
    is time blocked on downstream backpressure)."""
    out: Dict[str, float] = {}
    for k, v in metrics.subset("pipeline.").items():
        if k.endswith(("_in_stall_s", "_out_stall_s")) or k.endswith(
                ("dispatches", "gathers")) or k.endswith("_depth.mean"):
            out[k] = round(float(v), 4)
    return out


def synthetic_overlap_benchmark(n_batches: int = 6,
                                dispatch_ms: float = 100.0,
                                prepare_ms: float = 100.0,
                                rows: int = 8,
                                feature_dim: int = 4,
                                metrics: Optional[Metrics] = None
                                ) -> Dict[str, Any]:
    """Deterministic proof of host/device overlap on the CPU backend.

    Simulates the relayed-TPU regime PERF.md measures — a BLOCKING
    ~100 ms dispatch+fetch round trip that rivals the host-side decode
    cost — without needing the flaky relay: the engine's ``run_padded``
    is wrapped with a ``dispatch_ms`` sleep (the synthetic device) and
    producing each input batch sleeps ``prepare_ms`` (the synthetic JPEG
    decode).  The serial path pays ``n * (prepare + dispatch)``; the
    pipelined path overlaps them to ~``n * max(prepare, dispatch)`` — a
    2x ideal speedup at the default 100 ms/100 ms point, asserted at
    >= 1.5x by the tier-1 contract test.  Sleep-dominated, so the result
    is deterministic on any host; outputs are verified equal between the
    two paths before timings are reported.
    """
    from sparkdl_tpu.parallel.engine import InferenceEngine

    rng = np.random.default_rng(0)
    variables = {
        "w": rng.normal(size=(feature_dim, feature_dim)).astype(np.float32)}

    def fn(v, x):
        import jax.numpy as jnp

        return jnp.tanh(x @ v["w"])

    m = metrics if metrics is not None else Metrics()
    eng = InferenceEngine(fn, variables, device_batch_size=rows, metrics=m)
    real_run = eng.run_padded

    def slow_run(batch):  # the synthetic device: a blocking round trip
        time.sleep(dispatch_ms / 1e3)
        return real_run(batch)

    eng.run_padded = slow_run
    x = rng.normal(size=(eng.device_batch_size, feature_dim)
                   ).astype(np.float32)

    def batches():
        for _ in range(n_batches):
            time.sleep(prepare_ms / 1e3)  # the synthetic host decode
            yield x

    # warm the compile outside the timed region
    list(eng.map_batches([x], pipeline=False))

    t0 = time.perf_counter()
    serial = list(eng.map_batches(batches(), pipeline=False))
    serial_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    piped = list(eng.map_batches(batches(), pipeline=True))
    pipelined_s = time.perf_counter() - t0
    if len(serial) != len(piped) or not all(
            np.array_equal(a, b) for a, b in zip(serial, piped)):
        raise AssertionError(
            "pipelined outputs diverged from the serial path")
    return {
        "n_batches": n_batches,
        "dispatch_ms": dispatch_ms,
        "prepare_ms": prepare_ms,
        "serial_s": round(serial_s, 4),
        "pipelined_s": round(pipelined_s, 4),
        "speedup": round(serial_s / pipelined_s, 4),
        "stages": pipeline_stage_summary(m),
    }
