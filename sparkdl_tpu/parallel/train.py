"""Data-parallel training over the device mesh.

This is the north-star's NEW capability (SURVEY.md §2 parallelism table):
the reference's estimator ran each fit single-process (Keras on one
executor); here a fit is sharded over every chip — params replicated, batch
split on the ``data`` axis, and the gradient all-reduce expressed through
sharding: with replicated-out params and sharded-in batch, XLA's SPMD
partitioner inserts the ``psum`` over ICI that the reference ecosystem
needed Horovod/NCCL for.  ``jax.lax.with_sharding_constraint`` pins the
boundary; no hand-written collectives, no NCCL analog (SURVEY.md §2
"distributed communication backend").

Loss registry replaces ``SparkDLTypeConverters.toKerasLoss`` targets with
jax implementations keyed by the same canonical names.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import numpy as np

from sparkdl_tpu.parallel import mesh as mesh_lib
from sparkdl_tpu.utils.logging import get_logger
from sparkdl_tpu.utils.metrics import Metrics

logger = get_logger(__name__)

_EPS = 1e-7


# ---------------------------------------------------------------------------
# losses: fn(pred, y) -> per-example loss vector [B]

def _categorical_crossentropy(pred, y):
    import jax.numpy as jnp

    p = jnp.clip(pred, _EPS, 1.0 - _EPS)
    return -jnp.sum(y * jnp.log(p), axis=-1)


def _sparse_categorical_crossentropy(pred, y):
    import jax.numpy as jnp

    p = jnp.clip(pred, _EPS, 1.0 - _EPS)
    idx = y.astype(jnp.int32)
    return -jnp.log(jnp.take_along_axis(p, idx[:, None], axis=-1)[:, 0])


def _binary_crossentropy(pred, y):
    import jax.numpy as jnp

    p = jnp.clip(pred, _EPS, 1.0 - _EPS)
    p = p.reshape(p.shape[0], -1)
    yb = y.reshape(y.shape[0], -1).astype(p.dtype)
    return -jnp.mean(yb * jnp.log(p) + (1 - yb) * jnp.log(1 - p), axis=-1)


def _mse(pred, y):
    import jax.numpy as jnp

    d = (pred - y).reshape(pred.shape[0], -1)
    return jnp.mean(d * d, axis=-1)


def _mae(pred, y):
    import jax.numpy as jnp

    d = jnp.abs(pred - y).reshape(pred.shape[0], -1)
    return jnp.mean(d, axis=-1)


LOSSES: Dict[str, Callable] = {
    "categorical_crossentropy": _categorical_crossentropy,
    "sparse_categorical_crossentropy": _sparse_categorical_crossentropy,
    "binary_crossentropy": _binary_crossentropy,
    "mse": _mse,
    "mae": _mae,
}


def resolve_loss(loss) -> Callable:
    if callable(loss):
        return loss
    fn = LOSSES.get(str(loss))
    if fn is None:
        raise ValueError(f"Unknown loss {loss!r}; known: {sorted(LOSSES)}")
    return fn


# ---------------------------------------------------------------------------
# train step


class _MultiStepMixin:
    """Steps-per-execution support shared by both compiled-step flavors.

    ``multi()`` returns a jitted program running a whole STACK of batches
    (``xs``/``ys`` [k, B, ...], data-axis-sharded on dim 1) through the
    raw step under ``lax.scan`` — one dispatch + one loss fetch per k
    optimizer steps (Keras ``steps_per_execution``).  One jit object
    serves every k: jit's executable cache keys on the stacked shape.
    Subclasses provide ``raw_step``, ``mesh``, ``replicated``, and
    ``_state_shardings()`` (the sharding per state leg, in call order).
    """

    def multi(self, k: int) -> Callable:
        import jax

        del k  # shape-polymorphic: jit re-specializes per stack length
        if self.raw_step is None:
            raise ValueError(
                "multi() unavailable: step built without raw_step")
        if self._multi_fn is None:
            raw = self.raw_step
            n_state = len(self._state_shardings())

            def run(*args):
                state, xs, ys = args[:n_state], args[-2], args[-1]

                def body(carry, batch):
                    out = raw(*carry, batch[0], batch[1])
                    return tuple(out[:-1]), out[-1]

                carry, losses = jax.lax.scan(body, tuple(state), (xs, ys))
                return (*carry, losses)

            sh = self._state_shardings()
            stacked = self.stacked_batch_sharded
            self._multi_fn = jax.jit(
                run,
                in_shardings=(*sh, stacked, stacked),
                out_shardings=(*sh, self.replicated),
                donate_argnums=tuple(range(n_state)))
        return self._multi_fn

    @property
    def stacked_batch_sharded(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P(None, mesh_lib.DATA_AXIS))

    def put_batch_stack(self, xs, ys):
        """Place [k, B, ...] stacked batches under the stacked data-axis
        sharding (multi-controller: local rows per host, as put_batch)."""
        from sparkdl_tpu.parallel.distributed import put_sharded

        sh = self.stacked_batch_sharded
        return put_sharded(sh, xs), put_sharded(sh, ys)


@dataclass
class TrainStep(_MultiStepMixin):
    """A compiled data-parallel step: (params, opt_state, x, y) ->
    (params, opt_state, loss).  Params/opt_state stay device-resident
    across steps (replicated, or tensor-parallel-sharded on the mesh's
    ``model`` axis when built with ``param_specs``); x/y are sharded on
    the data axis."""

    step_fn: Callable
    mesh: Any
    replicated: Any
    batch_sharded: Any
    param_shardings: Any = None  # pytree of NamedSharding when TP is on
    opt_shardings: Any = None    # derived from param_shardings (TP only)
    raw_step: Any = None         # untraced python step, for multi()
    _multi_fn: Any = None        # lazily built jitted multi-step scan

    def _state_shardings(self):
        p_sh = (self.param_shardings if self.param_shardings is not None
                else self.replicated)
        o_sh = (self.opt_shardings if self.opt_shardings is not None
                else self.replicated)
        return (p_sh, o_sh)

    def put_state(self, params, opt_state):
        import jax

        if self.param_shardings is not None:
            params = jax.tree_util.tree_map(
                jax.device_put, params, self.param_shardings)
            # mu/nu/trace are placed under the SAME layouts the step was
            # compiled for, so step 1 already matches the executable.
            opt_state = jax.tree_util.tree_map(
                jax.device_put, opt_state, self.opt_shardings)
            return params, opt_state
        return (jax.device_put(params, self.replicated),
                jax.device_put(opt_state, self.replicated))

    def put_batch(self, x, y):
        """Place a batch under the data-axis sharding.  Multi-controller:
        x/y are this host's LOCAL rows and the global array is assembled
        across processes (see parallel.distributed.put_sharded)."""
        from sparkdl_tpu.parallel.distributed import put_sharded

        return (put_sharded(self.batch_sharded, x),
                put_sharded(self.batch_sharded, y))

    def __call__(self, params, opt_state, x, y):
        return self.step_fn(params, opt_state, x, y)


# TrainStep cache: one compiled step per (model fn, loss, optimizer, mesh).
# This is what makes a param grid x k folds compile ONCE (SURVEY.md §7 hard
# part #5): fitMultiple / CrossValidator re-enter make_train_step with the
# same constituents and get back the same jit object, whose own executable
# cache then hits on equal batch shapes.  Keys use object ids — safe because
# the cached TrainStep's closure keeps every keyed object alive, so ids
# cannot be recycled while the entry exists.  BoundedCache locks put/evict:
# fitMultiple's parallel fan-out reaches this from worker threads.
from sparkdl_tpu.utils.cache import BoundedCache

_STEP_CACHE = BoundedCache(cap=16)


def clear_train_step_cache() -> None:
    _STEP_CACHE.clear()
    _OPT_INSTANCES.clear()


def _mesh_key(mesh) -> tuple:
    return (tuple(d.id for d in mesh.devices.flat), tuple(mesh.axis_names),
            tuple(mesh.devices.shape))


def resolve_param_specs(param_specs, params, mesh):
    """``param_specs`` -> a pytree of NamedSharding matching ``params``.

    Accepts a pytree of ``PartitionSpec`` (same structure as params) or a
    callable ``(path_str, leaf) -> PartitionSpec`` applied per leaf — the
    rule form used for tensor-parallel layouts (e.g. shard only the
    classifier head's kernel on the ``model`` axis)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    if callable(param_specs):
        # the ONE leaf-name spelling (mesh.param_path_str), shared with
        # the inference engine's partition rules and the program
        # auditor — a rule written against one surface matches the
        # same names everywhere (sequence-indexed pytrees included)
        def rule(path, leaf):
            return NamedSharding(
                mesh, param_specs(mesh_lib.param_path_str(path), leaf))

        flat = jax.tree_util.tree_flatten_with_path(params)
        return jax.tree_util.tree_unflatten(
            flat[1], [rule(p, l) for p, l in flat[0]])
    # PartitionSpec subclasses tuple — stop tree traversal at specs
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), param_specs,
        is_leaf=lambda s: isinstance(s, PartitionSpec))


def _path_key(k) -> str:
    for attr in ("key", "idx", "name"):
        v = getattr(k, attr, None)
        if v is not None:
            return str(v)
    return str(k)


def resolve_opt_state_shardings(optimizer, params_template, param_shardings,
                                replicated):
    """Derive a sharding pytree for ``optimizer.init(params)`` from the
    param shardings (ADVICE r3: without this, TP steps left opt_state
    layout to the partitioner, which could re-layout mu/nu after step 1
    and force a second compilation with mismatched donated buffers).

    optax states mirror the param tree under attributes like ``mu`` /
    ``nu`` / ``trace``: a state leaf whose path SUFFIX matches a param's
    path (and whose shape matches) inherits that param's sharding;
    everything else (step counts, scalars) stays replicated."""
    import jax

    param_entries = [
        (tuple(_path_key(k) for k in path), tuple(leaf.shape), sh)
        for (path, leaf), (_, sh) in zip(
            jax.tree_util.tree_flatten_with_path(params_template)[0],
            jax.tree_util.tree_flatten_with_path(param_shardings)[0])
    ]
    # Longest paths first: a short param path (e.g. ('bias',)) must not
    # shadow a deeper one (('head','bias')) that matches more of the
    # state leaf's path.
    param_entries.sort(key=lambda e: len(e[0]), reverse=True)
    opt_shape = jax.eval_shape(optimizer.init, params_template)
    flat, treedef = jax.tree_util.tree_flatten_with_path(opt_shape)
    out = []
    for path, leaf in flat:
        keys = tuple(_path_key(k) for k in path)
        sh = replicated
        for ppath, pshape, psh in param_entries:
            if (len(keys) >= len(ppath) and keys[-len(ppath):] == ppath
                    and tuple(leaf.shape) == pshape):
                sh = psh
                break
        out.append(sh)
    return jax.tree_util.tree_unflatten(treedef, out)


def make_train_step(predict_fn: Callable, loss, optimizer,
                    mesh=None, cache: bool = True,
                    param_specs=None, params_template=None) -> TrainStep:
    """Build (or fetch the cached) jit-compiled data-parallel train step.

    ``predict_fn(params, x) -> pred``; ``loss(pred, y) -> [B]``;
    ``optimizer`` is an optax GradientTransformation.  The mean over the
    global batch is what makes XLA emit the cross-chip gradient psum.

    ``param_specs`` (with ``params_template``) enables TENSOR PARALLELISM:
    a pytree of ``PartitionSpec`` (or a ``(path, leaf) -> PartitionSpec``
    rule) sharding chosen parameters over the mesh's ``model`` axis —
    XLA's SPMD partitioner then inserts the activation/gradient
    collectives the layout implies.  The zoo's CNNs don't need TP
    (SURVEY.md §2); the path exists for oversized heads/embeddings and is
    exercised by the driver's multi-chip dryrun.  TP steps are not
    cached (their key would depend on the spec tree)."""
    import jax
    import jax.numpy as jnp

    mesh = mesh if mesh is not None else mesh_lib.get_mesh()
    if param_specs is not None:
        cache = False
    key = (id(predict_fn),
           loss if isinstance(loss, str) else id(loss),
           id(optimizer), _mesh_key(mesh))
    if cache:
        cached = _STEP_CACHE.get(key)
        if cached is not None:
            return cached
    replicated = mesh_lib.replicated_sharding(mesh)
    batch_sharded = mesh_lib.batch_sharding(mesh)
    loss_fn = resolve_loss(loss)

    def scalar_loss(params, x, y):
        pred = predict_fn(params, x)
        return jnp.mean(loss_fn(pred, y))

    def step(params, opt_state, x, y):
        lval, grads = jax.value_and_grad(scalar_loss)(params, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        return params, opt_state, lval

    param_shardings = None
    if param_specs is not None:
        if params_template is None:
            raise ValueError(
                "param_specs requires params_template (the params pytree "
                "the spec rule/tree is resolved against)")
        param_shardings = resolve_param_specs(param_specs, params_template,
                                              mesh)
        # Shardings committed on the inputs drive the partitioner; the
        # loss stays replicated.  opt_state shardings are PINNED to mirror
        # the param layouts (mu/nu/trace follow their param; counts stay
        # replicated) so every step shares one executable and donation
        # always sees the layout it compiled for.
        opt_shardings = resolve_opt_state_shardings(
            optimizer, params_template, param_shardings, replicated)
        step_fn = jax.jit(
            step,
            in_shardings=(param_shardings, opt_shardings, batch_sharded,
                          batch_sharded),
            out_shardings=(param_shardings, opt_shardings, replicated),
            donate_argnums=(0, 1))
    else:
        step_fn = jax.jit(
            step,
            in_shardings=(replicated, replicated, batch_sharded,
                          batch_sharded),
            out_shardings=(replicated, replicated, replicated),
            donate_argnums=(0, 1))
    result = TrainStep(step_fn=step_fn, mesh=mesh, replicated=replicated,
                       batch_sharded=batch_sharded,
                       param_shardings=param_shardings,
                       opt_shardings=(opt_shardings
                                      if param_specs is not None else None),
                       raw_step=step)
    if cache:
        _STEP_CACHE.put(key, result)
    return result


@dataclass
class TrainStepWithStats(_MultiStepMixin):
    """Compiled data-parallel step that ALSO updates BatchNorm statistics:
    (params, stats, opt_state, x, y) -> (params, stats, opt_state, loss).

    Under the sharded jit the batch-mean/variance reductions have GLOBAL
    semantics — XLA's SPMD partitioner inserts the cross-chip psum — so the
    updated stats match a single-device run over the whole global batch
    (the Keras ``fit`` behavior the reference estimator had, C15)."""

    step_fn: Callable
    mesh: Any
    replicated: Any
    batch_sharded: Any
    raw_step: Any = None
    _multi_fn: Any = None

    def _state_shardings(self):
        return (self.replicated,) * 3  # params, stats, opt_state

    def put_state(self, params, stats, opt_state):
        import jax

        return (jax.device_put(params, self.replicated),
                jax.device_put(stats, self.replicated),
                jax.device_put(opt_state, self.replicated))

    def put_batch(self, x, y):
        from sparkdl_tpu.parallel.distributed import put_sharded

        return (put_sharded(self.batch_sharded, x),
                put_sharded(self.batch_sharded, y))

    def __call__(self, params, stats, opt_state, x, y):
        return self.step_fn(params, stats, opt_state, x, y)


def make_train_step_with_stats(train_fn: Callable, loss, optimizer,
                               mesh=None, cache: bool = True
                               ) -> TrainStepWithStats:
    """Like :func:`make_train_step` but for models whose
    ``train_fn({"params":..., "batch_stats":...}, x) -> (pred, new_stats)``
    updates BatchNorm statistics (ModelFunction.train_fn)."""
    import jax
    import jax.numpy as jnp
    import optax

    mesh = mesh if mesh is not None else mesh_lib.get_mesh()
    key = ("stats", id(train_fn),
           loss if isinstance(loss, str) else id(loss),
           id(optimizer), _mesh_key(mesh))
    if cache:
        cached = _STEP_CACHE.get(key)
        if cached is not None:
            return cached
    replicated = mesh_lib.replicated_sharding(mesh)
    batch_sharded = mesh_lib.batch_sharding(mesh)
    loss_fn = resolve_loss(loss)

    def scalar_loss(params, stats, x, y):
        pred, new_stats = train_fn(
            {"params": params, "batch_stats": stats}, x)
        return jnp.mean(loss_fn(pred, y)), new_stats

    def step(params, stats, opt_state, x, y):
        (lval, new_stats), grads = jax.value_and_grad(
            scalar_loss, has_aux=True)(params, stats, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, lval

    step_fn = jax.jit(
        step,
        in_shardings=(replicated, replicated, replicated,
                      batch_sharded, batch_sharded),
        out_shardings=(replicated, replicated, replicated, replicated),
        donate_argnums=(0, 1, 2))
    result = TrainStepWithStats(step_fn=step_fn, mesh=mesh,
                                replicated=replicated,
                                batch_sharded=batch_sharded,
                                raw_step=step)
    if cache:
        _STEP_CACHE.put(key, result)
    return result


_OPT_INSTANCES: Dict[int, Any] = {}
_DEFAULT_OPTIMIZER = None


def _resolve_optimizer(optimizer):
    """Resolve None/factory forms to a STABLE GradientTransformation so the
    step cache can key on identity (a fresh adam per fit would defeat it)."""
    import optax

    global _DEFAULT_OPTIMIZER
    if optimizer is None:
        if _DEFAULT_OPTIMIZER is None:
            _DEFAULT_OPTIMIZER = optax.adam(1e-3)
        return _DEFAULT_OPTIMIZER
    if callable(optimizer) and not isinstance(
            optimizer, optax.GradientTransformation):
        # factory form from the param converter: one instance per factory
        inst = _OPT_INSTANCES.get(id(optimizer))
        if inst is None:
            inst = (optimizer, optimizer())  # pin factory so its id is stable
            _OPT_INSTANCES[id(optimizer)] = inst
        return inst[1]
    return optimizer


def _epoch_batches(x: np.ndarray, y: np.ndarray, batch_size: int,
                   epoch: int, shuffle: bool, seed: int,
                   num_steps: Optional[int] = None):
    """One epoch of fixed-shape batches: the last ragged batch is wrapped
    with leading samples so every device batch has the full shape (no
    recompiles, no masking — standard for small transfer-learning sets).
    Per-epoch seeding keeps shuffling deterministic under checkpoint
    resume.

    ``num_steps`` pins the number of batches yielded regardless of the
    local row count (wrapping modularly) — multi-controller fits use it so
    every host executes the same number of collective steps even when the
    per-host shards are unequal."""
    n = x.shape[0]
    rng = np.random.default_rng(seed + epoch)
    order = rng.permutation(n) if shuffle else np.arange(n)
    steps = -(-n // batch_size) if num_steps is None else int(num_steps)
    for s in range(steps):
        off = s * batch_size
        idx = order[off:off + batch_size]
        if len(idx) < batch_size:
            # Modular wrap keeps the batch exactly batch_size even when the
            # dataset is smaller than the shortfall (n < batch_size - len).
            idx = np.take(order, np.arange(off, off + batch_size) % n)
        yield x[idx], y[idx]


def _stream_epoch_batches(chunks: Iterable, batch_size: int,
                          num_steps: Optional[int] = None):
    """Fixed-shape batches from a stream of (x_chunk, y_chunk) pairs.

    The larger-than-RAM analog of :func:`_epoch_batches`: buffers at most
    O(chunk + batch) rows.  The ragged tail is padded by wrapping rows
    retained from the FIRST batch (same wrap-to-full-shape semantics,
    without holding the epoch in memory).  With ``num_steps`` the stream
    is truncated or extended (reservoir-wrapped batches) to EXACTLY that
    many steps — the multi-controller agreement rule.
    """
    buf_x: list = []
    buf_y: list = []
    buffered = 0
    head: Optional[Tuple[np.ndarray, np.ndarray]] = None
    emitted = 0

    def drain_batches():
        nonlocal buffered, head, emitted
        while buffered >= batch_size:
            x = np.concatenate([np.asarray(c) for c in buf_x], axis=0)
            y = np.concatenate([np.asarray(c) for c in buf_y], axis=0)
            buf_x.clear()
            buf_y.clear()
            bx, by = x[:batch_size], y[:batch_size]
            rest_x, rest_y = x[batch_size:], y[batch_size:]
            if len(rest_x):
                buf_x.append(rest_x)
                buf_y.append(rest_y)
            buffered = len(rest_x)
            if head is None:
                head = (bx.copy(), by.copy())
            emitted += 1
            yield bx, by

    for cx, cy in chunks:
        cx, cy = np.asarray(cx), np.asarray(cy)
        if cx.shape[0] == 0:
            continue
        buf_x.append(cx)
        buf_y.append(cy)
        buffered += cx.shape[0]
        for b in drain_batches():
            yield b
            if num_steps is not None and emitted >= num_steps:
                return
    # ragged tail: wrap with reservoir rows to keep the full batch shape
    if buffered and (num_steps is None or emitted < num_steps):
        x = np.concatenate([np.asarray(c) for c in buf_x], axis=0)
        y = np.concatenate([np.asarray(c) for c in buf_y], axis=0)
        if head is None:
            head = (x, y)  # stream smaller than one batch
        pad = batch_size - x.shape[0]
        while pad > 0:
            take = min(pad, head[0].shape[0])
            x = np.concatenate([x, head[0][:take]], axis=0)
            y = np.concatenate([y, head[1][:take]], axis=0)
            pad -= take
        emitted += 1
        yield x, y
    # short stream under a pinned step count: wrap whole reservoir batches
    while num_steps is not None and emitted < num_steps and head is not None:
        emitted += 1
        yield head


def _run_grouped_steps(step, with_stats: bool, spe: int, batches,
                       params, stats, opt_state):
    """Drive one epoch's batches through the compiled step, packing groups
    of ``spe`` consecutive steps into one dispatch (``TrainStep.multi``).
    Returns (params, stats, opt_state, step_losses) with ``step_losses``
    the fetched per-step float series (one D2H drain per flush group).
    Size-1 groups (ragged tails, spe=1) reuse the already-compiled 1-step
    program.  Batches that are VIEWS into a larger buffer (the streaming
    batcher slices its chunk concatenation) are copied before being held
    in a group — otherwise ``spe`` pinned views retain O(spe x chunk)
    host memory on exactly the larger-than-RAM datasets the stream path
    exists for."""
    losses = []

    def flush(group):
        nonlocal params, stats, opt_state
        if len(group) == 1:
            bx_d, by_d = step.put_batch(*group[0])
            if with_stats:
                params, stats, opt_state, lval = step(
                    params, stats, opt_state, bx_d, by_d)
            else:
                params, opt_state, lval = step(params, opt_state,
                                               bx_d, by_d)
            losses.append(lval)
            return
        xs = np.stack([g[0] for g in group])
        ys = np.stack([g[1] for g in group])
        xs_d, ys_d = step.put_batch_stack(xs, ys)
        if with_stats:
            params, stats, opt_state, ls = step.multi(len(group))(
                params, stats, opt_state, xs_d, ys_d)
        else:
            params, opt_state, ls = step.multi(len(group))(
                params, opt_state, xs_d, ys_d)
        losses.append(ls)

    def own(a):
        return a.copy() if (spe > 1 and a.base is not None) else a

    group = []
    for bx, by in batches:
        group.append((own(bx), own(by)))
        if len(group) == spe:
            flush(group)
            group = []
    if group:
        flush(group)
    step_losses = [] if not losses else list(np.concatenate(
        [np.asarray(l, np.float32).reshape(-1) for l in losses]))
    return params, stats, opt_state, step_losses


def fit_data_parallel_stream(predict_fn: Callable, params,
                             epoch_source: Callable[[], Iterable], *,
                             optimizer=None,
                             loss="categorical_crossentropy",
                             batch_size: int = 32,
                             epochs: int = 1,
                             steps_per_epoch: Optional[int] = None,
                             mesh=None,
                             checkpoint_dir: Optional[str] = None,
                             checkpoint_every_epochs: int = 1,
                             metrics: Optional[Metrics] = None,
                             train_fn: Optional[Callable] = None,
                             stats: Optional[Any] = None,
                             steps_per_execution: int = 1
                             ) -> Tuple[Any, list]:
    """Like :func:`fit_data_parallel` but over a RE-ITERABLE chunk source:
    ``epoch_source() -> iterator of (x_chunk, y_chunk)`` host arrays, called
    once per epoch.  Peak host memory is O(chunk + batch) — datasets larger
    than host RAM stream from disk every epoch (SURVEY.md §7 step 1, the
    grain-style reader the reference's collect-to-driver estimator lacked).

    Multi-controller runs REQUIRE ``steps_per_epoch`` (a stream cannot be
    counted in agreement across hosts without a full pass); single-process
    runs derive the step count from the stream itself.

    ``steps_per_execution``: as in :func:`fit_data_parallel` — k steps
    per compiled dispatch; host residency grows to O(chunk + k x batch).
    """
    import jax

    optimizer = _resolve_optimizer(optimizer)
    mesh = mesh if mesh is not None else mesh_lib.get_mesh()
    dp = mesh.shape[mesh_lib.DATA_AXIS]
    if batch_size % dp:
        batch_size += dp - batch_size % dp
        logger.info("global batch rounded up to %d (multiple of %d-way "
                    "data axis)", batch_size, dp)
    pc = jax.process_count()
    if pc > 1:
        if steps_per_epoch is None:
            raise ValueError(
                "multi-controller streaming fit requires steps_per_epoch "
                "(hosts cannot count an unseen stream in agreement); derive "
                "it from the global row count / global batch")
        batch_size = max(dp // pc, batch_size // pc)

    with_stats = train_fn is not None
    if with_stats:
        step = make_train_step_with_stats(train_fn, loss, optimizer,
                                          mesh=mesh)
        stats = stats if stats is not None else {}
    else:
        step = make_train_step(predict_fn, loss, optimizer, mesh=mesh)
    opt_state = optimizer.init(params)

    def _ckpt_state(p, s, o):
        state = {"params": p, "opt_state": o}
        if with_stats:
            state["batch_stats"] = s
        return state

    start_epoch = 0
    ckptr = None
    if checkpoint_dir:
        from sparkdl_tpu.checkpoint import TrainCheckpointer

        ckptr = TrainCheckpointer(checkpoint_dir, checkpoint_every_epochs)
        resumed = ckptr.restore_latest(
            template=_ckpt_state(params, stats, opt_state))
        if resumed is not None:
            start_epoch, state = resumed
            params, opt_state = state["params"], state["opt_state"]
            if with_stats:
                stats = state["batch_stats"]

    if with_stats:
        params, stats, opt_state = step.put_state(params, stats, opt_state)
    else:
        params, opt_state = step.put_state(params, opt_state)

    def _epoch_chunks():
        """The epoch's chunk iterator; multi-controller runs first verify
        EVERY host has rows this epoch (tiny allgather) so an empty shard
        raises consistently on all hosts instead of deadlocking the psum
        (the streaming analog of fit_data_parallel's zero-row guard)."""
        it = iter(epoch_source())
        first = next(it, None)
        while first is not None and np.asarray(first[0]).shape[0] == 0:
            first = next(it, None)  # skip empty leading chunks
        if pc > 1:
            from jax.experimental import multihost_utils

            n_first = 0 if first is None else int(np.asarray(first[0]).shape[0])
            counts = multihost_utils.process_allgather(
                np.asarray(n_first, np.int64))
            if int(np.min(counts)) == 0:
                raise ValueError(
                    f"multi-controller streaming fit requires >=1 row on "
                    f"every host at the start of each epoch; first-chunk "
                    f"rows per host: {counts.tolist()}")
        elif first is None:
            raise ValueError("epoch_source yielded no rows")

        def prefixed(f):
            # NOT itertools.chain: chain pins its argument tuple (and so
            # the first chunk) for the whole epoch — O(chunk) residency
            # demands the peeked chunk die right after consumption.
            yield f
            del f
            yield from it

        return prefixed(first)

    metrics = metrics if metrics is not None else Metrics()
    spe = max(1, int(steps_per_execution))
    epoch_losses = []
    for epoch in range(start_epoch, epochs):
        params, stats, opt_state, step_losses = _run_grouped_steps(
            step, with_stats, spe,
            _stream_epoch_batches(_epoch_chunks(), batch_size,
                                  num_steps=steps_per_epoch),
            params, stats, opt_state)
        if not step_losses:
            raise ValueError("epoch_source yielded no rows")
        mean = float(np.mean(step_losses))
        if not np.isfinite(mean):
            from sparkdl_tpu.utils import debug as _debug

            _debug.warn_or_raise_nonfinite_loss(step_losses, epoch)
        epoch_losses.append(mean)
        metrics.record_time("epoch_loss", mean)
        if ckptr is not None and ckptr.due(epoch + 1) and ckptr.is_writer():
            host_state = jax.tree_util.tree_map(
                np.asarray, _ckpt_state(params, stats, opt_state))
            ckptr.maybe_save(epoch + 1, host_state)
    if with_stats:
        return (jax.tree_util.tree_map(
            np.asarray, {"params": params, "batch_stats": stats}),
            epoch_losses)
    return jax.tree_util.tree_map(np.asarray, params), epoch_losses


def fit_data_parallel(predict_fn: Callable, params, x: np.ndarray,
                      y: np.ndarray, *,
                      optimizer=None,
                      loss="categorical_crossentropy",
                      batch_size: int = 32,
                      epochs: int = 1,
                      shuffle: bool = True,
                      seed: int = 0,
                      mesh=None,
                      checkpoint_dir: Optional[str] = None,
                      checkpoint_every_epochs: int = 1,
                      metrics: Optional[Metrics] = None,
                      train_fn: Optional[Callable] = None,
                      stats: Optional[Any] = None,
                      steps_per_execution: int = 1) -> Tuple[Any, list]:
    """Fit ``params`` on (x, y) with batch-sharded steps over the mesh.

    Returns (fitted params on host, per-epoch mean losses).  The analog of
    the reference estimator's executor-side ``model.fit`` hot loop
    (``keras_image_file_estimator.py``), distributed instead of single-node.

    With ``train_fn`` + ``stats`` (BatchNorm statistics pytree), the step
    also updates batch stats with global-batch semantics (estimator
    ``trainBatchStats=True``) and the fitted value returned is
    ``{"params": ..., "batch_stats": ...}``.

    With ``checkpoint_dir``, params+optimizer state are orbax-checkpointed
    every ``checkpoint_every_epochs`` epochs and an interrupted fit resumes
    from the newest checkpoint (SURVEY.md §5 — the capability the reference
    delegated to Spark task retry).

    ``steps_per_execution > 1`` packs that many optimizer steps into ONE
    compiled program per dispatch (``lax.scan`` over stacked batches —
    Keras's ``steps_per_execution``): identical math and loss series, one
    launch + one loss fetch per group.  Ragged epoch tails run as one
    smaller group (compiled once; tail size is constant across epochs).
    Host memory per dispatch grows by the factor; checkpoint cadence is
    unchanged (epoch-granular).
    """
    import jax

    optimizer = _resolve_optimizer(optimizer)
    mesh = mesh if mesh is not None else mesh_lib.get_mesh()
    dp = mesh.shape[mesh_lib.DATA_AXIS]
    if batch_size % dp:
        batch_size += dp - batch_size % dp
        logger.info("global batch rounded up to %d (multiple of %d-way "
                    "data axis)", batch_size, dp)
    pc = jax.process_count()
    steps_per_epoch = None
    if pc > 1:
        # Multi-controller GLOBAL-BATCH SPEC: (x, y) are THIS host's shard
        # (see distributed.shard_files).
        #   * The user's ``batch_size`` is the GLOBAL batch — rows per
        #     optimizer step across all hosts — already rounded up to a
        #     multiple of the data-axis size ``dp`` above.
        #   * Each host contributes ``local_batch = global/pc`` rows per
        #     step (every host has dp/pc local devices, so this stays
        #     device-aligned), floored at one row per local device.
        #   * Steps per epoch derive from the GLOBAL row count (allgather of
        #     local counts) so every host executes the SAME number of
        #     collective steps; hosts with short shards wrap modularly —
        #     without this, unequal shards (guaranteed when rows % pc != 0)
        #     run different step counts and the psum deadlocks.
        from jax.experimental import multihost_utils

        local_batch = max(dp // pc, batch_size // pc)
        counts = multihost_utils.process_allgather(
            np.asarray(x.shape[0], np.int64))
        if int(np.min(counts)) == 0:
            # A zero-row host cannot contribute its local_batch share to
            # make_array_from_process_local_data; every host sees the same
            # counts, so this raises consistently instead of hanging.
            raise ValueError(
                f"multi-controller fit requires >=1 row on every host; "
                f"per-host row counts: {counts.tolist()} (fewer files than "
                f"processes? see distributed.shard_files)")
        global_rows = int(np.sum(counts))
        steps_per_epoch = max(1, -(-global_rows // (local_batch * pc)))
        batch_size = local_batch
    else:
        batch_size = min(batch_size, max(dp, (x.shape[0] // dp) * dp))

    with_stats = train_fn is not None
    if with_stats:
        step = make_train_step_with_stats(train_fn, loss, optimizer,
                                          mesh=mesh)
        stats = stats if stats is not None else {}
    else:
        step = make_train_step(predict_fn, loss, optimizer, mesh=mesh)
    opt_state = optimizer.init(params)

    def _ckpt_state(p, s, o):
        state = {"params": p, "opt_state": o}
        if with_stats:
            state["batch_stats"] = s
        return state

    start_epoch = 0
    ckptr = None
    if checkpoint_dir:
        from sparkdl_tpu.checkpoint import TrainCheckpointer

        ckptr = TrainCheckpointer(checkpoint_dir, checkpoint_every_epochs)
        resumed = ckptr.restore_latest(
            template=_ckpt_state(params, stats, opt_state))
        if resumed is not None:
            start_epoch, state = resumed
            params, opt_state = state["params"], state["opt_state"]
            if with_stats:
                stats = state["batch_stats"]

    if with_stats:
        params, stats, opt_state = step.put_state(params, stats, opt_state)
    else:
        params, opt_state = step.put_state(params, opt_state)

    metrics = metrics if metrics is not None else Metrics()
    spe = max(1, int(steps_per_execution))
    epoch_losses = []
    for epoch in range(start_epoch, epochs):
        params, stats, opt_state, step_losses = _run_grouped_steps(
            step, with_stats, spe,
            _epoch_batches(x, y, batch_size, epoch, shuffle, seed,
                           num_steps=steps_per_epoch),
            params, stats, opt_state)
        if not step_losses:
            raise ValueError(
                "fit produced no batches (zero-row dataset?)")
        mean = float(np.mean(step_losses))
        if not np.isfinite(mean):
            from sparkdl_tpu.utils import debug as _debug

            _debug.warn_or_raise_nonfinite_loss(step_losses, epoch)
        epoch_losses.append(mean)
        metrics.record_time("epoch_loss", mean)
        if ckptr is not None and ckptr.due(epoch + 1) and ckptr.is_writer():
            # Gather to host only on epochs the cadence actually saves —
            # the device->host transfer of the full state is not free.
            # Gathering does not invalidate the device arrays; the next
            # step keeps using them (and donates them as usual).
            host_state = jax.tree_util.tree_map(
                np.asarray, _ckpt_state(params, stats, opt_state))
            ckptr.maybe_save(epoch + 1, host_state)
    if with_stats:
        return (jax.tree_util.tree_map(
            np.asarray, {"params": params, "batch_stats": stats}),
            epoch_losses)
    params = jax.tree_util.tree_map(np.asarray, params)
    return params, epoch_losses
