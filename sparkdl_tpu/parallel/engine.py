"""Batched inference engine: the TPU replacement for the reference's hot loop.

Reference hot loop (SURVEY.md §3.1/§3.2): per-partition TensorFrames
``Session::Run`` on each executor, model GraphDef torrent-broadcast to JVMs.
Here instead: ONE jit-compiled XLA program per (model, batch-shape,
sharding policy), params resident on device (replicated via NamedSharding
— the broadcast analog — or tensor-parallel-sharded across the mesh's
``model`` axis via partition rules, ISSUE 14), batch rows sharded over
the mesh's data axis, and a fixed padded batch shape so XLA never
recompiles (SURVEY.md §7 hard part #4).

Throughput design:
  * fixed ``device_batch_size`` (rounded up to a multiple of the data-axis
    size) — one compile, reused forever;
  * the tail batch is zero-padded and trimmed on the host after gather, so
    ragged input never poisons shapes;
  * dispatch is async with a bounded in-flight window (double buffering):
    the next batch's host->device transfer overlaps the current batch's
    compute, while device residency stays O(window x batch) regardless of
    input size (both ``map_batches`` and ``__call__``);
  * host stages overlap the device by default: prepare (decode/pack/pad),
    H2D+dispatch, and D2H gather run on worker threads with backpressure
    queues (``parallel.pipeline.PipelinedRunner``; ``SPARKDL_PIPELINE=0``
    restores the serial path) — batch k+1 decodes while batch k computes
    and batch k-1 gathers, bit-identically to the serial path.
"""

from __future__ import annotations

import time as time_lib
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

import numpy as np

from sparkdl_tpu.analysis.lockcheck import named_lock
from sparkdl_tpu.faults import inject
from sparkdl_tpu.obs.flight import emit as flight_emit
from sparkdl_tpu.obs.trace import get_tracer
from sparkdl_tpu.parallel import mesh as mesh_lib
from sparkdl_tpu.parallel.pipeline import (PipelinedRunner,
                                           pipeline_enabled_from_env)
from sparkdl_tpu.utils.logging import get_logger
from sparkdl_tpu.utils.metrics import Metrics
from sparkdl_tpu.utils.retry import NON_RETRYABLE, with_retries

logger = get_logger(__name__)


class CircuitOpenError(RuntimeError):
    """The engine's dispatch circuit breaker is OPEN: ``breaker_threshold``
    consecutive device errors tripped it, and dispatches now fail fast
    (with the last device error's text) instead of each paying a full
    retry-with-backoff budget against a dead device.  ``retry_after_s``
    is the remaining cool-down before a half-open trial dispatch is
    allowed."""

    def __init__(self, message: str, retry_after_s: float = 0.0,
                 last_error: Optional[str] = None):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.last_error = last_error


class DispatchCircuitBreaker:
    """Consecutive-failure circuit breaker for device dispatch.

    closed --(threshold consecutive failures)--> open
    open   --(cooldown elapses)-->                half_open (ONE trial)
    half_open --success--> closed; --failure--> open (fresh cooldown)

    Deterministic errors (``utils.retry.NON_RETRYABLE`` — shape/param
    validation, NaN fail-fast) never count: they indicate a caller bug,
    not a dying device, and must keep failing loudly per call.
    ``threshold <= 0`` disables the breaker entirely (gate/record are
    no-ops without taking the lock — the default-path budget).
    """

    def __init__(self, threshold: int = 8, cooldown_s: float = 30.0):
        self.threshold = int(threshold)
        self.cooldown_s = max(0.0, float(cooldown_s))
        self._lock = named_lock("engine.breaker")
        self._consecutive = 0
        self._open_until = 0.0
        self._open = False
        self._trial_inflight = False
        self._last_error: Optional[str] = None
        self._opened_count = 0

    @property
    def enabled(self) -> bool:
        return self.threshold > 0

    def gate(self) -> None:
        """Fail fast with :class:`CircuitOpenError` while open; admit a
        single trial dispatch once the cool-down elapses (half-open —
        recorded as a ``breaker.half_open`` flight event, outside the
        lock)."""
        if self.threshold <= 0:
            return
        trial = False
        with self._lock:
            if self._open:
                now = time_lib.monotonic()
                remaining = self._open_until - now
                if remaining > 0 or self._trial_inflight:
                    raise CircuitOpenError(
                        f"dispatch circuit breaker open "
                        f"({self._consecutive} consecutive device errors; "
                        f"last: {self._last_error}); failing fast — retry in "
                        f"{max(0.0, remaining):.2f}s",
                        retry_after_s=max(0.0, remaining),
                        last_error=self._last_error)
                self._trial_inflight = True  # half-open: this caller probes
                trial = True
        if trial:
            flight_emit("breaker.half_open")

    def record_success(self) -> None:
        if self.threshold <= 0:
            return
        with self._lock:
            closed_now = self._open
            self._consecutive = 0
            self._open = False
            self._trial_inflight = False
        if closed_now:
            flight_emit("breaker.close")

    def release_trial(self) -> None:
        """Give back a half-open trial slot WITHOUT judging the device
        (the attempt died on a deterministic caller error, which proves
        nothing about device health).  The breaker stays open, but the
        next gate() may admit a fresh trial — without this, a
        NON_RETRYABLE error during the trial would pin ``_trial_inflight``
        and leave the breaker open forever."""
        if self.threshold <= 0:
            return
        with self._lock:
            self._trial_inflight = False

    def record_failure(self, exc: BaseException) -> bool:
        """Count a device error; returns True when this failure OPENED
        (or re-opened) the breaker — recorded as a ``breaker.open``
        flight event outside the lock."""
        if self.threshold <= 0 or isinstance(exc, NON_RETRYABLE):
            return False
        with self._lock:
            self._consecutive += 1
            was_trial = self._trial_inflight
            self._trial_inflight = False
            self._last_error = f"{type(exc).__name__}: {exc}"
            opened = was_trial or (not self._open
                                   and self._consecutive >= self.threshold)
            if opened:
                self._open = True
                self._open_until = time_lib.monotonic() + self.cooldown_s
                self._opened_count += 1
            consecutive = self._consecutive
        if opened:
            flight_emit("breaker.open", consecutive=consecutive,
                        cooldown_s=self.cooldown_s,
                        error=type(exc).__name__)
        return opened

    def open_remaining_s(self) -> Optional[float]:
        """Remaining cool-down if OPEN, else None — the cheap per-submit
        query (one lock, no snapshot dict) the serving admission path
        uses; half-open reports None so trial traffic is admitted."""
        if self.threshold <= 0:
            return None
        with self._lock:
            if not self._open:
                return None
            remaining = self._open_until - time_lib.monotonic()
            if remaining <= 0 and not self._trial_inflight:
                return None  # half-open: let the trial through
            return max(0.0, remaining)

    def state(self) -> Dict[str, Any]:
        """JSON-serializable breaker snapshot (``Server.health`` /
        ``varz`` surface this per bucket engine)."""
        with self._lock:
            now = time_lib.monotonic()
            if not self._open:
                st = "closed"
            elif now < self._open_until or self._trial_inflight:
                st = "open"
            else:
                st = "half_open"
            return {
                "state": st,
                "enabled": self.threshold > 0,
                "consecutive_failures": self._consecutive,
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
                "retry_after_s": (round(max(0.0, self._open_until - now), 3)
                                  if st == "open" else 0.0),
                "opened_count": self._opened_count,
                "last_error": self._last_error,
            }


# Module-level compiled-program cache: engines built around the SAME model
# fn / mesh / donation policy share one jax.jit object (whose executable
# cache then de-duplicates per batch shape).  A tuning grid produces many
# fitted models over one fn with different weights — without this, every
# model.transform() recompiled the identical program.  Keys use id(fn);
# safe because the cached jit closes over fn, keeping the id pinned.
# BoundedCache locks put/evict: fitMultiple's parallel fan-out transforms
# from worker threads.
from sparkdl_tpu.utils.cache import BoundedCache

_JIT_CACHE = BoundedCache(cap=32)


def clear_engine_jit_cache() -> None:
    _JIT_CACHE.clear()


def resolve_engine_mesh(mesh=None):
    """The mesh an :class:`InferenceEngine` actually runs on when the
    caller passes ``mesh`` (possibly None).  Scoring is per-controller by
    design (PERF.md topology envelope): under multi-controller jax the
    default covers LOCAL devices only, and an explicit cross-process mesh
    is refused loudly — device_put of process-local numpy onto a global
    sharding fails confusingly at runtime.  Shared with the serving
    bucket plan and ``analysis.program`` so enumerated programs see the
    same topology the engine compiles for."""
    import jax

    if mesh is None:
        if jax.process_count() > 1:
            mesh = mesh_lib.get_mesh(devices=jax.local_devices())
        else:
            mesh = mesh_lib.get_mesh()
    if jax.process_count() > 1 and any(
            d.process_index != jax.process_index()
            for d in mesh.devices.flat):
        raise NotImplementedError(
            "InferenceEngine is single-controller: pass a mesh over "
            "this process's local devices (mesh.get_mesh(devices="
            "jax.local_devices())) and shard input rows per host; "
            "multi-controller collectives belong to the TRAIN path "
            "(parallel.train / parallel.distributed).")
    return mesh


def effective_device_batch(device_batch_size: int, mesh) -> int:
    """The device batch the engine actually compiles for: rounded UP to a
    multiple of the mesh's data-axis size so every chip gets identical
    work.  Single-sourced so the serving bucket plan and the program
    auditor (``analysis.program``) enumerate exactly the shapes
    :class:`InferenceEngine` builds."""
    dp = mesh.shape[mesh_lib.DATA_AXIS]
    b = max(1, int(device_batch_size))
    rem = b % dp
    return b + (dp - rem) if rem else b


def build_dispatch_jit(fn: Callable, mesh, donate_batch: bool,
                       param_shardings=None):
    """THE per-batch dispatch program: ``jit(fn)`` with params placed
    under ``param_shardings`` (a pytree of per-leaf ``NamedSharding`` —
    the tensor-parallel weight layout from ``mesh.
    resolve_param_shardings``; ``None`` = the classic replicate-
    everything layout, byte-identical to the pre-ISSUE-14 program),
    batch sharded on the data axis, and the batch donated when asked.
    :class:`InferenceEngine` compiles through this (via the module jit
    cache) and ``analysis.program`` lowers the same object abstractly —
    one constructor, so the audited program cannot drift from the served
    one."""
    import jax

    params_sh = (param_shardings if param_shardings is not None
                 else mesh_lib.replicated_sharding(mesh))
    return jax.jit(
        fn,
        in_shardings=(params_sh, mesh_lib.batch_sharding(mesh)),
        out_shardings=mesh_lib.batch_sharding(mesh),
        donate_argnums=(1,) if donate_batch else ())


def build_grouped_dispatch_jit(fn: Callable, mesh, donate_batch: bool,
                               batches_per_dispatch: int,
                               param_shardings=None):
    """The grouped (``batches_per_dispatch`` > 1) dispatch program: one
    ``lax.map`` launch over a stacked leading group axis.  Shared with
    ``analysis.program`` exactly like :func:`build_dispatch_jit`;
    ``param_shardings`` has the same semantics."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    group_sh = NamedSharding(mesh, P(None, mesh_lib.DATA_AXIS))

    def fn_group(v, xs):
        return jax.lax.map(lambda x: fn(v, x), xs)

    params_sh = (param_shardings if param_shardings is not None
                 else mesh_lib.replicated_sharding(mesh))
    return jax.jit(
        fn_group,
        in_shardings=(params_sh, group_sh),
        out_shardings=group_sh,
        donate_argnums=(1,) if donate_batch else ())


def dense_head_row(head, features):
    """THE canonical per-tenant head: one dense projection applied to ONE
    feature row (no batch axis — :func:`build_head_fanout_jit` vmaps it).
    ``head`` is the per-tenant weight pytree ``{"kernel": (D, C),
    "bias": (C,)}``.  Module-level on purpose: the runtime
    :class:`HeadBank`, the audited program in ``analysis.program.
    inventory``, and the zoo's feature-cut bundle all reference this ONE
    function object, so the lockfile-pinned head program is the program
    served.

    Spelled as an explicit broadcast-multiply-reduce rather than ``@``
    ON PURPOSE: the vmapped form (a per-row head gathered out of the
    bank) and the unbatched form (an independent full-model oracle)
    then lower to the SAME reduction order, so fan-out outputs are
    bit-identical to per-tenant oracles — the headline proof.  With
    ``@``, XLA picks a batched-matmul kernel for the vmapped head and a
    plain gemm for the oracle, whose accumulation orders differ by an
    ulp (measured on CPU XLA), silently breaking the bit-identity
    contract."""
    import jax.numpy as jnp

    return (jnp.sum(features[:, None] * head["kernel"], axis=0)
            + head["bias"])


def head_fanout_backbone_fn(variables, batch):
    """The chip-free backbone stand-in for the head fan-out tier's
    deterministic proofs (tests/bench/inventory): a dense tanh
    featurizer.  Module-level for the same reason as
    :func:`dense_head_row` — the audited backbone-cut program and the
    sleep-wrapped backbone the replay tests serve are the SAME fn, so
    jit-object identity is meaningful evidence."""
    import jax.numpy as jnp

    return jnp.tanh(batch @ variables["backbone"])


def head_fanout_oracle_fn(variables, row):
    """The INDEPENDENT per-tenant full-model oracle the fan-out tier's
    bit-identity proofs compare against: one unbatched row through the
    fused weights ``{"backbone", "kernel", "bias"}`` — the program shape
    a dedicated per-tenant full-model deployment would serve.  Jitted
    independently by each test/bench (never through
    :func:`build_head_fanout_jit`), so agreement with the fan-out path
    is evidence, not tautology."""
    import jax.numpy as jnp

    feats = jnp.tanh(row @ variables["backbone"])
    return dense_head_row(
        {"kernel": variables["kernel"], "bias": variables["bias"]}, feats)


def build_head_fanout_jit(head_fn: Callable, mesh):
    """THE stacked-head dispatch program: gather-by-tenant-index + vmap,
    so K tenants' rows in one batch cost ONE head pass.

    ``fanout(stacked, idx, feats)`` takes the head bank (every tenant's
    head pytree stacked along a leading capacity axis, replicated),
    a per-row ``int32`` tenant-index vector, and the feature rows
    (both data-sharded); it gathers each row's head out of the bank and
    applies ``vmap(head_fn)``.  Gather + vmap lowers to the same
    per-row contraction a dedicated per-tenant program would emit —
    the bit-identity tests against independent full-model oracles pin
    that down.  One constructor shared with ``analysis.program`` (like
    :func:`build_dispatch_jit`), so the audited stacked program cannot
    drift from the served one."""
    import jax

    def fanout(stacked, idx, feats):
        gathered = jax.tree_util.tree_map(lambda leaf: leaf[idx], stacked)
        return jax.vmap(head_fn)(gathered, feats)

    # donate nothing: the stacked bank is long-lived state shared by
    # every dispatch, and the padded feature rows are caller-owned
    return jax.jit(
        fanout,
        donate_argnums=(),
        in_shardings=(mesh_lib.replicated_sharding(mesh),
                      mesh_lib.batch_sharding(mesh),
                      mesh_lib.batch_sharding(mesh)),
        out_shardings=mesh_lib.batch_sharding(mesh))


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class HeadBank:
    """Per-tenant head weights stacked into ONE device pytree served by
    ONE vmapped program (:func:`build_head_fanout_jit`).

    The bank holds K tenants' head pytrees stacked along a leading
    capacity axis (capacity = next power of two, so adds recompile the
    HEAD program at most log2(K) times and the backbone never).  A
    mixed-tenant feature batch dispatches as gather-by-tenant-index —
    one head pass regardless of how many tenants' rows it carries.

    Degraded mode instead of a crash (tested): a head whose pytree
    structure/shape/dtype cannot stack with the bank ("indivisible"),
    or a bank whose stacked bytes would exceed ``hbm_budget_bytes``
    (checked via ``mesh.param_sharding_stats``), flips the bank to
    per-tenant fallback — every tenant is served through the SAME
    fan-out jit object as a bank of one, so program identity and
    bit-identity survive, only the one-pass batching is lost.

    Thread-safety: all mutation and dispatch run under
    ``named_lock("engine.headbank")``, so a hot-swap under load is
    atomic — in-flight dispatches see the old bank or the new one,
    never a torn index."""

    def __init__(self, head_fn: Optional[Callable] = None, mesh=None,
                 hbm_budget_bytes: Optional[int] = None,
                 metrics: Optional[Metrics] = None):
        self.head_fn = head_fn if head_fn is not None else dense_head_row
        self.mesh = resolve_engine_mesh(mesh)
        self.hbm_budget_bytes = (None if hbm_budget_bytes is None
                                 else int(hbm_budget_bytes))
        self.metrics = metrics if metrics is not None else Metrics()
        self._lock = named_lock("engine.headbank")
        self._hosts: Dict[str, Any] = {}    # tenant -> host head pytree
        self._index: Dict[str, int] = {}    # tenant -> row in the bank
        self._order: list = []              # tenants in stacking order
        self._stacked = None                # device pytree (capacity, ...)
        self._capacity = 0
        self._leaf_sig = None               # pinned (treedef, shapes, dtypes)
        self._fallback = False
        self._fallback_reason: Optional[str] = None
        # Same module-cache recipe as InferenceEngine: one jit object per
        # (head_fn, mesh), shared across banks/servers — the head-swap
        # no-recompile proof compares id() of this object.
        mesh_key = (tuple(d.id for d in self.mesh.devices.flat),
                    tuple(self.mesh.axis_names),
                    tuple(self.mesh.devices.shape))
        key = (id(self.head_fn),) + mesh_key + ("fanout",)
        jitted = _JIT_CACHE.get(key)
        if jitted is None:
            jitted = build_head_fanout_jit(self.head_fn, self.mesh)
            _JIT_CACHE.put(key, jitted)
        self._fanout = jitted

    # -- introspection ---------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._order)

    @property
    def mode(self) -> str:
        with self._lock:
            return "fallback" if self._fallback else "stacked"

    def tenants(self) -> list:
        with self._lock:
            return list(self._order)

    def jit_info(self) -> Dict[str, Any]:
        """The head half of the no-recompile proof (the shape
        ``Server.executable_state`` uses for backbone buckets): the
        fan-out jit object's id plus its executable-cache size.  A head
        add/swap may grow ``executables`` (that's the HEAD program, by
        design at most once per capacity doubling); ``jit_id`` must
        never change."""
        try:
            size = int(self._fanout._cache_size())
        except (AttributeError, TypeError):  # older jax: identity only
            size = None
        return {"jit_id": id(self._fanout), "executables": size,
                "mode": self.mode}

    def stats(self) -> Dict[str, Any]:
        """Stacked-bank HBM accounting via ``mesh.param_sharding_stats``
        — the same ledger GC005 audits, so the budget the bank enforces
        is the budget the program auditor sees."""
        with self._lock:
            if self._fallback or not self._order:
                tree = dict(self._hosts) if self._hosts else None
            else:
                tree = self._stack_hosts(self._capacity)
            if tree is None:
                param = {"param_bytes_total": 0, "param_bytes_per_chip": 0}
            else:
                param = mesh_lib.param_sharding_stats(self.mesh, tree)
            out = dict(param)
            out.update({
                "tenants": len(self._order),
                "capacity": self._capacity,
                "mode": "fallback" if self._fallback else "stacked",
                "fallback_reason": self._fallback_reason,
                "hbm_budget_bytes": self.hbm_budget_bytes,
            })
            return out

    # -- mutation --------------------------------------------------------

    def add_head(self, tenant: str, weights) -> None:
        """Register a NEW tenant's head.  Raises ``ValueError`` if the
        tenant already has one (use :meth:`swap_head`)."""
        self._mutate(tenant, weights, op="add")

    def swap_head(self, tenant: str, weights) -> None:
        """Hot-swap an EXISTING tenant's head.  Raises ``KeyError`` if
        the tenant is unknown (use :meth:`add_head`)."""
        self._mutate(tenant, weights, op="swap")

    def remove_head(self, tenant: str) -> None:
        """Evict a departed tenant: its row leaves the bank and the
        remaining tenants re-stack (capacity may shrink)."""
        self._mutate(tenant, None, op="remove")

    def _mutate(self, tenant: str, weights, op: str) -> None:
        import jax

        tenant = str(tenant)
        with self._lock:
            # Fault site fires BEFORE any state changes: an injected
            # error aborts the mutation with the bank unchanged.
            inject("head.swap")
            if op == "remove":
                if tenant not in self._hosts:
                    raise KeyError(f"head bank has no tenant {tenant!r}")
                del self._hosts[tenant]
                self._order.remove(tenant)
            else:
                if op == "add" and tenant in self._hosts:
                    raise ValueError(
                        f"tenant {tenant!r} already has a head; "
                        "swap_head() replaces it")
                if op == "swap" and tenant not in self._hosts:
                    raise KeyError(f"head bank has no tenant {tenant!r}")
                host = jax.tree_util.tree_map(np.asarray, weights)
                sig = self._signature(host)
                if self._leaf_sig is None:
                    self._leaf_sig = sig
                elif sig != self._leaf_sig and not self._fallback:
                    self._degrade(
                        f"tenant {tenant!r} head does not stack with the "
                        f"bank (pytree/shape/dtype mismatch)")
                self._hosts[tenant] = host
                if op == "add":
                    self._order.append(tenant)
            if not self._fallback:
                cap = _next_pow2(max(1, len(self._order)))
                over = self._budget_excess(cap)
                if over is not None:
                    self._degrade(
                        f"stacked bank would hold {over} bytes per chip, "
                        f"over hbm_budget_bytes={self.hbm_budget_bytes}")
            self._rebuild()
            self.metrics.incr(f"headbank.{op}")
            flight_emit("head.swap", tenant=tenant, op=op,
                        tenants=len(self._order),
                        mode="fallback" if self._fallback else "stacked")

    def _signature(self, host):
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(host)
        return (treedef,
                tuple(tuple(np.shape(x)) for x in leaves),
                tuple(str(np.asarray(x).dtype) for x in leaves))

    def _degrade(self, reason: str) -> None:
        self._fallback = True
        self._fallback_reason = reason
        self.metrics.incr("headbank.fallbacks")
        logger.warning("HeadBank degrading to per-tenant dispatch: %s",
                       reason)

    def _budget_excess(self, capacity: int):
        """Bytes-per-chip the stacked bank would occupy if it exceeds the
        budget, else None.  Uses ``param_sharding_stats`` (replicated
        layout) so the number matches GC005's ledger."""
        if self.hbm_budget_bytes is None or not self._order:
            return None
        tree = self._stack_hosts(capacity)
        stats = mesh_lib.param_sharding_stats(self.mesh, tree)
        per_chip = int(stats["param_bytes_per_chip"])
        return per_chip if per_chip > self.hbm_budget_bytes else None

    def _stack_hosts(self, capacity: int):
        import jax

        heads = [self._hosts[t] for t in self._order]
        pad = heads[0]
        rows = heads + [pad] * (capacity - len(heads))
        return jax.tree_util.tree_map(
            lambda *ls: np.stack([np.asarray(x) for x in ls]), *rows)

    def _rebuild(self) -> None:
        import jax

        self._index = {t: i for i, t in enumerate(self._order)}
        if self._fallback or not self._order:
            self._stacked = None
            self._capacity = 0 if not self._order else self._capacity
            if not self._order:
                self._capacity = 0
            return
        cap = _next_pow2(len(self._order))
        stacked_host = self._stack_hosts(cap)
        self._stacked = jax.device_put(
            stacked_host, mesh_lib.replicated_sharding(self.mesh))
        self._capacity = cap

    # -- dispatch --------------------------------------------------------

    def _row_bucket(self, n: int) -> int:
        """Pad row counts to a power of two rounded to the data axis, so
        the head program compiles O(log) executables, not one per ragged
        batch size."""
        dp = self.mesh.shape[mesh_lib.DATA_AXIS]
        p = _next_pow2(max(1, n))
        rem = p % dp
        return p + (dp - rem) if rem else p

    def dispatch(self, features, tenants) -> np.ndarray:
        """One head pass over a mixed-tenant feature batch.

        ``features`` is ``(n, ...)`` host rows (a single row is
        promoted); ``tenants`` names each row's head.  Returns host
        outputs row-aligned with the input.  Raises ``KeyError`` for a
        tenant with no registered head (a departed tenant must fail
        loudly, not serve a stale row)."""
        import jax

        features = np.asarray(features)
        if features.ndim == 1:
            features = features[None]
        tenants = [str(t) for t in tenants]
        if len(tenants) != int(features.shape[0]):
            raise ValueError(
                f"{features.shape[0]} feature rows but "
                f"{len(tenants)} tenants")
        with self._lock:
            inject("head.dispatch")
            missing = sorted({t for t in tenants if t not in self._hosts})
            if missing:
                raise KeyError(
                    f"head bank has no head for tenant(s) {missing}")
            self.metrics.incr("headbank.dispatches")
            self.metrics.incr("headbank.rows", len(tenants))
            if self._fallback:
                return self._dispatch_fallback(features, tenants)
            n = int(features.shape[0])
            idx = np.asarray([self._index[t] for t in tenants],
                             dtype=np.int32)
            padded = self._row_bucket(n)
            if padded != n:
                features = np.concatenate(
                    [features,
                     np.zeros((padded - n,) + features.shape[1:],
                              dtype=features.dtype)])
                idx = np.concatenate(
                    [idx, np.zeros(padded - n, dtype=np.int32)])
            out = self._fanout(self._stacked, idx, features)
            return np.asarray(out)[:n]

    def _dispatch_fallback(self, features, tenants) -> np.ndarray:
        """Per-tenant degraded path: each tenant's rows go through the
        SAME fan-out jit as a bank of one (same program identity, same
        numerics) — one head pass per tenant instead of one total."""
        import jax

        groups: Dict[str, list] = {}
        for i, t in enumerate(tenants):
            groups.setdefault(t, []).append(i)
        out = None
        for t, rows in groups.items():
            sel = np.asarray(rows, dtype=np.int64)
            feats_t = features[sel]
            n = int(feats_t.shape[0])
            padded = self._row_bucket(n)
            if padded != n:
                feats_t = np.concatenate(
                    [feats_t,
                     np.zeros((padded - n,) + feats_t.shape[1:],
                              dtype=feats_t.dtype)])
            bank1 = jax.tree_util.tree_map(
                lambda leaf: np.asarray(leaf)[None], self._hosts[t])
            idx = np.zeros(padded, dtype=np.int32)
            res = np.asarray(self._fanout(bank1, idx, feats_t))[:n]
            if out is None:
                out = np.zeros((len(tenants),) + res.shape[1:],
                               dtype=res.dtype)
            out[sel] = res
        return out


def batches_per_dispatch_from_env() -> int:
    """``SPARKDL_BATCHES_PER_DISPATCH`` (clamped to >= 1) — the one
    parser every engine-constructing site shares, so cache keys and
    defaults cannot drift."""
    import os

    raw = os.environ.get("SPARKDL_BATCHES_PER_DISPATCH", "") or "1"
    return max(1, int(raw))


def _is_narrow_float(dtype) -> bool:
    """True iff ``dtype`` is an ml_dtypes narrow float (bf16/f8 families).

    These register as numpy kind 'V' (void), which also covers structured
    dtypes — ``ml_dtypes.finfo`` accepts only the float ones.
    """
    if np.dtype(dtype).kind != "V":
        return False
    try:
        import ml_dtypes

        ml_dtypes.finfo(dtype)
        return True
    except (ImportError, ValueError, TypeError, KeyError):
        return False


def _cast_floating(variables, dtype):
    import jax
    import jax.numpy as jnp

    def cast(leaf):
        arr = jnp.asarray(leaf)
        if jnp.issubdtype(arr.dtype, jnp.floating):
            return arr.astype(dtype)
        return arr

    return jax.tree_util.tree_map(cast, variables)


class InferenceEngine:
    """Runs ``fn(variables, batch) -> out`` over arbitrarily-sized inputs in
    fixed-shape device batches on a device mesh.

    ``fn`` must be jit-traceable with a leading batch axis on ``batch`` and
    on every output leaf (outputs may be a single array or a pytree).

    Weight sharding (ISSUE 14): ``partition_rules`` (a ``(regex,
    PartitionSpec)`` rule list or a ``mesh -> rules`` factory — see
    ``mesh.match_partition_rules`` / ``mesh.default_partition_rules``)
    or an explicit ``param_shardings`` pytree split chosen param leaves
    across the mesh's ``model`` axis, ending the one-full-weight-copy-
    per-chip model: each chip holds ``bytes / model_axis`` of a sharded
    leaf and XLA's SPMD partitioner inserts the collectives the layout
    implies.  The default (both ``None``) — and any policy that
    resolves all-replicated, e.g. the default rules on a model-axis-1
    mesh — keeps the classic replicate-everything layout with
    byte-identical programs.  The policy is part of the jit-cache key
    (``sharding_digest``), so engines under different layouts never
    alias a compiled program.
    """

    def __init__(self, fn: Callable, variables: Any, *,
                 mesh=None,
                 device_batch_size: int = 64,
                 compute_dtype: Optional[Any] = None,
                 output_host_dtype: Optional[Any] = None,
                 donate_batch: bool = False,
                 partition_rules: Any = None,
                 param_shardings: Any = None,
                 batches_per_dispatch: int = 1,
                 dispatch_retries: int = 0,
                 dispatch_backoff_s: float = 0.05,
                 dispatch_max_backoff_s: float = 2.0,
                 dispatch_jitter: float = 0.25,
                 breaker_threshold: int = 8,
                 breaker_cooldown_s: float = 30.0,
                 on_dispatch_error: Optional[
                     Callable[[BaseException], None]] = None,
                 metrics: Optional[Metrics] = None):
        import jax

        # Scoring is per-controller by design (PERF.md topology
        # envelope): each host scores its own rows on its own devices —
        # see resolve_engine_mesh (the zoo transformers pass no mesh, so
        # the local-devices default keeps them working on pods).
        self.mesh = resolve_engine_mesh(mesh)
        self.data_parallel = self.mesh.shape[mesh_lib.DATA_AXIS]
        self.model_parallel = self.mesh.shape[mesh_lib.MODEL_AXIS]
        # Round the device batch up to a multiple of the data-axis size so
        # every chip gets identical work.
        b = effective_device_batch(device_batch_size, self.mesh)
        if b != max(1, int(device_batch_size)):
            logger.info("device_batch_size rounded up to %d (multiple of "
                        "%d-way data axis)", b, self.data_parallel)
        self.device_batch_size = b
        self.metrics = metrics if metrics is not None else Metrics()
        # Fetch device outputs in their compute dtype and cast on the HOST:
        # a bf16 model result upcast to f32 on device carries no extra
        # information, but doubles the D2H bytes of every gather — casting
        # host-side after the fetch is bit-identical and halves transfer
        # (minimise host<->device traffic; D2H is the narrow direction on
        # relayed links — PERF.md).  None = return outputs as produced.
        self.output_host_dtype = (np.dtype(output_host_dtype)
                                  if output_host_dtype is not None else None)

        # Failure domain (ISSUE 4): bounded retry-with-backoff for
        # TRANSIENT dispatch faults (jittered + capped via utils.retry —
        # the Spark task-retry analog at dispatch granularity; default 0
        # = fail fast, callers opt in) and a consecutive-failure circuit
        # breaker so a STICKY-dead device fails fast with a clear error
        # instead of paying the full retry budget per call forever.
        # ``on_dispatch_error`` fires on every failed ATTEMPT (even ones
        # a retry later absorbs) — the serving layer's health() hook.
        self.dispatch_retries = max(0, int(dispatch_retries))
        self.dispatch_backoff_s = max(0.0, float(dispatch_backoff_s))
        self.dispatch_max_backoff_s = float(dispatch_max_backoff_s)
        self.dispatch_jitter = float(dispatch_jitter)
        self.breaker = DispatchCircuitBreaker(
            threshold=breaker_threshold, cooldown_s=breaker_cooldown_s)
        self._on_dispatch_error = on_dispatch_error

        # k host batches per compiled dispatch (lax.map over a stacked
        # leading group axis): one launch + one result fetch per k batches
        # — the inference analog of the train loop's steps_per_execution.
        # Identical per-batch math (lax.map is a scan, not a vmap, so
        # nothing about the batch dimension the model sees changes); wins
        # whenever dispatch/fetch latency rivals compute (relayed links,
        # multi-host pods).  k=1 is the plain program.  map_batches scales
        # its in-flight window to max(1, window // k) GROUPS so grouping
        # does not silently multiply peak device residency by ~k.
        self.batches_per_dispatch = max(1, int(batches_per_dispatch))

        if compute_dtype is not None:
            variables = _cast_floating(variables, compute_dtype)
        self._replicated = mesh_lib.replicated_sharding(self.mesh)
        self._batch_sharding = mesh_lib.batch_sharding(self.mesh)
        # Tensor-parallel weight sharding (ISSUE 14): resolve the policy
        # to per-leaf NamedShardings.  ``param_shardings`` (a pytree of
        # PartitionSpec/NamedSharding matching ``variables``) wins over
        # ``partition_rules`` (a regex rule list, or a ``mesh -> rules``
        # factory like mesh.default_partition_rules).  An all-replicated
        # resolution COLLAPSES to the classic single replicate sharding,
        # so model-axis-1 meshes build byte-identical programs with the
        # same executable cache keys as the pre-ISSUE-14 stack.
        self.param_shardings = None
        self._param_specs = None
        if param_shardings is not None:
            # explicit leaves (PartitionSpec or NamedSharding) are
            # normalized onto THIS engine's mesh through the ONE
            # resolution path the rules share — same structure check,
            # same per-leaf divisibility fallback (an indivisible
            # explicit spec replicates instead of crashing device_put)
            self.param_shardings, self._param_specs = (
                mesh_lib.resolve_param_shardings(variables, self.mesh,
                                                 specs=param_shardings))
        elif partition_rules is not None:
            self.param_shardings, self._param_specs = (
                mesh_lib.resolve_param_shardings(variables, self.mesh,
                                                 partition_rules))
        if (self._param_specs is not None
                and mesh_lib.specs_all_replicated(self._param_specs)):
            self.param_shardings = None
            self._param_specs = None
        self.sharding_digest = mesh_lib.partition_digest(self._param_specs)
        # HBM accounting (ISSUE 14 bench rider): per-chip param bytes
        # under this layout vs the one-full-copy-per-chip baseline,
        # gauged so bench lines / varz can stamp the claim chip-free
        self._sharding_stats = mesh_lib.param_sharding_stats(
            self.mesh, variables, self._param_specs)
        self.metrics.gauge("engine.mesh_data_axis",
                           float(self.data_parallel))
        self.metrics.gauge("engine.mesh_model_axis",
                           float(self.model_parallel))
        self.metrics.gauge("engine.replicated_param_bytes",
                           float(self._sharding_stats["param_bytes_total"]))
        self.metrics.gauge("engine.param_bytes_per_chip",
                           float(self._sharding_stats["param_bytes_per_chip"]))
        # Persistent compile cache (ISSUE 13): resolve the
        # SPARKDL_COMPILE_CACHE knob once per process BEFORE any
        # program of this engine compiles, so fleet deploys and
        # serving cold-starts across restarts reuse on-disk
        # executables keyed on the committed lockfile.  Disabled path
        # = one module-global read.  The FIRST engine's mesh/partition
        # policy keys the manifest (ISSUE 14): a restarted process
        # under a different sharding policy purges the population
        # cleanly instead of trusting content-addressing alone.
        from sparkdl_tpu.parallel import compile_cache

        compile_cache.ensure_from_env(policy=self.compile_policy())
        # Params live on device once — per-leaf NamedShardings when the
        # policy splits them (each chip holds bytes/model_axis of a
        # sharded leaf), the NamedSharding replicate otherwise (the TPU
        # analog of the reference's model-GraphDef broadcast).
        self.variables = jax.device_put(
            variables, self.param_shardings if self.param_shardings
            is not None else self._replicated)
        # grid SHAPE is part of the key (as in train._mesh_key): a
        # (1, 8) and a (2, 4) mesh over the same 8 devices share flat
        # device ids and axis names but compile different programs
        mesh_key = (tuple(d.id for d in self.mesh.devices.flat),
                    tuple(self.mesh.axis_names),
                    tuple(self.mesh.devices.shape), bool(donate_batch),
                    self.sharding_digest)
        key = (id(fn),) + mesh_key + (1,)
        compiled = _JIT_CACHE.get(key)
        if compiled is None:
            compiled = build_dispatch_jit(fn, self.mesh, donate_batch,
                                          param_shardings=self.param_shardings)
            _JIT_CACHE.put(key, compiled)
        # the plain per-batch program always exists: it runs run_padded
        # and the ragged tail group (cheaper than padding a group with
        # full zero batches that would execute the whole model)
        self._compiled = compiled
        if self.batches_per_dispatch > 1:
            gkey = (id(fn),) + mesh_key + (self.batches_per_dispatch,)
            grouped = _JIT_CACHE.get(gkey)
            if grouped is None:
                grouped = build_grouped_dispatch_jit(
                    fn, self.mesh, donate_batch, self.batches_per_dispatch,
                    param_shardings=self.param_shardings)
                _JIT_CACHE.put(gkey, grouped)
            self._compiled_group = grouped

    # -- low level ---------------------------------------------------------
    @staticmethod
    def _leaves(batch):
        import jax

        leaves = jax.tree_util.tree_leaves(batch)
        if not leaves:
            raise ValueError("Batch pytree has no array leaves")
        n = leaves[0].shape[0]
        if any(l.shape[0] != n for l in leaves):
            raise ValueError("All batch leaves must share the leading "
                             "(batch) axis length")
        return n

    def _attempt_dispatch(self, thunk):
        """ONE gated dispatch attempt: breaker gate -> fault-injection
        site -> H2D + launch; success/failure feed the breaker and the
        ``on_dispatch_error`` health hook.  Deterministic errors
        (``NON_RETRYABLE``) bypass the breaker count — they are caller
        bugs, not device state."""
        self.breaker.gate()
        try:
            inject("engine.dispatch")
            out = thunk()
        except NON_RETRYABLE:
            # deterministic caller error: not device evidence either way
            # — but a half-open trial slot must be handed back, or the
            # breaker could never re-probe
            self.breaker.release_trial()
            raise
        except BaseException as e:  # noqa: BLE001 — device/runtime error
            self._charge_breaker(e, "engine.dispatch_errors")
            raise
        # NOTE: success is NOT recorded here.  Dispatch is an async
        # ENQUEUE — a dying device usually raises when the result is
        # forced (D2H), so the attempt is only known good at force time
        # (_force_parts), which records the breaker success.
        return out

    def _charge_breaker(self, e: BaseException, counter: str) -> None:
        """Shared failure bookkeeping for both failure surfaces of an
        async dispatch (the enqueue attempt and the result force):
        metrics, breaker count, open log line, and the health hook."""
        self.metrics.incr(counter)
        if self.breaker.record_failure(e):
            self.metrics.incr("engine.breaker_opened")
            logger.warning(
                "dispatch circuit breaker OPENED after %d consecutive "
                "device errors (last: %s: %s); failing fast for %.1fs",
                self.breaker.state()["consecutive_failures"],
                type(e).__name__, e, self.breaker.cooldown_s)
        if self._on_dispatch_error is not None:
            self._on_dispatch_error(e)

    def _force_parts(self, ns, out, block=None):
        """Force one in-flight dispatch to host row batch(es) — the D2H
        fetch + trim shared verbatim by the serial drain and the
        pipelined gather stage (``ns`` int = plain piece; tuple = a
        grouped dispatch, fetched once and sliced host-side).

        This is the OTHER failure surface of an async dispatch: jax's
        enqueue returns before the device runs, so a dying device
        typically raises here, not in ``_attempt_dispatch`` — errors are
        charged to the same breaker/health accounting (no retry: a
        failed force cannot be re-run without re-dispatching), and a
        successful force is what records breaker success.  ``block``
        (the gather span's ``block_until_ready``) forces device
        completion inside the caller's span so device wait stays
        attributed."""
        import jax

        try:
            inject("engine.gather")
            if block is not None:
                block(out)
            if isinstance(ns, int):
                parts = [self._trim(out, ns)]
            else:
                # one D2H fetch for the whole group, sliced on the host
                # (per-batch device slicing would pay k fetch round
                # trips — the latency the grouping exists to amortize)
                host = jax.tree_util.tree_map(np.asarray, out)
                parts = [self._trim(jax.tree_util.tree_map(
                    lambda a, i=i: a[i], host), n)
                    for i, n in enumerate(ns)]
        except NON_RETRYABLE:
            self.breaker.release_trial()
            raise
        except BaseException as e:  # noqa: BLE001 — device/runtime error
            self._charge_breaker(e, "engine.gather_errors")
            raise
        self.breaker.record_success()
        return parts

    def _run_dispatch(self, thunk):
        """Dispatch with the engine's transient-fault retry budget:
        ``dispatch_retries`` re-executions with jittered, capped
        exponential backoff (``utils.retry``).  Deterministic failures
        and a breaker that opened mid-budget fail immediately."""
        if self.dispatch_retries <= 0:
            return self._attempt_dispatch(thunk)

        def on_retry(attempt, exc):
            self.metrics.incr("engine.dispatch_retries")

        return with_retries(
            lambda: self._attempt_dispatch(thunk),
            max_retries=self.dispatch_retries,
            non_retryable=NON_RETRYABLE + (CircuitOpenError,),
            backoff_seconds=self.dispatch_backoff_s,
            max_backoff_seconds=self.dispatch_max_backoff_s,
            jitter=self.dispatch_jitter,
            on_retry=on_retry)

    def breaker_state(self) -> Dict[str, Any]:
        """The dispatch circuit breaker's JSON-serializable snapshot."""
        return self.breaker.state()

    def compile_policy(self) -> str:
        """The mesh + partition-rule policy string keying the persistent
        compile-cache manifest (``parallel.compile_cache``): a restarted
        process whose first engine resolves a DIFFERENT policy purges
        the on-disk executable population instead of trusting
        content-addressing alone."""
        return (f"mesh={self.data_parallel}x{self.model_parallel}"
                f"|params={self.sharding_digest}")

    def sharding_info(self) -> Dict[str, Any]:
        """JSON snapshot of this engine's weight-sharding layout (ISSUE
        14): mesh shape, total vs per-chip param bytes, sharded leaf
        count, and the policy digest — what ``Server.varz`` embeds and
        the bench HBM rider stamps next to ``pad_overhead``."""
        return dict(self._sharding_stats,
                    sharding_digest=self.sharding_digest,
                    sharded=self.param_shardings is not None)

    def run_padded(self, batch):
        """Run one already-padded device batch (array or pytree of arrays
        sharing the leading batch axis); returns device output(s)."""
        import jax

        if self._leaves(batch) != self.device_batch_size:
            raise ValueError(
                f"run_padded expects batch of {self.device_batch_size}, "
                f"got {self._leaves(batch)}")

        # span covers H2D + async launch only (the call returns as soon
        # as the dispatch is enqueued); the device wait is bracketed by
        # whichever stage forces the result (pipeline.gather / _trim)
        def attempt():
            with get_tracer().span("engine.dispatch",
                                   rows=self.device_batch_size):
                x = jax.device_put(batch, self._batch_sharding)
                return self._compiled(self.variables, x)

        return self._run_dispatch(attempt)

    def _pad(self, chunk):
        import jax

        n = self._leaves(chunk)
        # pad-to-bucket ledger (ISSUE 11): real vs padded rows per
        # dispatch piece, so the measured pad overhead GC004 budgets
        # abstractly is observable live (`engine.pad_rows /
        # (engine.rows + engine.pad_rows)`) and bench lines can stamp
        # it next to the lockfile's analytic bounds
        self.metrics.incr("engine.rows", n)
        if n == self.device_batch_size:
            return chunk
        self.metrics.incr("engine.pad_rows", self.device_batch_size - n)

        def pad_leaf(a):
            pad = [(0, self.device_batch_size - n)] + [(0, 0)] * (a.ndim - 1)
            return np.pad(a, pad)

        return jax.tree_util.tree_map(pad_leaf, chunk)

    def _trim(self, out, n: int):
        import jax

        def gather(a):
            host = np.asarray(a[:n])
            # cast float->float only: integer/bool leaves (e.g. argmax
            # ids) must never be silently floated.  ml_dtypes narrow
            # floats (bf16/f8) register as kind 'V', not np.floating —
            # but so do genuinely structured/void dtypes, which must
            # pass through untouched, so probe ml_dtypes explicitly.
            src_float = (np.issubdtype(host.dtype, np.floating)
                         or _is_narrow_float(host.dtype))
            if (self.output_host_dtype is not None
                    and host.dtype != self.output_host_dtype
                    and src_float
                    and np.issubdtype(self.output_host_dtype, np.floating)):
                host = host.astype(self.output_host_dtype)
            return host

        return jax.tree_util.tree_map(gather, out)

    @staticmethod
    def _slice(batch, off: int, size: int):
        import jax

        return jax.tree_util.tree_map(lambda a: a[off:off + size], batch)

    # -- whole-array API ---------------------------------------------------
    def __call__(self, batch, window: int = 2,
                 pipeline: Optional[bool] = None,
                 on_metered=None):
        """Process a full batch (array or pytree); returns host output with
        matching row count.

        ``on_metered``, when given, is invoked once per call with the
        metered wall seconds (the same span ``engine_call`` records) —
        the cost ledger's device-time feed.  Per-call rather than
        per-engine so concurrent batches on one shared bucket engine
        each observe their own span.

        Host-memory contract: the pipelined path (``pipeline=True``, the
        ``SPARKDL_PIPELINE`` default) PREALLOCATES the output — the leaf
        output shape is fixed by the single compiled program, so after the
        first gathered chunk the full ``[n, ...]`` result buffer is
        allocated once and every later chunk is copied into it and
        released.  Peak host residency is therefore the output itself plus
        O(window + depth) chunks, never a second whole-output's worth of
        accumulated parts (the serial path concatenates a per-chunk list,
        which transiently doubles the output footprint).  Either way the
        OUTPUT still materializes in host RAM — route multi-million-row
        frames through ``map_batches`` streaming instead.

        Chunks run through the same bounded in-flight window as
        ``map_batches`` (chunk k+1 transfers/computes while chunk k is
        gathered), so device residency is O(window x device_batch) even
        for huge inputs.  Pipelined outputs are bit-identical to serial
        ones (same programs, same pad/trim, same order); inputs that fit
        one device batch skip the worker threads entirely — nothing to
        overlap — so serving-sized calls pay no thread latency.
        """
        import time

        import jax

        batch = jax.tree_util.tree_map(np.asarray, batch)
        n = self._leaves(batch)
        if n == 0:
            raise ValueError("Empty input batch")
        use_pipe = (pipeline_enabled_from_env() if pipeline is None
                    else bool(pipeline))
        t0 = time.perf_counter()
        with get_tracer().span("engine.call", rows=n):
            if not use_pipe or n <= self.device_batch_size:
                outs = list(self.map_batches([batch], window=window,
                                             pipeline=False))
                result = jax.tree_util.tree_map(
                    lambda *parts: np.concatenate(parts, axis=0), *outs)
            else:
                out = None
                off = 0
                for part in self.map_batches([batch], window=window,
                                             pipeline=True):
                    k = self._leaves(part)
                    if out is None:
                        # leaf trailing shapes are fixed by the one
                        # compiled program: preallocate [n, ...] per leaf
                        # and stream chunks straight in
                        out = jax.tree_util.tree_map(
                            lambda a: np.empty((n,) + a.shape[1:], a.dtype),
                            part)
                        self.metrics.incr("engine_call_prealloc")
                    for dst, src in zip(jax.tree_util.tree_leaves(out),
                                        jax.tree_util.tree_leaves(part)):
                        dst[off:off + k] = src
                    off += k
                result = out
        elapsed = time.perf_counter() - t0
        self.metrics.incr("items", n)
        self.metrics.record_time("engine_call", elapsed)
        # unbounded float accumulator (timing series are capped): THE
        # conservation reference the cost ledger's totals are proved
        # against
        self.metrics.incr("engine.device_time_s", elapsed)
        if on_metered is not None:
            on_metered(elapsed)
        return result

    def _stack_group(self, pieces):
        """Host half of a grouped dispatch: pad each of the
        ``batches_per_dispatch`` ``pieces`` and stack them on a leading
        group axis; returns (true_row_counts, stacked_host_batch)."""
        import jax

        ns = tuple(self._leaves(p) for p in pieces)
        stacked = jax.tree_util.tree_map(
            lambda *parts: np.stack(parts, axis=0),
            *[self._pad(p) for p in pieces])
        return ns, stacked

    def _dispatch_group(self, stacked):
        """Device half of a grouped dispatch: H2D transfer + ONE stacked
        lax.map launch; returns the device output."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(self.mesh, P(None, mesh_lib.DATA_AXIS))

        def attempt():
            with get_tracer().span("engine.dispatch",
                                   group=self.batches_per_dispatch):
                return self._compiled_group(self.variables,
                                            jax.device_put(stacked, sh))

        return self._run_dispatch(attempt)

    # -- streaming API -----------------------------------------------------
    def map_batches(self, batches: Iterable[Any], window: int = 2,
                    pipeline: Optional[bool] = None) -> Iterator[Any]:
        """Map over an iterator of host batches with a bounded in-flight
        window (double buffering by default): batch k+1 transfers/computes
        while batch k is gathered.  With ``batches_per_dispatch`` = k > 1
        the in-flight unit is a GROUP of k stacked batches (one launch,
        ONE host fetch per group), so the effective window is scaled to
        ``max(1, window // k)`` groups — peak device residency stays
        O(window x device_batch) in HOST-BATCH terms instead of growing
        ~k-fold with the dispatch grouping.  A ragged tail group runs its
        pieces through the plain per-batch program instead of padding
        with whole zero batches.

        ``pipeline`` (default: the ``SPARKDL_PIPELINE`` env knob, ON)
        runs host prepare, H2D+dispatch, and D2H gather on overlapping
        worker threads (:class:`~sparkdl_tpu.parallel.pipeline.
        PipelinedRunner`): the input iterator — typically the decode
        stage — is pulled on its own thread while the device computes and
        a third thread gathers, with the same bounded window and
        BIT-IDENTICAL outputs.  ``pipeline=False`` (or
        ``SPARKDL_PIPELINE=0``) keeps everything on the calling thread."""
        use_pipe = (pipeline_enabled_from_env() if pipeline is None
                    else bool(pipeline))
        if use_pipe:
            return PipelinedRunner(self, window=window).run(batches)
        return self._map_batches_serial(batches, window)

    def _iter_pieces(self, batches: Iterable[Any]) -> Iterator[tuple]:
        """THE host-prepare sequence, shared verbatim by the serial path
        and the pipelined runner's prepare stage (so their dispatch order
        is identical by construction): slice chunks into device-batch
        pieces and pad them, stacking full ``batches_per_dispatch``
        groups; yields ``("plain", n_rows, padded_piece)`` /
        ``("group", n_rows_tuple, stacked_group)`` in dispatch order.
        The ragged tail group runs its pieces through the plain per-batch
        program instead of padding with whole zero batches."""
        import jax

        group: list = []
        for chunk in batches:
            chunk = jax.tree_util.tree_map(np.asarray, chunk)
            n = self._leaves(chunk)
            for off in range(0, n, self.device_batch_size):
                piece = self._slice(chunk, off, self.device_batch_size)
                if self.batches_per_dispatch == 1:
                    yield ("plain", self._leaves(piece), self._pad(piece))
                else:
                    group.append(piece)
                    if len(group) == self.batches_per_dispatch:
                        yield ("group",) + self._stack_group(group)
                        group = []
        for piece in group:  # ragged tail: plain program, no zero batches
            yield ("plain", self._leaves(piece), self._pad(piece))

    def _map_batches_serial(self, batches: Iterable[Any],
                            window: int = 2) -> Iterator[Any]:
        """The single-threaded path (``SPARKDL_PIPELINE=0``): identical
        piece order and programs, no worker threads."""
        from collections import deque

        if self.batches_per_dispatch > 1:
            window = max(1, int(window) // self.batches_per_dispatch)
        inflight: deque = deque()

        def drain(limit):
            while len(inflight) > limit:
                ns, out = inflight.popleft()
                yield from self._force_parts(ns, out)

        for kind, ns, host in self._iter_pieces(batches):
            inflight.append((ns, self.run_padded(host) if kind == "plain"
                             else self._dispatch_group(host)))
            yield from drain(window)
        yield from drain(0)

    @property
    def num_devices(self) -> int:
        return self.mesh.size


def get_cached_engine(holder, model_function, *, device_batch_size: int,
                      **engine_kwargs) -> InferenceEngine:
    """Engine cache keyed on (model_function, batch) living on ``holder``
    (typically a pipeline stage): repeated ``transform`` calls — e.g. a
    CrossValidator loop — reuse one compiled program and one device copy of
    the weights instead of recompiling per call.

    The cache entry pins the ModelFunction alive so id-keying cannot alias
    a recycled object.
    """
    engine_kwargs.setdefault("batches_per_dispatch",
                             batches_per_dispatch_from_env())
    cache = holder.__dict__.setdefault("_engine_cache", {})
    key = (id(model_function), device_batch_size,
           engine_kwargs["batches_per_dispatch"])
    entry = cache.get(key)
    if entry is None:
        eng = InferenceEngine(model_function.fn, model_function.variables,
                              device_batch_size=device_batch_size,
                              **engine_kwargs)
        cache[key] = (model_function, eng)
        return eng
    return entry[1]
