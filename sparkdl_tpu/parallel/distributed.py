"""Multi-host (multi-controller) scaffolding.

SURVEY.md §2 "distributed communication backend": the reference moved data
with Spark's machinery (torrent broadcast, shuffles); the TPU equivalent is
multi-controller JAX — one process per host, ``jax.distributed`` for
runtime bootstrap, deterministic per-host file sharding instead of a
shuffle, and ``jax.make_array_from_process_local_data`` to assemble global
device arrays from each host's local rows (collectives then ride ICI/DCN
via the mesh).  Everything degrades to a no-op in the common one-process
case, so the same estimator code runs from one chip to a pod slice.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np

from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_INITIALIZED = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               **kwargs) -> bool:
    """Bootstrap multi-controller JAX.  Returns True if ``jax.distributed``
    was initialized, False for the single-process degenerate run (no-op).

    Mirrors ``jax.distributed.initialize`` semantics: all three arguments
    may be None when the environment provides them (TPU pod metadata /
    cluster env vars); an explicit ``num_processes=1`` (or leaving
    everything unset outside a cluster) skips initialization entirely.
    """
    global _INITIALIZED
    import jax

    if _INITIALIZED:
        logger.info("jax.distributed already initialized; skipping")
        return True
    explicit = any(v is not None
                   for v in (coordinator_address, num_processes, process_id))
    if not explicit or num_processes in (0, 1):
        logger.info("single-process run; jax.distributed not initialized")
        return False
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kwargs)
    _INITIALIZED = True
    logger.info("jax.distributed initialized: process %d/%d, %d local / %d "
                "global devices", jax.process_index(), jax.process_count(),
                jax.local_device_count(), jax.device_count())
    return True


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def shard_files(paths: Sequence[str], index: Optional[int] = None,
                count: Optional[int] = None) -> List[str]:
    """Deterministic per-host shard of a file list.

    Sorted then strided (``sorted(paths)[index::count]``): every host
    derives the same global order independently — no coordination, no
    shuffle service — and shard sizes differ by at most one file.  This
    replaces the reference's Spark partition assignment for ingest.
    """
    idx = process_index() if index is None else int(index)
    cnt = process_count() if count is None else int(count)
    if cnt < 1:
        raise ValueError(f"count must be >= 1, got {cnt}")
    if not (0 <= idx < cnt):
        raise ValueError(f"index {idx} out of range for count {cnt}")
    return sorted(paths)[idx::cnt]


def local_batch_size(global_batch_size: int,
                     count: Optional[int] = None) -> int:
    """Rows THIS host contributes per global batch."""
    cnt = process_count() if count is None else int(count)
    if global_batch_size % cnt:
        raise ValueError(
            f"global batch {global_batch_size} is not divisible by "
            f"{cnt} processes")
    return global_batch_size // cnt


def put_sharded(sharding, data: Any):
    """Place a host batch onto devices under ``sharding``.

    Single-process: a plain ``device_put``.  Multi-controller: each process
    passes its LOCAL rows and ``jax.make_array_from_process_local_data``
    assembles the global array (global batch = sum of local rows) — the
    per-host data path SURVEY.md §2 names as the broadcast/shuffle
    replacement.
    """
    import jax

    if jax.process_count() == 1:
        return jax.device_put(data, sharding)
    return jax.tree_util.tree_map(
        lambda a: jax.make_array_from_process_local_data(
            sharding, np.asarray(a)), data)
