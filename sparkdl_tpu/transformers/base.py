"""Stage base classes: Transformer / Estimator / Pipeline.

Re-creates the Spark ML Pipeline stage contract the reference builds every
user-facing class on (``pyspark.ml.Transformer``/``Estimator`` — the
reference's stages in ``python/sparkdl/transformers/`` and
``python/sparkdl/estimators/`` all subclass these), over our Arrow-backed
DataFrame instead of Spark's.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from sparkdl_tpu.param.params import Param, Params, keyword_only


class Transformer(Params):
    """A stage mapping DataFrame -> DataFrame (pyspark.ml.Transformer
    contract: ``transform(dataset, params=None)``)."""

    def transform(self, dataset, params: Optional[Dict] = None):
        if params:
            return self.copy(params).transform(dataset)
        return self._transform(dataset)

    def transformStream(self, batches: Iterable, params: Optional[Dict] = None):
        """Partition-at-a-time transform: lazily map an iterator of Arrow
        ``RecordBatch``es to output ``RecordBatch``es.

        This is the unbounded-dataset path — the analog of the reference's
        per-partition executor loop (SURVEY.md §3.1): each input batch is
        transformed independently and yielded before the next is pulled, so
        peak memory is O(batch), not O(dataset).  Compose with the lazy
        readers (``imageIO.iterFileBatches`` / ``iterImageBatches``) and
        chain stages via ``PipelineModel.transformStream``."""
        if params:
            yield from self.copy(params).transformStream(batches)
            return
        from sparkdl_tpu.frame import DataFrame

        for rb in batches:
            out = self._transform(DataFrame(rb))
            yield from out.table.to_batches()

    def _transform(self, dataset):
        raise NotImplementedError


class Model(Transformer):
    """A fitted Transformer produced by an Estimator."""


class Estimator(Params):
    """A stage learning a Model from a DataFrame (pyspark.ml.Estimator
    contract: ``fit(dataset, params=None)`` where params may be a single
    param map or a list of maps — the latter returns one model per map,
    which is what CrossValidator drives)."""

    def fit(self, dataset, params: Optional[Any] = None):
        if isinstance(params, (list, tuple)):
            return [m for _, m in self.fitMultiple(dataset, list(params))]
        if params:
            return self.copy(params)._fit(dataset)
        return self._fit(dataset)

    def fitMultiple(self, dataset, paramMaps: Sequence[Dict]
                    ) -> Iterable[Tuple[int, Model]]:
        """Yield ``(index, model)`` per param map.  Subclasses override to
        fan out across mesh slices (the reference fanned out one Spark task
        per map — ``keras_image_file_estimator.py — _fitInParallel``)."""
        for i, pm in enumerate(paramMaps):
            yield i, self.copy(pm)._fit(dataset)

    def _fit(self, dataset) -> Model:
        raise NotImplementedError


class PipelineModel(Model):
    """Chain of fitted transformers."""

    def __init__(self, stages: List[Transformer]):
        super().__init__()
        self.stages = list(stages)

    def _transform(self, dataset):
        for stage in self.stages:
            dataset = stage.transform(dataset)
        return dataset

    def transformStream(self, batches, params: Optional[Dict] = None):
        """Lazily chain every stage's ``transformStream``: batch k flows
        through the whole pipeline before batch k+1 is read."""
        if params:
            yield from self.copy(params).transformStream(batches)
            return
        for stage in self.stages:
            batches = stage.transformStream(batches)
        yield from batches

    def _persist(self, path):
        from sparkdl_tpu import persistence

        return {"stages": persistence.save_nested(self.stages, path)}, None, {}

    @classmethod
    def _restore(cls, extra, pytree, pickles, path):
        from sparkdl_tpu import persistence

        return cls(persistence.load_nested(path, extra["stages"]))


class Pipeline(Estimator):
    """Sequential pipeline of stages (pyspark.ml.Pipeline semantics: fitting
    runs estimators in order, feeding each stage the output of the previous
    fitted prefix)."""

    stages = Param("undefined", "stages", "pipeline stages (in order)")

    @keyword_only
    def __init__(self, stages: Optional[List] = None):
        super().__init__()
        self._set(**self._input_kwargs)

    def setStages(self, value: List):
        return self._set(stages=value)

    def getStages(self) -> List:
        return self.getOrDefault(self.stages)

    def _fit(self, dataset) -> PipelineModel:
        fitted: List[Transformer] = []
        stages = self.getStages()
        # Transformers after the last estimator need no data pass.
        last_est = max((i for i, s in enumerate(stages)
                        if isinstance(s, Estimator)), default=-1)
        for i, stage in enumerate(stages):
            if isinstance(stage, Transformer):
                fitted.append(stage)
                if i <= last_est:
                    dataset = stage.transform(dataset)
            elif isinstance(stage, Estimator):
                model = stage.fit(dataset)
                fitted.append(model)
                if i < last_est:
                    dataset = model.transform(dataset)
            else:
                raise TypeError(
                    f"Pipeline stage {i} is neither Transformer nor "
                    f"Estimator: {type(stage).__name__}")
        return PipelineModel(fitted)
