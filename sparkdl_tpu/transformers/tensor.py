"""Tensor-column transformers.

Replaces ``python/sparkdl/transformers/tf_tensor.py`` (C5 ``TFTransformer``)
and ``keras_tensor.py`` (C6 ``KerasTransformer``): applying a model to
numeric/array columns.  The reference froze a TF graph and ran it blockwise
through TensorFrames; here the model is a :class:`ModelFunction` jitted over
the mesh.

  * :class:`ModelTransformer` — the native stage: ModelFunction over one
    array column.
  * :class:`KerasTransformer` — loads a user Keras model (file or object),
    converts it to a ModelFunction (graph.keras_convert), then behaves like
    ModelTransformer.  Input rows are 1-D float arrays (reference contract).
  * :class:`TFTransformer` — multi-input/multi-output mapping form: a
    TFInputGraph/ModelFunction plus {column->input} / {output->column}
    maps (reference's feed/fetch wiring).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa

from sparkdl_tpu.param.converters import SparkDLTypeConverters
from sparkdl_tpu.param.params import Param, keyword_only
from sparkdl_tpu.param.shared import HasBatchSize, HasInputCol, HasOutputCol
from sparkdl_tpu.parallel.engine import get_cached_engine
from sparkdl_tpu.persistence import PersistableModelFunctionMixin
from sparkdl_tpu.transformers.base import Transformer


def _rows_to_list_array(mat: np.ndarray) -> pa.Array:
    mat = np.asarray(mat)
    flat = mat.reshape(mat.shape[0], -1).astype(np.float32)
    return pa.array([[float(v) for v in row] for row in flat],
                    type=pa.list_(pa.float32()))


class ModelTransformer(PersistableModelFunctionMixin, Transformer,
                       HasInputCol, HasOutputCol, HasBatchSize):
    """Apply a ModelFunction to an array column (one row = one example)."""

    modelFunction = Param(
        "undefined", "modelFunction",
        "ModelFunction applied to the stacked input column",
        typeConverter=SparkDLTypeConverters.toModelFunction)

    @keyword_only
    def __init__(self, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelFunction=None,
                 batchSize: Optional[int] = None):
        super().__init__()
        self._setDefault(batchSize=64)
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  modelFunction=None,
                  batchSize: Optional[int] = None):
        return self._set(**self._input_kwargs)

    def getModelFunction(self):
        return self.getOrDefault(self.modelFunction)

    def _transform(self, dataset):
        x = dataset.column_to_numpy(self.getInputCol()).astype(np.float32)
        mf = self.getModelFunction()
        eng = get_cached_engine(self, mf, device_batch_size=self.getBatchSize())
        out = eng(x)
        return dataset.withColumn(self.getOutputCol(), _rows_to_list_array(out))


class KerasTransformer(ModelTransformer):
    """Apply a user Keras model to a column of 1-D float arrays.

    Counterpart of the reference's ``KerasTransformer``
    (``keras_tensor.py``): ``modelFile`` points at a saved Keras model
    (HDF5/.keras); it is converted once to a jax ModelFunction at first
    transform.
    """

    modelFile = Param(
        "undefined", "modelFile",
        "path to a saved Keras model (.h5/.keras) applied row-wise")

    @keyword_only
    def __init__(self, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelFile: Optional[str] = None,
                 batchSize: Optional[int] = None):
        # Note: bypasses ModelTransformer.__init__ (keyword_only stashing
        # composes badly across two levels); Params init + own defaults.
        Transformer.__init__(self)
        self._setDefault(batchSize=64)
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  modelFile: Optional[str] = None,
                  batchSize: Optional[int] = None):
        return self._set(**self._input_kwargs)

    def getModelFile(self):
        return self.getOrDefault(self.modelFile)

    def getModelFunction(self):
        if not self.isSet(self.modelFunction):
            from sparkdl_tpu.graph.function import ModelFunction

            mf = ModelFunction.from_keras(self.getModelFile())
            self._set(modelFunction=mf)
        return self.getOrDefault(self.modelFunction)


class TFTransformer(Transformer, HasBatchSize):
    """Mapping form: model with named inputs/outputs over several columns.

    Counterpart of the reference's ``TFTransformer`` (C5): ``inputMapping``
    = {column name -> model input name}, ``outputMapping`` = {model output
    name -> new column name}.  The model is a :class:`ModelFunction` whose
    ``fn(variables, x)`` takes a dict of arrays keyed by input name and
    returns a dict keyed by output name (exactly what
    ``TFInputGraph``-imported graphs produce).
    """

    modelFunction = Param(
        "undefined", "modelFunction",
        "ModelFunction taking/returning dicts keyed by input/output names",
        typeConverter=SparkDLTypeConverters.toModelFunction)

    inputMapping = Param(
        "undefined", "inputMapping", "{column -> model input name}",
        typeConverter=SparkDLTypeConverters.toColumnToTensorMap)

    outputMapping = Param(
        "undefined", "outputMapping", "{model output name -> column}",
        typeConverter=SparkDLTypeConverters.toColumnToTensorMap)

    @keyword_only
    def __init__(self, modelFunction=None,
                 inputMapping: Optional[Dict[str, str]] = None,
                 outputMapping: Optional[Dict[str, str]] = None,
                 batchSize: Optional[int] = None):
        super().__init__()
        self._setDefault(batchSize=64)
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, modelFunction=None,
                  inputMapping: Optional[Dict[str, str]] = None,
                  outputMapping: Optional[Dict[str, str]] = None,
                  batchSize: Optional[int] = None):
        return self._set(**self._input_kwargs)

    def getModelFunction(self):
        return self.getOrDefault(self.modelFunction)

    def getInputMapping(self) -> Dict[str, str]:
        return self.getOrDefault(self.inputMapping)

    def getOutputMapping(self) -> Dict[str, str]:
        return self.getOrDefault(self.outputMapping)

    def _transform(self, dataset):
        mf = self.getModelFunction()
        in_map = self.getInputMapping()
        out_map = self.getOutputMapping()
        missing = set(in_map.values()) - set(mf.input_names)
        if missing:
            raise ValueError(
                f"inputMapping refers to unknown model inputs {sorted(missing)}; "
                f"model has {list(mf.input_names)}")
        missing = set(out_map) - set(mf.output_names)
        if missing:
            raise ValueError(
                f"outputMapping refers to unknown model outputs "
                f"{sorted(missing)}; model has {list(mf.output_names)}")
        x = {
            input_name: dataset.column_to_numpy(col).astype(np.float32)
            for col, input_name in in_map.items()
        }
        eng = get_cached_engine(self, mf, device_batch_size=self.getBatchSize())
        out = eng(x)
        if not isinstance(out, dict):
            out = {mf.output_names[0]: out}
        for output_name, col in out_map.items():
            dataset = dataset.withColumn(
                col, _rows_to_list_array(out[output_name]))
        return dataset
