"""URI-column transformers with a user image loader.

Replaces ``python/sparkdl/transformers/keras_image.py`` (C6
``KerasImageFileTransformer`` + ``CanLoadImage`` mixin): the stage reads a
column of file URIs, runs the user's ``imageLoader`` (decode + model-specific
preprocessing, ``uri -> [H,W,C] float array``) on the host, and feeds the
stacked batch to the model on the mesh.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
import pyarrow as pa

from sparkdl_tpu.image.io import _io_executor
from sparkdl_tpu.param.converters import SparkDLTypeConverters
from sparkdl_tpu.param.params import Param, keyword_only
from sparkdl_tpu.param.shared import (CanLoadImage, HasBatchSize, HasInputCol,
                                      HasOutputCol)
from sparkdl_tpu.parallel.engine import get_cached_engine
from sparkdl_tpu.persistence import PersistableModelFunctionMixin
from sparkdl_tpu.transformers.base import Transformer
from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class ImageFileTransformer(PersistableModelFunctionMixin, Transformer,
                           HasInputCol, HasOutputCol,
                           HasBatchSize, CanLoadImage):
    """Apply a ModelFunction to images loaded from a URI column via the
    user's ``imageLoader``.  Rows whose loader raises or returns None become
    null outputs (the imageIO drop-to-null contract)."""

    modelFunction = Param(
        "undefined", "modelFunction",
        "ModelFunction applied to the stacked loaded-image batch",
        typeConverter=SparkDLTypeConverters.toModelFunction)

    @keyword_only
    def __init__(self, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelFunction=None,
                 imageLoader=None,
                 batchSize: Optional[int] = None):
        super().__init__()
        self._setDefault(batchSize=64)
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  modelFunction=None,
                  imageLoader=None,
                  batchSize: Optional[int] = None):
        return self._set(**self._input_kwargs)

    def getModelFunction(self):
        return self.getOrDefault(self.modelFunction)

    def _safe_loader(self):
        loader = self.getImageLoader()

        def safe_load(uri):
            if uri is None:
                return None
            try:
                arr = loader(uri)
                return None if arr is None else np.asarray(arr)
            except Exception as e:
                logger.warning("imageLoader failed for %r: %s", uri, e)
                return None

        return safe_load

    def _loaded_chunks(self, dataset, chunk_rows: int, valid_idx: List[int]):
        """Generator of stacked float32 chunks over URIs whose load
        succeeded.  Reads + decodes one record batch of files at a time (on
        the shared host-IO pool) — the whole dataset's pixels never coexist
        in memory; appends global indices of loadable rows to ``valid_idx``
        as a side effect."""
        safe_load = self._safe_loader()
        col_idx = dataset.table.column_names.index(self.getInputCol())
        offset = 0
        for rb in dataset.iter_batches(chunk_rows):
            uris = rb.column(col_idx).to_pylist()
            arrays = list(_io_executor().map(safe_load, uris))
            vi_local = [i for i, a in enumerate(arrays) if a is not None]
            if vi_local:
                valid_idx.extend(offset + i for i in vi_local)
                yield np.stack(
                    [arrays[i] for i in vi_local]).astype(np.float32)
            offset += len(uris)

    def _transform(self, dataset):
        from itertools import chain

        from sparkdl_tpu.parallel.pipeline import pipeline_enabled_from_env
        from sparkdl_tpu.utils.prefetch import prefetch_iter

        valid_idx: List[int] = []
        chunks = self._loaded_chunks(dataset, max(1, self.getBatchSize()),
                                     valid_idx)
        # under the pipelined engine its prepare thread pulls the loader
        # iterator; the explicit prefetch hop is the serial fallback's
        it = (iter(chunks) if pipeline_enabled_from_env()
              else prefetch_iter(chunks, depth=2))
        first = next(it, None)
        outs = []
        if first is not None:
            import time

            # Engine (weight load + compile) only once a chunk proves
            # there's work to do.
            eng = get_cached_engine(self, self.getModelFunction(),
                                    device_batch_size=self.getBatchSize())
            t0 = time.perf_counter()
            outs = list(eng.map_batches(chain([first], it)))
            elapsed = time.perf_counter() - t0
            k, ndev = len(valid_idx), eng.num_devices
            ips = k / elapsed if elapsed > 0 else float("inf")
            logger.info("%s: %d images in %.3fs — %.1f img/s "
                        "(%.1f img/s/chip over %d devices)",
                        type(self).__name__, k, elapsed, ips, ips / ndev,
                        ndev)
        n = len(dataset)
        values: List[Optional[list]] = [None] * n
        if outs:
            out = np.concatenate([np.asarray(o) for o in outs], axis=0)
            flat = out.reshape(out.shape[0], -1).astype(np.float32)
            for row, i in zip(flat, valid_idx):
                values[i] = [float(v) for v in row]
        else:
            logger.warning("imageLoader produced no usable images out of %d "
                           "URIs; output column is all null", n)
        return dataset.withColumn(
            self.getOutputCol(), pa.array(values, type=pa.list_(pa.float32())))


class KerasImageFileTransformer(ImageFileTransformer):
    """The Keras-model flavor: ``modelFile`` (.h5/.keras) is converted to a
    ModelFunction on first use — reference's ``KerasImageFileTransformer``."""

    modelFile = Param(
        "undefined", "modelFile",
        "path to a saved Keras model applied to the loaded images")

    @keyword_only
    def __init__(self, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelFile: Optional[str] = None,
                 imageLoader=None,
                 batchSize: Optional[int] = None):
        Transformer.__init__(self)
        self._setDefault(batchSize=64)
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  modelFile: Optional[str] = None,
                  imageLoader=None,
                  batchSize: Optional[int] = None):
        return self._set(**self._input_kwargs)

    def getModelFile(self):
        return self.getOrDefault(self.modelFile)

    def getModelFunction(self):
        if not self.isSet(self.modelFunction):
            from sparkdl_tpu.graph.function import ModelFunction

            self._set(modelFunction=ModelFunction.from_keras(self.getModelFile()))
        return self.getOrDefault(self.modelFunction)
