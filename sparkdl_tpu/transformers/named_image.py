"""Named pretrained-model transformers.

Replaces ``python/sparkdl/transformers/named_image.py`` (C3:
``DeepImagePredictor``, ``DeepImageFeaturizer``, ``_NamedImageTransformer``)
and the Scala fast path (C13 ``DeepImageFeaturizer.scala``): zoo-model
inference over an image-struct column.  The reference's two execution paths
(Python tf.Session vs. Scala TensorFrames) collapse into one: a
jit-compiled, mesh-sharded XLA program (parallel.engine).

Also hosts :class:`TFImageTransformer` — the arbitrary-model-over-images
stage (C4 ``tf_image.py``), which here takes a :class:`ModelFunction`
instead of a TF graph.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from sparkdl_tpu.image.io import structsToBatch
from sparkdl_tpu.image.schema import imageArrayToStruct, imageSchema
from sparkdl_tpu.models import get_model_spec, load_model
from sparkdl_tpu.models.imagenet import decode_predictions
from sparkdl_tpu.param.converters import SparkDLTypeConverters
from sparkdl_tpu.param.params import Param, TypeConverters, keyword_only
from sparkdl_tpu.param.shared import (HasBatchSize, HasInputCol, HasModelName,
                                      HasOutputCol, HasOutputMode, HasTopK)
from sparkdl_tpu.parallel.engine import InferenceEngine, get_cached_engine
from sparkdl_tpu.transformers.base import Transformer
from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Process-wide caches: zoo weights load once, engines compile once per
# (model, purpose, batch).  The analog of the reference broadcasting one
# GraphDef per stage rather than per partition.
_MODEL_CACHE: Dict[str, tuple] = {}
_ENGINE_CACHE: Dict[tuple, InferenceEngine] = {}


def clear_model_caches():
    _MODEL_CACHE.clear()
    _ENGINE_CACHE.clear()


def _cached_model(name: str):
    if name not in _MODEL_CACHE:
        _MODEL_CACHE[name] = load_model(name)
    return _MODEL_CACHE[name]


def _zoo_engine(name: str, featurize: bool, batch_size: int) -> InferenceEngine:
    key = (name, featurize, batch_size)
    eng = _ENGINE_CACHE.get(key)
    if eng is None:
        module, variables = _cached_model(name)
        spec = get_model_spec(name)
        pre = spec.preprocess

        def fn(v, x):  # x: uint8 RGB [B,H,W,3]
            return module.apply(v, pre(x), train=False, features=featurize)

        eng = InferenceEngine(fn, variables, device_batch_size=batch_size)
        _ENGINE_CACHE[key] = eng
    return eng


def _float_list_array(mat: np.ndarray, valid_idx: Sequence[int],
                      num_rows: int) -> pa.Array:
    """Rows of ``mat`` at positions ``valid_idx``; nulls elsewhere."""
    values: List[Optional[list]] = [None] * num_rows
    for row, i in zip(mat, valid_idx):
        values[i] = [float(v) for v in row]
    return pa.array(values, type=pa.list_(pa.float32()))


class _ImageInputStage(Transformer, HasInputCol, HasOutputCol, HasBatchSize):
    """Shared plumbing: pull the image-struct column, decode/resize valid
    rows into a dense batch, keep nulls aligned (undecodable rows stay null
    — the reference's imageIO drops-to-null contract)."""

    def _image_rows(self, dataset):
        col = dataset.table.column(self.getInputCol())
        structs = col.to_pylist()
        valid_idx = [i for i, s in enumerate(structs) if s is not None]
        return structs, valid_idx

    def _batch_for(self, structs, valid_idx, height: int, width: int):
        return structsToBatch([structs[i] for i in valid_idx], height, width)


class _NamedImageTransformer(_ImageInputStage, HasModelName):
    """Base of the zoo stages — resolves modelName against the registry
    (same role as the reference's ``SUPPORTED_MODELS`` lookup)."""

    featurize: bool = False

    def __init__(self):
        super().__init__()
        from sparkdl_tpu.models import SUPPORTED_MODELS

        self.modelName.typeConverter = SparkDLTypeConverters.supportedNameConverter(
            SUPPORTED_MODELS)
        self._setDefault(batchSize=64)

    def _run_model(self, dataset) -> Tuple[np.ndarray, list, int]:
        name = self.getModelName()
        spec = get_model_spec(name)
        structs, valid_idx = self._image_rows(dataset)
        h, w = spec.input_size
        batch = self._batch_for(structs, valid_idx, h, w)
        if len(valid_idx) == 0:
            dim = spec.feature_size if self.featurize else 1000
            return np.zeros((0, dim), np.float32), valid_idx, len(structs)
        eng = _zoo_engine(name, self.featurize, self.getBatchSize())
        out = eng(batch)
        return np.asarray(out), valid_idx, len(structs)


class DeepImageFeaturizer(_NamedImageTransformer):
    """Zoo-model featurization for transfer learning.

    Counterpart of the reference's ``DeepImageFeaturizer`` (Python wrapper +
    Scala implementation): output column holds the penultimate-layer vector
    (e.g. 2048-d for InceptionV3), ready for any downstream classifier.
    """

    featurize = True

    @keyword_only
    def __init__(self, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelName: Optional[str] = None,
                 batchSize: Optional[int] = None):
        super().__init__()
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  modelName: Optional[str] = None,
                  batchSize: Optional[int] = None):
        return self._set(**self._input_kwargs)

    def _transform(self, dataset):
        feats, valid_idx, n = self._run_model(dataset)
        return dataset.withColumn(
            self.getOutputCol(), _float_list_array(feats, valid_idx, n))


class DeepImagePredictor(_NamedImageTransformer):
    """Zoo-model prediction.

    Counterpart of the reference's ``DeepImagePredictor``: class
    probabilities, optionally decoded to top-K ``(class, description,
    probability)`` structs (``_decodeOutputAsPredictions``).
    """

    featurize = False

    decodePredictions = Param(
        "undefined", "decodePredictions",
        "decode the output probabilities into top-K (class, description, "
        "probability) rows", typeConverter=TypeConverters.toBoolean)

    topK = HasTopK.topK

    @keyword_only
    def __init__(self, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelName: Optional[str] = None,
                 decodePredictions: bool = False,
                 topK: int = 5,
                 batchSize: Optional[int] = None):
        super().__init__()
        self._setDefault(decodePredictions=False, topK=5)
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  modelName: Optional[str] = None,
                  decodePredictions: Optional[bool] = None,
                  topK: Optional[int] = None,
                  batchSize: Optional[int] = None):
        return self._set(**self._input_kwargs)

    def getDecodePredictions(self):
        return self.getOrDefault(self.decodePredictions)

    def getTopK(self):
        return self.getOrDefault(self.topK)

    def _transform(self, dataset):
        probs, valid_idx, n = self._run_model(dataset)
        out_col = self.getOutputCol()
        if not self.getDecodePredictions():
            return dataset.withColumn(
                out_col, _float_list_array(probs, valid_idx, n))
        decoded = decode_predictions(probs, top=self.getTopK())
        pred_type = pa.list_(pa.struct([
            pa.field("class", pa.string()),
            pa.field("description", pa.string()),
            pa.field("probability", pa.float32()),
        ]))
        values: List[Optional[list]] = [None] * n
        for row, i in zip(decoded, valid_idx):
            values[i] = [
                {"class": c, "description": d, "probability": p}
                for c, d, p in row]
        return dataset.withColumn(out_col, pa.array(values, type=pred_type))


class TFImageTransformer(_ImageInputStage, HasOutputMode):
    """Arbitrary model over the image column.

    Counterpart of the reference's ``TFImageTransformer`` (C4): where that
    shipped a merged GraphDef (image-converter subgraph ∘ user graph) to
    TensorFrames, this applies a user :class:`ModelFunction` to the decoded
    uint8 RGB batch inside one jit program.  ``outputMode="vector"`` emits a
    flat float vector per row; ``"image"`` re-packs a [H,W,3] float output
    as an image struct.
    """

    modelFunction = Param(
        "undefined", "modelFunction",
        "ModelFunction applied to the decoded [B,H,W,3] uint8 RGB batch",
        typeConverter=SparkDLTypeConverters.toModelFunction)

    inputSize = Param(
        "undefined", "inputSize",
        "[height, width] the images are resized to before the model; "
        "defaults to the first row's stored size",
        typeConverter=TypeConverters.toList)

    @keyword_only
    def __init__(self, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelFunction=None,
                 inputSize: Optional[Sequence[int]] = None,
                 outputMode: str = "vector",
                 batchSize: Optional[int] = None):
        super().__init__()
        self._setDefault(outputMode="vector", batchSize=64)
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  modelFunction=None,
                  inputSize: Optional[Sequence[int]] = None,
                  outputMode: Optional[str] = None,
                  batchSize: Optional[int] = None):
        return self._set(**self._input_kwargs)

    def getModelFunction(self):
        return self.getOrDefault(self.modelFunction)

    def _transform(self, dataset):
        structs, valid_idx = self._image_rows(dataset)
        if not valid_idx:
            raise ValueError(
                f"No decodable images in column {self.getInputCol()!r}")
        if self.isDefined(self.inputSize):
            h, w = (int(v) for v in self.getOrDefault(self.inputSize))
        else:
            first = structs[valid_idx[0]]
            h, w = int(first["height"]), int(first["width"])
        batch = self._batch_for(structs, valid_idx, h, w)
        mf = self.getModelFunction()
        eng = get_cached_engine(self, mf, device_batch_size=self.getBatchSize())
        out = np.asarray(eng(batch))
        n = len(structs)
        mode = self.getOutputMode()
        if mode == "vector":
            flat = out.reshape(out.shape[0], -1).astype(np.float32)
            return dataset.withColumn(
                self.getOutputCol(), _float_list_array(flat, valid_idx, n))
        # image mode: each output row must be [B,H,W,C]
        if out.ndim != 4:
            raise ValueError(
                f'outputMode="image" needs [B,H,W,C] model output, got '
                f"shape {out.shape}")
        values: List[Optional[dict]] = [None] * n
        for row, i in zip(out, valid_idx):
            origin = structs[i].get("origin", "") if structs[i] else ""
            if row.shape[-1] == 3:
                row = row[:, :, ::-1]  # model RGB -> struct BGR convention
            elif row.shape[-1] == 4:
                # RGBA -> BGRA: flip only the color channels, keep alpha last
                # (the CV_8UC4/CV_32FC4 struct convention).
                row = row[:, :, [2, 1, 0, 3]]
            values[i] = imageArrayToStruct(
                np.ascontiguousarray(row, dtype=np.float32), origin=origin)
        return dataset.withColumn(
            self.getOutputCol(), pa.array(values, type=imageSchema))
