"""Named pretrained-model transformers.

Replaces ``python/sparkdl/transformers/named_image.py`` (C3:
``DeepImagePredictor``, ``DeepImageFeaturizer``, ``_NamedImageTransformer``)
and the Scala fast path (C13 ``DeepImageFeaturizer.scala``): zoo-model
inference over an image-struct column.  The reference's two execution paths
(Python tf.Session vs. Scala TensorFrames) collapse into one: a
jit-compiled, mesh-sharded XLA program (parallel.engine).

Also hosts :class:`TFImageTransformer` — the arbitrary-model-over-images
stage (C4 ``tf_image.py``), which here takes a :class:`ModelFunction`
instead of a TF graph.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from sparkdl_tpu.image.io import arrowStructsToBatch
from sparkdl_tpu.image.schema import imageArrayToStruct, imageSchema
from sparkdl_tpu.models import get_model_spec, load_model, model_variant_key
from sparkdl_tpu.models.imagenet import decode_predictions
from sparkdl_tpu.param.converters import SparkDLTypeConverters
from sparkdl_tpu.param.params import Param, TypeConverters, keyword_only
from sparkdl_tpu.param.shared import (HasBatchSize, HasInputCol, HasModelName,
                                      HasOutputCol, HasOutputMode, HasTopK)
from sparkdl_tpu.parallel.engine import (InferenceEngine,
                                         batches_per_dispatch_from_env,
                                         get_cached_engine)
from sparkdl_tpu.persistence import PersistableModelFunctionMixin
from sparkdl_tpu.transformers.base import Transformer
from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Process-wide caches: zoo weights load once, engines compile once per
# (model, purpose, batch).  The analog of the reference broadcasting one
# GraphDef per stage rather than per partition.
_MODEL_CACHE: Dict[tuple, tuple] = {}
_ENGINE_CACHE: Dict[tuple, InferenceEngine] = {}


def clear_model_caches():
    _MODEL_CACHE.clear()
    _ENGINE_CACHE.clear()


def _cached_model(name: str):
    # key includes the env-dependent build variant (e.g. SPARKDL_S2D_STEM)
    # so toggling the knob mid-process rebuilds instead of serving stale
    key = (name, model_variant_key(name))
    if key not in _MODEL_CACHE:
        _MODEL_CACHE[key] = load_model(name)
    return _MODEL_CACHE[key]


def zoo_compute_dtype_name() -> str:
    """Canonicalized ``SPARKDL_ZOO_COMPUTE_DTYPE`` ("float32" or
    "bfloat16"); raises on unsupported values.  One parser for the engine
    cache key, the serving resolver, and the program auditor — the
    declared compute dtype graftcheck GC002 enforces must be read the
    same way everywhere."""
    import os

    cdt_name = os.environ.get("SPARKDL_ZOO_COMPUTE_DTYPE", "").lower()
    if cdt_name not in ("", "float32", "f32", "bfloat16", "bf16"):
        raise ValueError(
            f"SPARKDL_ZOO_COMPUTE_DTYPE={cdt_name!r} not supported; use "
            f"'bfloat16' or 'float32'")
    return {"bf16": "bfloat16", "f32": "float32", "": "float32"}.get(
        cdt_name, cdt_name)


def zoo_model_fn(name: str, featurize: bool, compute_dtype=None,
                 module=None):
    """THE ``fn(variables, x)`` the zoo engine jit-compiles: fused
    preprocess, optional cast to the compute dtype, inference-mode apply
    at the featurizer or predictor cut.  ``module`` defaults to a fresh
    ``spec.build()`` — the program auditor (``analysis.program``) builds
    the fn this way with abstract variables (no weights, no device), so
    the audited program is the served program by construction."""
    spec = get_model_spec(name)
    if module is None:
        module = spec.build()
    pre = spec.preprocess
    cdt = compute_dtype

    def fn(v, x):  # x: uint8 RGB [B,H,W,3]
        xf = pre(x)
        if cdt is not None:
            xf = xf.astype(cdt)
        return module.apply(v, xf, train=False, features=featurize)

    return fn


def zoo_serving_bundle(name: str, featurize: bool,
                       feature_cut: bool = False):
    """``(fn, variables, engine_overrides)`` for serving zoo model
    ``name`` — THE zoo resolution the online stack shares: weights via
    the process cache, the fn through :func:`zoo_model_fn` (so served ==
    transformed == audited stays true by construction), and the
    ``SPARKDL_ZOO_COMPUTE_DTYPE`` contract as engine overrides (bf16
    compute + f32 host cast under the bench configuration).  Used by
    ``serving.server._resolve_model`` and the fleet model registry
    (``serving.fleet.registry``); the registry resolves ONCE per entry
    and reuses the fn across versions, which is what lets a hot-swapped
    version reuse the compiled executable instead of re-jitting.

    ``feature_cut=True`` (head fan-out tier, ISSUE 17) instead returns
    the SPLIT bundle ``(backbone_fn, variables, engine_overrides,
    head_fn)``: ``backbone_fn`` is the featurizer-cut fn — the exact
    object the featurize programs in ``PROGRAMS.lock.json`` pin, built
    through the same :func:`zoo_model_fn` path, so backbone identity
    (jit object + StableHLO fingerprint) can NEVER change when tenant
    heads churn — and ``head_fn`` is the canonical per-row head
    (``parallel.engine.dense_head_row``) the vmapped
    ``build_head_fanout_jit`` program serves over a
    :class:`~sparkdl_tpu.parallel.engine.HeadBank`."""
    module, zoo_vars = _cached_model(name)
    cdt = None
    # GC001's recorded zoo exemption, enforced where the engines are
    # built: the uint8 image batch can never alias the float feature
    # output, so declaring the donation would only make XLA drop it —
    # the serving auto-donation probe must not even try
    # (analysis.program.inventory.ZOO_DONATE_REASON).
    # Weight sharding (ISSUE 14): the zoo family's default partition
    # rules ride the overrides — flax kernels split their output dim
    # across the mesh's model axis when it is >1 (per-chip HBM =
    # bytes/model_axis), and resolve all-replicated (byte-identical
    # programs) on the model-axis-1 meshes every current zoo config
    # uses.  An explicit Server partition_rules/param_shardings wins.
    from sparkdl_tpu.parallel import mesh as _mesh_lib

    overrides: Dict[str, object] = {
        "donate_batch": False,
        "partition_rules": _mesh_lib.default_partition_rules,
    }
    if zoo_compute_dtype_name() == "bfloat16":
        import jax.numpy as jnp

        cdt = jnp.bfloat16
        overrides.update({"compute_dtype": jnp.bfloat16,
                          "output_host_dtype": np.float32})
    if feature_cut and not featurize:
        raise ValueError(
            "feature_cut=True requires featurize=True: the split's "
            "backbone program IS the featurizer cut (the head fan-out "
            "tier has no predictor-cut backbone)")
    fn = zoo_model_fn(name, featurize=featurize, compute_dtype=cdt,
                      module=module)
    if feature_cut:
        from sparkdl_tpu.parallel.engine import dense_head_row

        return fn, zoo_vars, overrides, dense_head_row
    return fn, zoo_vars, overrides


def _zoo_engine(name: str, featurize: bool, batch_size: int) -> InferenceEngine:
    """One cached engine per (model, cut, batch).

    ``SPARKDL_ZOO_COMPUTE_DTYPE=bfloat16`` runs the zoo model in bf16 (the
    bench's configuration: ~MXU-native, and outputs are fetched in bf16
    then cast to f32 on the HOST — bit-identical features, half the D2H
    bytes).  Default stays float32: the reference's scoring contract is
    f32 end-to-end and the parity oracles are f32.
    """
    cdt_name = zoo_compute_dtype_name()
    bpd = batches_per_dispatch_from_env()
    key = (name, model_variant_key(name), featurize, batch_size, cdt_name,
           bpd)
    eng = _ENGINE_CACHE.get(key)
    if eng is None:
        import jax.numpy as jnp

        module, variables = _cached_model(name)
        cdt = jnp.bfloat16 if cdt_name == "bfloat16" else None
        fn = zoo_model_fn(name, featurize, compute_dtype=cdt, module=module)
        eng = InferenceEngine(
            fn, variables, device_batch_size=batch_size,
            compute_dtype=cdt,
            batches_per_dispatch=bpd,
            output_host_dtype=np.float32 if cdt is not None else None)
        _ENGINE_CACHE[key] = eng
    return eng


def _float_list_array(mat: np.ndarray, valid_idx: Sequence[int],
                      num_rows: int) -> pa.Array:
    """Rows of ``mat`` at positions ``valid_idx``; nulls elsewhere."""
    values: List[Optional[list]] = [None] * num_rows
    for row, i in zip(mat, valid_idx):
        values[i] = [float(v) for v in row]
    return pa.array(values, type=pa.list_(pa.float32()))


class _ImageInputStage(Transformer, HasInputCol, HasOutputCol, HasBatchSize):
    """Shared plumbing: pull the image-struct column, decode/resize valid
    rows into dense batches, keep nulls aligned (undecodable rows stay null
    — the reference's imageIO drops-to-null contract).

    The decode is STREAMING: the column is consumed one record batch at a
    time (the analog of the reference's per-partition hot loop, SURVEY.md
    §3.1) — at no point does a whole-dataset ``[N,H,W,3]`` array exist.
    Host decode of chunk k+1 runs on a prefetch thread while the device
    computes chunk k, and the engine bounds in-flight device buffers."""

    def _first_valid_struct(self, dataset) -> Optional[dict]:
        """First non-null image struct, without materializing the column."""
        col_idx = dataset.table.column_names.index(self.getInputCol())
        for rb in dataset.iter_batches(64):
            for s in rb.column(col_idx).to_pylist():
                if s is not None:
                    return s
        return None

    def _decoded_chunks(self, dataset, height: int, width: int,
                        chunk_rows: int, valid_idx: List[int],
                        origins: Optional[List[str]] = None):
        """Generator of decoded [b,h,w,3] uint8 RGB chunks over valid rows.

        Side effects as it advances: appends the global row index of each
        valid row to ``valid_idx`` (and its origin to ``origins`` if given)
        so the caller can re-align outputs with null rows after the stream
        is drained."""
        name = self.getInputCol()
        col_idx = dataset.table.column_names.index(name)
        offset = 0
        for rb in dataset.iter_batches(chunk_rows):
            col = rb.column(col_idx)
            # zero-copy struct packing (no per-row dict materialization);
            # compact=True: the batch holds only the decodable rows
            batch, ok = arrowStructsToBatch(col, height, width,
                                            compact=True)
            vi_local = np.nonzero(ok)[0]
            if len(vi_local):
                valid_idx.extend(int(offset + i) for i in vi_local)
                if origins is not None:
                    ocol = col.field("origin")
                    origins.extend(
                        (ocol[int(i)].as_py() or "") for i in vi_local)
                yield batch
            offset += len(col)

    def _chunk_rows(self) -> int:
        """Decode granularity: batchSize rounded up to the data-axis size,
        computed WITHOUT building an engine (mesh construction is cheap;
        engine construction loads weights and compiles)."""
        from sparkdl_tpu.parallel import mesh as mesh_lib

        dp = mesh_lib.get_mesh().shape[mesh_lib.DATA_AXIS]
        b = max(1, int(self.getBatchSize()))
        return b + (-b % dp)

    def _stream_model_outputs(self, dataset, engine_factory, height: int,
                              width: int, valid_idx: List[int],
                              origins: Optional[List[str]] = None):
        """Lazily yield per-chunk model outputs for the image column.

        Fills ``valid_idx`` (and ``origins``) as a side effect; yields
        nothing when no row decodes.  The engine (weights + compile) is
        only built once the first decoded chunk proves there is work to
        do.  Consumers that pack outputs incrementally (image mode) keep
        peak host residency at O(chunk), not O(dataset).

        Decode/compute overlap: under the default pipelined engine
        (``SPARKDL_PIPELINE``) the runner's own prepare thread pulls the
        decode iterator while the device computes and a gather thread
        fetches — wrapping the decode in ``prefetch_iter`` too would only
        add a queue hop, so the explicit prefetch is reserved for the
        serial escape hatch."""
        from itertools import chain

        import time

        from sparkdl_tpu.parallel.pipeline import pipeline_enabled_from_env
        from sparkdl_tpu.utils.prefetch import prefetch_iter

        chunks = self._decoded_chunks(
            dataset, height, width, self._chunk_rows(), valid_idx, origins)
        it = (iter(chunks) if pipeline_enabled_from_env()
              else prefetch_iter(chunks, depth=2))
        first = next(it, None)
        if first is None:
            return
        engine = engine_factory()
        t0 = time.perf_counter()
        yield from engine.map_batches(chain([first], it))
        elapsed = time.perf_counter() - t0
        n, ndev = len(valid_idx), engine.num_devices
        ips = n / elapsed if elapsed > 0 else float("inf")
        logger.info("%s: %d images in %.3fs — %.1f img/s "
                    "(%.1f img/s/chip over %d devices)",
                    type(self).__name__, n, elapsed, ips, ips / ndev, ndev)

    def _run_streaming(self, dataset, engine_factory, height: int,
                       width: int, origins: Optional[List[str]] = None):
        """Stream the image column through the engine; returns (outputs
        [n_valid, ...] or None when nothing decoded, valid_idx).  For
        small-row outputs (vectors/probabilities) concatenating is cheap;
        image-sized outputs should consume :meth:`_stream_model_outputs`
        directly instead."""
        import jax

        valid_idx: List[int] = []
        outs = list(self._stream_model_outputs(
            dataset, engine_factory, height, width, valid_idx, origins))
        if not outs:
            return None, valid_idx
        out = jax.tree_util.tree_map(
            lambda *parts: np.concatenate(parts, axis=0), *outs)
        return out, valid_idx


class _NamedImageTransformer(_ImageInputStage, HasModelName):
    """Base of the zoo stages — resolves modelName against the registry
    (same role as the reference's ``SUPPORTED_MODELS`` lookup)."""

    featurize: bool = False

    def __init__(self):
        super().__init__()
        from sparkdl_tpu.models import SUPPORTED_MODELS

        self.modelName.typeConverter = SparkDLTypeConverters.supportedNameConverter(
            SUPPORTED_MODELS)
        self._setDefault(batchSize=64)

    def _run_model(self, dataset) -> Tuple[np.ndarray, list, int]:
        name = self.getModelName()
        spec = get_model_spec(name)
        h, w = spec.input_size
        out, valid_idx = self._run_streaming(
            dataset,
            lambda: _zoo_engine(name, self.featurize, self.getBatchSize()),
            h, w)
        if out is None:
            dim = spec.feature_size if self.featurize else 1000
            return np.zeros((0, dim), np.float32), valid_idx, len(dataset)
        return np.asarray(out), valid_idx, len(dataset)


class DeepImageFeaturizer(_NamedImageTransformer):
    """Zoo-model featurization for transfer learning.

    Counterpart of the reference's ``DeepImageFeaturizer`` (Python wrapper +
    Scala implementation): output column holds the penultimate-layer vector
    (e.g. 2048-d for InceptionV3), ready for any downstream classifier.
    """

    featurize = True

    @keyword_only
    def __init__(self, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelName: Optional[str] = None,
                 batchSize: Optional[int] = None):
        super().__init__()
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  modelName: Optional[str] = None,
                  batchSize: Optional[int] = None):
        return self._set(**self._input_kwargs)

    def _transform(self, dataset):
        feats, valid_idx, n = self._run_model(dataset)
        return dataset.withColumn(
            self.getOutputCol(), _float_list_array(feats, valid_idx, n))


class DeepImagePredictor(_NamedImageTransformer):
    """Zoo-model prediction.

    Counterpart of the reference's ``DeepImagePredictor``: class
    probabilities, optionally decoded to top-K ``(class, description,
    probability)`` structs (``_decodeOutputAsPredictions``).
    """

    featurize = False

    decodePredictions = Param(
        "undefined", "decodePredictions",
        "decode the output probabilities into top-K (class, description, "
        "probability) rows", typeConverter=TypeConverters.toBoolean)

    topK = HasTopK.topK

    @keyword_only
    def __init__(self, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelName: Optional[str] = None,
                 decodePredictions: bool = False,
                 topK: int = 5,
                 batchSize: Optional[int] = None):
        super().__init__()
        self._setDefault(decodePredictions=False, topK=5)
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  modelName: Optional[str] = None,
                  decodePredictions: Optional[bool] = None,
                  topK: Optional[int] = None,
                  batchSize: Optional[int] = None):
        return self._set(**self._input_kwargs)

    def getDecodePredictions(self):
        return self.getOrDefault(self.decodePredictions)

    def getTopK(self):
        return self.getOrDefault(self.topK)

    def _transform(self, dataset):
        probs, valid_idx, n = self._run_model(dataset)
        out_col = self.getOutputCol()
        if not self.getDecodePredictions():
            return dataset.withColumn(
                out_col, _float_list_array(probs, valid_idx, n))
        decoded = decode_predictions(probs, top=self.getTopK())
        pred_type = pa.list_(pa.struct([
            pa.field("class", pa.string()),
            pa.field("description", pa.string()),
            pa.field("probability", pa.float32()),
        ]))
        values: List[Optional[list]] = [None] * n
        for row, i in zip(decoded, valid_idx):
            values[i] = [
                {"class": c, "description": d, "probability": p}
                for c, d, p in row]
        return dataset.withColumn(out_col, pa.array(values, type=pred_type))


class TFImageTransformer(PersistableModelFunctionMixin, _ImageInputStage,
                         HasOutputMode):
    """Arbitrary model over the image column.

    Counterpart of the reference's ``TFImageTransformer`` (C4): where that
    shipped a merged GraphDef (image-converter subgraph ∘ user graph) to
    TensorFrames, this applies a user :class:`ModelFunction` to the decoded
    uint8 RGB batch inside one jit program.  ``outputMode="vector"`` emits a
    flat float vector per row; ``"image"`` re-packs a [H,W,3] float output
    as an image struct.
    """

    modelFunction = Param(
        "undefined", "modelFunction",
        "ModelFunction applied to the decoded [B,H,W,3] uint8 RGB batch",
        typeConverter=SparkDLTypeConverters.toModelFunction)

    inputSize = Param(
        "undefined", "inputSize",
        "[height, width] the images are resized to before the model; "
        "defaults to the first row's stored size",
        typeConverter=TypeConverters.toList)

    @keyword_only
    def __init__(self, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelFunction=None,
                 inputSize: Optional[Sequence[int]] = None,
                 outputMode: str = "vector",
                 batchSize: Optional[int] = None):
        super().__init__()
        self._setDefault(outputMode="vector", batchSize=64)
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  modelFunction=None,
                  inputSize: Optional[Sequence[int]] = None,
                  outputMode: Optional[str] = None,
                  batchSize: Optional[int] = None):
        return self._set(**self._input_kwargs)

    def getModelFunction(self):
        return self.getOrDefault(self.modelFunction)

    def transformStream(self, batches, params=None):
        """Stream with a CONSISTENT inferred input size: when ``inputSize``
        is unset, it is resolved once from the first valid struct and pinned
        for the whole stream — per-batch re-inference would let batches with
        different first-image sizes emit different feature dims into one
        column."""
        if params:
            yield from self.copy(params).transformStream(batches)
            return
        if self.isDefined(self.inputSize):
            yield from super().transformStream(batches)
            return
        from itertools import chain

        from sparkdl_tpu.frame import DataFrame

        it = iter(batches)
        buffered, size = [], None
        for rb in it:
            buffered.append(rb)
            s = self._first_valid_struct(DataFrame(rb))
            if s is not None:
                size = [int(s["height"]), int(s["width"])]
                break
        if size is None:
            raise ValueError(
                f"No decodable images in column {self.getInputCol()!r}")
        pinned = self.copy({"inputSize": size})
        yield from pinned.transformStream(chain(buffered, it))

    def _transform(self, dataset):
        if self.isDefined(self.inputSize):
            h, w = (int(v) for v in self.getOrDefault(self.inputSize))
        else:
            first = self._first_valid_struct(dataset)
            if first is None:
                raise ValueError(
                    f"No decodable images in column {self.getInputCol()!r}")
            h, w = int(first["height"]), int(first["width"])
        n = len(dataset)
        mode = self.getOutputMode()
        factory = lambda: get_cached_engine(  # noqa: E731
            self, self.getModelFunction(),
            device_batch_size=self.getBatchSize())
        if mode == "image":
            return self._transform_image_mode(dataset, factory, h, w, n)
        origins: List[str] = []
        out, valid_idx = self._run_streaming(dataset, factory, h, w,
                                             origins=origins)
        if out is None:
            # Nothing decodable but the size was known (explicit or pinned
            # by transformStream): keep the drop-to-null contract — an
            # all-null record batch mid-stream must not kill the job.
            return dataset.withColumn(
                self.getOutputCol(),
                pa.array([None] * n, type=pa.list_(pa.float32())))
        out = np.asarray(out)
        flat = out.reshape(out.shape[0], -1).astype(np.float32)
        return dataset.withColumn(
            self.getOutputCol(), _float_list_array(flat, valid_idx, n))

    def _transform_image_mode(self, dataset, engine_factory, h, w, n):
        """Image-sized outputs are packed to structs PER CHUNK as the
        engine yields them (VERDICT r2 weak #5): at no point does a
        whole-dataset float output array exist — peak residency is the
        arrow column under construction plus O(engine window) chunks."""
        origins: List[str] = []
        valid_idx: List[int] = []
        packed: List[dict] = []
        consumed = 0
        for out in self._stream_model_outputs(
                dataset, engine_factory, h, w, valid_idx, origins):
            out = np.asarray(out)
            if out.ndim != 4:
                raise ValueError(
                    f'outputMode="image" needs [B,H,W,C] model output, got '
                    f"shape {out.shape}")
            for row, origin in zip(out, origins[consumed:consumed + len(out)]):
                if row.shape[-1] == 3:
                    row = row[:, :, ::-1]  # model RGB -> struct BGR
                elif row.shape[-1] == 4:
                    # RGBA -> BGRA: flip color channels, keep alpha last
                    # (the CV_8UC4/CV_32FC4 struct convention).
                    row = row[:, :, [2, 1, 0, 3]]
                packed.append(imageArrayToStruct(
                    np.ascontiguousarray(row, dtype=np.float32),
                    origin=origin))
            consumed += len(out)
        values: List[Optional[dict]] = [None] * n
        for struct, i in zip(packed, valid_idx):
            values[i] = struct
        return dataset.withColumn(
            self.getOutputCol(), pa.array(values, type=imageSchema))
