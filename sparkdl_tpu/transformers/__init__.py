"""Pipeline-stage layer (transformers).

Replaces the reference's L4 transformer surface
(``python/sparkdl/transformers/`` + the Scala ``DeepImageFeaturizer`` —
SURVEY.md §2 C3–C6, C13) with stages that run batched XLA programs on the
device mesh instead of per-executor TF sessions.
"""

from sparkdl_tpu.transformers.base import (Estimator, Model, Pipeline,
                                           PipelineModel, Transformer)
from sparkdl_tpu.transformers.named_image import (DeepImageFeaturizer,
                                                  DeepImagePredictor,
                                                  TFImageTransformer)
from sparkdl_tpu.transformers.tensor import (KerasTransformer,
                                             ModelTransformer, TFTransformer)
from sparkdl_tpu.transformers.image_file import (ImageFileTransformer,
                                                 KerasImageFileTransformer)

__all__ = [
    "DeepImageFeaturizer", "DeepImagePredictor", "Estimator",
    "ImageFileTransformer", "KerasImageFileTransformer", "KerasTransformer",
    "Model", "ModelTransformer", "Pipeline", "PipelineModel",
    "TFImageTransformer", "TFTransformer", "Transformer",
]
