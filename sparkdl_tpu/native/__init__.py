"""Native host-IO core: build + ctypes binding.

The reference had no in-repo native code — all native execution lived in
external engines (SURVEY.md §2 "Native components: NONE in-repo").  The TPU
build keeps the *compute* path in XLA but owns its host runtime: this module
compiles ``sparkdl_native.cpp`` (threaded fused JPEG/PNG decode+resize) on
first use with the system toolchain and binds it via ctypes (no pybind11 in
the image).  Everything degrades to the PIL path if the toolchain or
libjpeg/libpng are unavailable — the framework never hard-requires the
native core.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence, Tuple

import numpy as np

from sparkdl_tpu.analysis.lockcheck import named_lock
from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "sparkdl_native.cpp")
_LIB_DIR = os.path.join(os.path.dirname(__file__), "_build")
_LIB_PATH = os.path.join(_LIB_DIR, "libsparkdl_native.so")

_lock = named_lock("native.load")
_lib = None
_load_attempted = False


def _build() -> bool:
    os.makedirs(_LIB_DIR, exist_ok=True)
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
        _SRC, "-ljpeg", "-lpng", "-o", _LIB_PATH,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=300)
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.warning("native build failed to run (%s); using PIL path", e)
        return False
    if proc.returncode != 0:
        logger.warning("native build failed; using PIL path:\n%s",
                       proc.stderr[-2000:])
        return False
    return True


def _load():
    global _lib, _load_attempted
    with _lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        if os.environ.get("SPARKDL_TPU_DISABLE_NATIVE"):
            logger.info("native IO disabled by SPARKDL_TPU_DISABLE_NATIVE")
            return None
        src_mtime = os.path.getmtime(_SRC) if os.path.exists(_SRC) else 0
        needs_build = (not os.path.exists(_LIB_PATH)
                       or os.path.getmtime(_LIB_PATH) < src_mtime)
        if needs_build and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            logger.warning("native library load failed (%s); using PIL path",
                           e)
            return None
        lib.sdl_decode_resize_batch.restype = ctypes.c_int
        lib.sdl_decode_resize_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
        ]
        lib.sdl_resize_batch.restype = None
        lib.sdl_resize_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
        ]
        _lib = lib
        logger.info("native IO core loaded (%s)", _LIB_PATH)
        return _lib


def native_available() -> bool:
    return _load() is not None


def _default_threads() -> int:
    return min(16, os.cpu_count() or 4)


def decode_resize_batch(blobs: Sequence[bytes], height: int, width: int,
                        num_threads: Optional[int] = None
                        ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Fused decode(JPEG/PNG)+resize of encoded images into a [N,h,w,3]
    uint8 RGB batch + boolean ok-mask.  Returns None when the native core is
    unavailable (caller falls back to PIL)."""
    lib = _load()
    if lib is None:
        return None
    n = len(blobs)
    out = np.zeros((n, height, width, 3), dtype=np.uint8)
    status = np.zeros(n, dtype=np.uint8)
    if n == 0:
        return out, status.astype(bool)
    # Keep byte objects alive + build pointer arrays.
    buffers = [bytes(b) for b in blobs]
    ptrs = (ctypes.c_char_p * n)(*buffers)
    sizes = (ctypes.c_size_t * n)(*[len(b) for b in buffers])
    lib.sdl_decode_resize_batch(
        ptrs, sizes, n, height, width,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        status.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        num_threads or _default_threads())
    return out, status.astype(bool)


def resize_batch_rgb(images: Sequence[np.ndarray], height: int, width: int,
                     num_threads: Optional[int] = None
                     ) -> Optional[np.ndarray]:
    """Resize a list of [h,w,3] uint8 RGB arrays into one [N,h,w,3] batch.
    Returns None when the native core is unavailable."""
    lib = _load()
    if lib is None:
        return None
    n = len(images)
    out = np.zeros((n, height, width, 3), dtype=np.uint8)
    if n == 0:
        return out
    contiguous = [np.ascontiguousarray(im, dtype=np.uint8) for im in images]
    for im in contiguous:
        if im.ndim != 3 or im.shape[2] != 3:
            raise ValueError(f"resize_batch_rgb needs [h,w,3] uint8 arrays, "
                             f"got {im.shape}")
    ptrs = (ctypes.c_char_p * n)(
        *[im.ctypes.data_as(ctypes.c_char_p) for im in contiguous])
    hs = (ctypes.c_int * n)(*[im.shape[0] for im in contiguous])
    ws = (ctypes.c_int * n)(*[im.shape[1] for im in contiguous])
    lib.sdl_resize_batch(
        ptrs, hs, ws, n, height, width,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        num_threads or _default_threads())
    return out
