// sparkdl_tpu native host-IO core.
//
// The reference delegated image decode to PIL (Python path) / java.awt
// (Scala path) per executor (SURVEY.md §2 C2, C13).  Feeding a TPU chip is
// harder than feeding a GPU executor: host-side decode+resize is the
// throughput bottleneck (SURVEY.md §7 hard part #2).  This library fuses
// JPEG/PNG decode and bilinear resize in one pass per image with:
//   * libjpeg DCT-domain prescaling (decode at 1/2, 1/4, 1/8 scale when the
//     target is much smaller than the source — skips most of the IDCT work;
//     PIL does not do this unless explicitly drafted),
//   * a std::thread pool with no Python GIL involvement,
//   * per-image failure status (undecodable rows surface as nulls upstream,
//     never as job failures — the imageIO drop-to-null contract).
//
// C ABI only; bound from Python via ctypes (no pybind11 in this image).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <png.h>

namespace {

// ---------------------------------------------------------------------------
// bilinear resize (RGB8, triangle kernel with area-style support for
// downscale — close to PIL's BILINEAR; parity is tolerance-based, matching
// the reference's own cross-backend resize tests)

void resize_bilinear_rgb(const uint8_t* src, int sh, int sw,
                         uint8_t* dst, int dh, int dw) {
  if (sh == dh && sw == dw) {
    std::memcpy(dst, src, static_cast<size_t>(sh) * sw * 3);
    return;
  }
  const float scale_y = static_cast<float>(sh) / dh;
  const float scale_x = static_cast<float>(sw) / dw;
  std::vector<float> row_acc(static_cast<size_t>(dw) * 3);

  // Separable triangle filter; support widens for downscale (anti-alias),
  // degenerates to classic bilinear for upscale.
  const float support_y = std::max(1.0f, scale_y);
  const float support_x = std::max(1.0f, scale_x);

  // Precompute horizontal taps per output column.
  struct Tap { int start, count; };
  std::vector<Tap> xtaps(dw);
  std::vector<float> xweights;
  std::vector<int> xoff(dw);
  for (int ox = 0; ox < dw; ++ox) {
    const float center = (ox + 0.5f) * scale_x;
    int lo = static_cast<int>(std::floor(center - support_x));
    int hi = static_cast<int>(std::ceil(center + support_x));
    lo = std::max(lo, 0);
    hi = std::min(hi, sw);
    xoff[ox] = static_cast<int>(xweights.size());
    float total = 0.0f;
    for (int sx = lo; sx < hi; ++sx) {
      float d = std::fabs((sx + 0.5f) - center) / support_x;
      float wgt = std::max(0.0f, 1.0f - d);
      xweights.push_back(wgt);
      total += wgt;
    }
    if (total <= 0.0f) {  // degenerate window: nearest
      lo = std::min(std::max(static_cast<int>(center), 0), sw - 1);
      hi = lo + 1;
      xoff[ox] = static_cast<int>(xweights.size());
      xweights.push_back(1.0f);
      total = 1.0f;
    }
    for (size_t k = xoff[ox]; k < xweights.size(); ++k) xweights[k] /= total;
    xtaps[ox] = {lo, hi - lo};
  }

  std::vector<float> ycol;  // vertical weights per output row
  for (int oy = 0; oy < dh; ++oy) {
    const float center = (oy + 0.5f) * scale_y;
    int lo = static_cast<int>(std::floor(center - support_y));
    int hi = static_cast<int>(std::ceil(center + support_y));
    lo = std::max(lo, 0);
    hi = std::min(hi, sh);
    ycol.clear();
    float total = 0.0f;
    for (int sy = lo; sy < hi; ++sy) {
      float d = std::fabs((sy + 0.5f) - center) / support_y;
      float wgt = std::max(0.0f, 1.0f - d);
      ycol.push_back(wgt);
      total += wgt;
    }
    if (total <= 0.0f) {
      lo = std::min(std::max(static_cast<int>(center), 0), sh - 1);
      hi = lo + 1;
      ycol.assign(1, 1.0f);
      total = 1.0f;
    }
    for (float& wgt : ycol) wgt /= total;

    std::fill(row_acc.begin(), row_acc.end(), 0.0f);
    for (int t = 0; t < hi - lo; ++t) {
      const uint8_t* srow = src + static_cast<size_t>(lo + t) * sw * 3;
      const float wy = ycol[t];
      for (int ox = 0; ox < dw; ++ox) {
        const Tap tap = xtaps[ox];
        const float* wx = &xweights[xoff[ox]];
        float r = 0, gch = 0, b = 0;
        const uint8_t* p = srow + static_cast<size_t>(tap.start) * 3;
        for (int k = 0; k < tap.count; ++k, p += 3) {
          r += wx[k] * p[0];
          gch += wx[k] * p[1];
          b += wx[k] * p[2];
        }
        float* acc = &row_acc[static_cast<size_t>(ox) * 3];
        acc[0] += wy * r;
        acc[1] += wy * gch;
        acc[2] += wy * b;
      }
    }
    uint8_t* drow = dst + static_cast<size_t>(oy) * dw * 3;
    for (int i = 0; i < dw * 3; ++i) {
      drow[i] = static_cast<uint8_t>(
          std::min(255.0f, std::max(0.0f, row_acc[i] + 0.5f)));
    }
  }
}

// ---------------------------------------------------------------------------
// JPEG decode (libjpeg with longjmp error trap + DCT prescale)

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jb, 1);
}

bool decode_jpeg_resized(const uint8_t* data, size_t size, int out_h,
                         int out_w, uint8_t* out) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  std::vector<uint8_t> pixels;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(size));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  // DCT-domain prescale: decode at the smallest 1/1..1/8 scale that still
  // covers the target, skipping most IDCT + color conversion work.
  const int full_w = cinfo.image_width, full_h = cinfo.image_height;
  int denom = 1;
  while (denom < 8 && (full_w / (denom * 2)) >= out_w &&
         (full_h / (denom * 2)) >= out_h) {
    denom *= 2;
  }
  cinfo.scale_num = 1;
  cinfo.scale_denom = denom;
  jpeg_start_decompress(&cinfo);
  const int sw = cinfo.output_width, sh = cinfo.output_height;
  const int ch = cinfo.output_components;
  if (ch != 3) {  // grayscale etc. -> expand below
    if (ch != 1) {
      jpeg_destroy_decompress(&cinfo);
      return false;
    }
  }
  pixels.resize(static_cast<size_t>(sh) * sw * 3);
  std::vector<uint8_t> line(static_cast<size_t>(sw) * ch);
  for (int y = 0; y < sh; ++y) {
    uint8_t* lp = line.data();
    jpeg_read_scanlines(&cinfo, &lp, 1);
    uint8_t* dst = &pixels[static_cast<size_t>(y) * sw * 3];
    if (ch == 3) {
      std::memcpy(dst, lp, static_cast<size_t>(sw) * 3);
    } else {
      for (int x = 0; x < sw; ++x) {
        dst[x * 3] = dst[x * 3 + 1] = dst[x * 3 + 2] = lp[x];
      }
    }
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  resize_bilinear_rgb(pixels.data(), sh, sw, out, out_h, out_w);
  return true;
}

// ---------------------------------------------------------------------------
// PNG decode (libpng from memory)

struct PngReadState {
  const uint8_t* data;
  size_t size;
  size_t off;
};

void png_read_fn(png_structp png, png_bytep dst, png_size_t len) {
  PngReadState* st = static_cast<PngReadState*>(png_get_io_ptr(png));
  if (st->off + len > st->size) {
    png_error(png, "eof");
  }
  std::memcpy(dst, st->data + st->off, len);
  st->off += len;
}

bool decode_png_resized(const uint8_t* data, size_t size, int out_h,
                        int out_w, uint8_t* out) {
  if (size < 8 || png_sig_cmp(data, 0, 8)) return false;
  png_structp png = png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr,
                                           nullptr, nullptr);
  if (!png) return false;
  png_infop info = png_create_info_struct(png);
  if (!info) {
    png_destroy_read_struct(&png, nullptr, nullptr);
    return false;
  }
  std::vector<uint8_t> pixels;
  std::vector<png_bytep> rows;
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    return false;
  }
  PngReadState st{data, size, 0};
  png_set_read_fn(png, &st, png_read_fn);
  png_read_info(png, info);
  png_set_strip_16(png);
  png_set_palette_to_rgb(png);
  png_set_expand_gray_1_2_4_to_8(png);
  png_set_strip_alpha(png);
  png_set_gray_to_rgb(png);
  png_read_update_info(png, info);
  const int sw = png_get_image_width(png, info);
  const int sh = png_get_image_height(png, info);
  if (png_get_channels(png, info) != 3) {
    png_destroy_read_struct(&png, &info, nullptr);
    return false;
  }
  pixels.resize(static_cast<size_t>(sh) * sw * 3);
  rows.resize(sh);
  for (int y = 0; y < sh; ++y) {
    rows[y] = &pixels[static_cast<size_t>(y) * sw * 3];
  }
  png_read_image(png, rows.data());
  png_destroy_read_struct(&png, &info, nullptr);
  resize_bilinear_rgb(pixels.data(), sh, sw, out, out_h, out_w);
  return true;
}

// ---------------------------------------------------------------------------
// threadpool driver

template <typename Fn>
void parallel_for(int n, int n_threads, Fn fn) {
  if (n_threads <= 1 || n <= 1) {
    for (int i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int> next{0};
  auto worker = [&]() {
    for (;;) {
      const int i = next.fetch_add(1);
      if (i >= n) return;
      fn(i);
    }
  };
  const int k = std::min(n_threads, n);
  std::vector<std::thread> threads;
  threads.reserve(k - 1);
  for (int t = 1; t < k; ++t) threads.emplace_back(worker);
  worker();
  for (auto& th : threads) th.join();
}

}  // namespace

extern "C" {

// Decode (JPEG/PNG) + resize a batch of encoded images into a contiguous
// [n, out_h, out_w, 3] RGB8 buffer.  status[i]=1 on success, 0 on failure
// (the row's output pixels are zeroed).  Returns the success count.
int sdl_decode_resize_batch(const uint8_t** inputs, const size_t* sizes,
                            int n, int out_h, int out_w, uint8_t* out,
                            uint8_t* status, int n_threads) {
  const size_t stride = static_cast<size_t>(out_h) * out_w * 3;
  std::atomic<int> ok_count{0};
  parallel_for(n, n_threads, [&](int i) {
    uint8_t* dst = out + stride * i;
    const uint8_t* data = inputs[i];
    const size_t size = sizes[i];
    bool ok = false;
    if (size >= 2 && data[0] == 0xFF && data[1] == 0xD8) {
      ok = decode_jpeg_resized(data, size, out_h, out_w, dst);
    } else if (size >= 8 && !png_sig_cmp(data, 0, 8)) {
      ok = decode_png_resized(data, size, out_h, out_w, dst);
    }
    if (!ok) {
      std::memset(dst, 0, stride);
    } else {
      ok_count.fetch_add(1);
    }
    status[i] = ok ? 1 : 0;
  });
  return ok_count.load();
}

// Resize a batch of raw RGB8 images (possibly different sizes) into a
// contiguous [n, out_h, out_w, 3] buffer.
void sdl_resize_batch(const uint8_t** inputs, const int* hs, const int* ws,
                      int n, int out_h, int out_w, uint8_t* out,
                      int n_threads) {
  const size_t stride = static_cast<size_t>(out_h) * out_w * 3;
  parallel_for(n, n_threads, [&](int i) {
    resize_bilinear_rgb(inputs[i], hs[i], ws[i], out + stride * i, out_h,
                        out_w);
  });
}

int sdl_version() { return 1; }

}  // extern "C"
