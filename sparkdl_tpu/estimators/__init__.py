"""Estimator layer (training + tuning).

Replaces the reference's L5 (``python/sparkdl/estimators/`` — C15
``KerasImageFileEstimator``) and the pyspark.ml tuning/evaluation machinery
it plugged into (``CrossValidator``, ``ParamGridBuilder``, evaluators),
re-built for the mesh: a single fit is data-parallel over every chip (XLA
psum gradient all-reduce), and hyperparameter fan-out reuses one compiled
step where shapes allow.
"""

from sparkdl_tpu.estimators.classification import (LogisticRegression,
                                                   LogisticRegressionModel)
from sparkdl_tpu.estimators.evaluation import (BinaryClassificationEvaluator,
                                               Evaluator,
                                               MulticlassClassificationEvaluator)
from sparkdl_tpu.estimators.image_file_estimator import (ImageFileEstimator,
                                                         ImageFileModel,
                                                         KerasImageFileEstimator)
from sparkdl_tpu.estimators.tuning import (CrossValidator, CrossValidatorModel,
                                           ParamGridBuilder,
                                           TrainValidationSplit)

__all__ = [
    "BinaryClassificationEvaluator", "CrossValidator", "CrossValidatorModel",
    "Evaluator", "ImageFileEstimator", "ImageFileModel",
    "KerasImageFileEstimator", "LogisticRegression",
    "LogisticRegressionModel", "MulticlassClassificationEvaluator",
    "ParamGridBuilder", "TrainValidationSplit",
]
