"""Hyperparameter tuning: ParamGridBuilder / CrossValidator.

The reference's tuning story (README: ``KerasImageFileEstimator`` +
``CrossValidator`` + ``ParamGridBuilder``) relies on pyspark.ml.tuning;
re-built here with the same string-addressable param-grid contract
(SURVEY.md §5 "config/flag system" — the addressability is load-bearing).
Fan-out: the reference ran one Spark task per (fold, paramMap); here each
fit already spans the mesh, so maps run sequentially by default —
``fitMultiple`` on the estimator loads/shares data once across maps.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from sparkdl_tpu.estimators.evaluation import Evaluator
from sparkdl_tpu.frame import DataFrame
from sparkdl_tpu.param.params import Param, Params, keyword_only
from sparkdl_tpu.transformers.base import Estimator, Model
from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class ParamGridBuilder:
    """Builds [{Param: value}] grids — pyspark.ml.tuning.ParamGridBuilder
    contract (addGrid/baseOn/build)."""

    def __init__(self):
        self._grid: Dict[Param, List[Any]] = {}
        self._base: Dict[Param, Any] = {}

    def addGrid(self, param: Param, values: Sequence[Any]) -> "ParamGridBuilder":
        if not isinstance(param, Param):
            raise TypeError(f"addGrid expects a Param, got {type(param).__name__}")
        self._grid[param] = list(values)
        return self

    def baseOn(self, *args) -> "ParamGridBuilder":
        if len(args) == 1 and isinstance(args[0], dict):
            self._base.update(args[0])
        else:
            for param, value in args:
                self._base[param] = value
        return self

    def build(self) -> List[Dict[Param, Any]]:
        keys = list(self._grid.keys())
        maps = []
        for combo in itertools.product(*(self._grid[k] for k in keys)):
            m = dict(self._base)
            m.update(dict(zip(keys, combo)))
            maps.append(m)
        return maps or [dict(self._base)]


def _kfold_indices(n: int, k: int, seed: int) -> List[np.ndarray]:
    rng = np.random.default_rng(seed)
    order = rng.permutation(n)
    return [order[i::k] for i in range(k)]


def _take_rows(df: DataFrame, idx: np.ndarray) -> DataFrame:
    return DataFrame(df.table.take(np.sort(idx)))


class CrossValidatorModel(Model):
    def __init__(self, bestModel: Model, avgMetrics: List[float]):
        super().__init__()
        self.bestModel = bestModel
        self.avgMetrics = list(avgMetrics)

    def _transform(self, dataset):
        return self.bestModel.transform(dataset)

    def _persist(self, path):
        from sparkdl_tpu import persistence

        names = persistence.save_nested([self.bestModel], path)
        return ({"bestModel": names[0],
                 "avgMetrics": [float(m) for m in self.avgMetrics]},
                None, {})

    @classmethod
    def _restore(cls, extra, pytree, pickles, path):
        import os

        from sparkdl_tpu import persistence

        best = persistence.load_stage(
            os.path.join(path, "stages", extra["bestModel"]))
        return cls(best, extra.get("avgMetrics", []))


class CrossValidator(Estimator):
    """K-fold model selection over a param grid.

    pyspark.ml.tuning.CrossValidator contract: ``estimator``,
    ``estimatorParamMaps`` (from ParamGridBuilder), ``evaluator``,
    ``numFolds``; ``fit`` returns a CrossValidatorModel holding the best
    model refit on the full data plus per-map average metrics.
    """

    @keyword_only
    def __init__(self, estimator: Optional[Estimator] = None,
                 estimatorParamMaps: Optional[List[Dict]] = None,
                 evaluator: Optional[Evaluator] = None,
                 numFolds: int = 3, seed: int = 0, parallelism: int = 1):
        super().__init__()
        self.estimator = estimator
        self.estimatorParamMaps = estimatorParamMaps
        self.evaluator = evaluator
        self.numFolds = int(numFolds)
        self.seed = int(seed)
        # pyspark.ml.tuning parity: how many param-map fits may run
        # concurrently.  Forwarded to the estimator's own `parallelism`
        # param when it has one (ImageFileEstimator fans maps out over
        # device-mesh slices); estimators without the param fit
        # sequentially as before.
        self.parallelism = int(parallelism)

    def _effective_estimator(self) -> Estimator:
        est = self.estimator
        if (self.parallelism > 1 and hasattr(est, "hasParam")
                and est.hasParam("parallelism")):
            return est.copy({est.getParam("parallelism"): self.parallelism})
        return est

    def _fit(self, dataset) -> CrossValidatorModel:
        est, maps, ev = (self._effective_estimator(),
                         self.estimatorParamMaps, self.evaluator)
        if est is None or not maps or ev is None:
            raise ValueError(
                "CrossValidator requires estimator, estimatorParamMaps and "
                "evaluator")
        n = len(dataset)
        if self.numFolds < 2:
            raise ValueError("numFolds must be >= 2")
        folds = _kfold_indices(n, self.numFolds, self.seed)
        metrics = np.zeros(len(maps), dtype=np.float64)
        for f, val_idx in enumerate(folds):
            train_idx = np.concatenate(
                [folds[i] for i in range(self.numFolds) if i != f])
            train_df = _take_rows(dataset, train_idx)
            val_df = _take_rows(dataset, val_idx)
            for m, (_, model) in zip(
                    range(len(maps)), est.fitMultiple(train_df, maps)):
                metric = ev.evaluate(model.transform(val_df))
                metrics[m] += metric / self.numFolds
                logger.info("fold %d map %d: %.4f", f, m, metric)
        best = int(np.argmax(metrics) if ev.isLargerBetter()
                   else np.argmin(metrics))
        logger.info("best param map %d (avg metric %.4f); refitting on full "
                    "data", best, metrics[best])
        best_model = est.fit(dataset, maps[best])
        return CrossValidatorModel(best_model, list(metrics))


class TrainValidationSplit(Estimator):
    """Single-split variant (pyspark.ml.tuning.TrainValidationSplit)."""

    @keyword_only
    def __init__(self, estimator: Optional[Estimator] = None,
                 estimatorParamMaps: Optional[List[Dict]] = None,
                 evaluator: Optional[Evaluator] = None,
                 trainRatio: float = 0.75, seed: int = 0,
                 parallelism: int = 1):
        super().__init__()
        self.estimator = estimator
        self.estimatorParamMaps = estimatorParamMaps
        self.evaluator = evaluator
        self.trainRatio = float(trainRatio)
        self.seed = int(seed)
        self.parallelism = int(parallelism)

    _effective_estimator = CrossValidator._effective_estimator

    def _fit(self, dataset) -> CrossValidatorModel:
        est, maps, ev = (self._effective_estimator(),
                         self.estimatorParamMaps, self.evaluator)
        if est is None or not maps or ev is None:
            raise ValueError(
                "TrainValidationSplit requires estimator, estimatorParamMaps "
                "and evaluator")
        n = len(dataset)
        rng = np.random.default_rng(self.seed)
        order = rng.permutation(n)
        cut = int(n * self.trainRatio)
        if cut == 0 or cut == n:
            raise ValueError(f"trainRatio {self.trainRatio} leaves an empty "
                             f"split for {n} rows")
        train_df = _take_rows(dataset, order[:cut])
        val_df = _take_rows(dataset, order[cut:])
        metrics = []
        for _, model in est.fitMultiple(train_df, maps):
            metrics.append(ev.evaluate(model.transform(val_df)))
        metrics = np.asarray(metrics)
        best = int(np.argmax(metrics) if ev.isLargerBetter()
                   else np.argmin(metrics))
        best_model = est.fit(dataset, maps[best])
        return CrossValidatorModel(best_model, list(metrics))
