"""Image-file estimator: distributed transfer learning + tuning fan-out.

Replaces ``python/sparkdl/estimators/keras_image_file_estimator.py`` (C15
``KerasImageFileEstimator``) and upgrades its execution model (SURVEY.md
§3.3):

  reference: collect (uri,label) to driver -> driver-side PIL loop ->
             sc.broadcast(numpy) -> ONE SPARK TASK PER PARAM MAP, each task
             a single-process Keras fit.
  here:      threaded host load ONCE -> each fit is DATA-PARALLEL over the
             whole mesh (XLA psum gradient all-reduce — the new north-star
             capability) -> param maps run sequentially against the same
             in-memory arrays, reusing the compiled step when shapes and
             optimizer topology allow (SURVEY.md §7 hard part #5).

The user model is a :class:`ModelFunction` (or a Keras ``modelFile``
converted on the fly).  BatchNorm statistics stay frozen during fine-tuning
(inference-mode conversion) — weights still train; divergence from Keras
``fit`` (which updates moving stats) is documented here deliberately.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from sparkdl_tpu.param.converters import SparkDLTypeConverters
from sparkdl_tpu.param.params import Param, TypeConverters, keyword_only
from sparkdl_tpu.param.shared import (CanLoadImage, HasBatchSize, HasInputCol,
                                      HasLabelCol, HasOutputCol)
from sparkdl_tpu.parallel.train import fit_data_parallel
from sparkdl_tpu.transformers.base import Estimator, Model
from sparkdl_tpu.utils.cache import ByteBoundedLRU
from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class ImageFileEstimator(Estimator, HasInputCol, HasLabelCol, HasOutputCol,
                         HasBatchSize, CanLoadImage):
    """Fine-tune a model on images loaded from a URI column.

    Params mirror the reference's (``kerasOptimizer``/``kerasLoss``/
    ``kerasFitParams`` become ``optimizer``/``loss``/``fitParams``; the
    Keras-named aliases live on :class:`KerasImageFileEstimator`).
    """

    modelFunction = Param(
        "undefined", "modelFunction",
        "trainable ModelFunction (fn(variables, x) -> predictions)",
        typeConverter=SparkDLTypeConverters.toModelFunction)

    optimizer = Param(
        "undefined", "optimizer",
        "optax optimizer, factory, or name (adam/sgd/rmsprop/...)",
        typeConverter=SparkDLTypeConverters.toOptimizer)

    loss = Param(
        "undefined", "loss",
        "loss name (categorical_crossentropy/...) or callable (pred, y)->[B]",
        typeConverter=SparkDLTypeConverters.toLoss)

    fitParams = Param(
        "undefined", "fitParams",
        "fit settings: {'epochs': int, 'shuffle': bool, 'seed': int, "
        "'checkpoint_dir': str, 'checkpoint_every_epochs': int}",
        typeConverter=TypeConverters.toDict)

    trainBatchStats = Param(
        "undefined", "trainBatchStats",
        "update BatchNorm statistics during the fit (Keras fit semantics; "
        "stats reductions have global-batch semantics via the SPMD psum). "
        "Default False: stats stay frozen (inference-mode fine-tuning). "
        "Requires a model with a train-mode apply "
        "(ModelFunction.train_fn, e.g. from_flax with batch_stats).",
        typeConverter=TypeConverters.toBoolean)

    parallelism = Param(
        "undefined", "parallelism",
        "max param maps fitted CONCURRENTLY by fitMultiple, each on its own "
        "slice of the device mesh (the TPU analog of the reference's "
        "one-Spark-task-per-paramMap fan-out, SURVEY.md §2; same contract "
        "as pyspark.ml.tuning's parallelism). 1 (default) = sequential "
        "fits, each spanning the whole mesh.",
        typeConverter=TypeConverters.toInt)

    @keyword_only
    def __init__(self, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 labelCol: Optional[str] = None,
                 modelFunction=None,
                 imageLoader=None,
                 optimizer=None,
                 loss: Optional[Any] = None,
                 fitParams: Optional[Dict] = None,
                 batchSize: Optional[int] = None,
                 trainBatchStats: Optional[bool] = None,
                 parallelism: Optional[int] = None):
        super().__init__()
        self._setDefault(batchSize=32, fitParams={},
                         loss="categorical_crossentropy",
                         trainBatchStats=False, parallelism=1)
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  labelCol: Optional[str] = None,
                  modelFunction=None,
                  imageLoader=None,
                  optimizer=None,
                  loss: Optional[Any] = None,
                  fitParams: Optional[Dict] = None,
                  batchSize: Optional[int] = None,
                  trainBatchStats: Optional[bool] = None,
                  parallelism: Optional[int] = None):
        return self._set(**self._input_kwargs)

    def getTrainBatchStats(self) -> bool:
        return bool(self.getOrDefault(self.trainBatchStats))

    # -- param access ------------------------------------------------------
    def getModelFunction(self):
        return self.getOrDefault(self.modelFunction)

    def getOptimizer(self):
        if self.isDefined(self.optimizer) and self.isSet(self.optimizer):
            return self.getOrDefault(self.optimizer)
        return None

    def getLoss(self):
        return self.getOrDefault(self.loss)

    def getFitParams(self) -> Dict:
        return dict(self.getOrDefault(self.fitParams))

    # -- validation (reference: _validateParams) ---------------------------
    def _validateParams(self):
        missing = []
        for p in ("inputCol", "labelCol", "outputCol", "imageLoader"):
            if not self.isDefined(self.getParam(p)) or not self.isSet(
                    self.getParam(p)):
                missing.append(p)
        try:
            self.getModelFunction()
        except KeyError:
            missing.append("modelFunction")
        if missing:
            raise ValueError(
                f"{type(self).__name__} requires params {missing} to be set")
        return True

    # -- data loading (reference: _getNumpyFeaturesAndLabels) --------------
    @staticmethod
    def _stack_labels(labels) -> np.ndarray:
        y = np.asarray(labels)
        if y.dtype == object:  # one-hot rows as lists
            y = np.asarray([np.asarray(v, dtype=np.float32) for v in labels])
        return y

    def _decode_uris(self, uris, loader) -> list:
        """Threaded decode of a URI list to arrays (shared by the cached
        whole-dataset path and the streaming per-chunk path)."""
        with ThreadPoolExecutor(min(16, max(2, len(uris)))) as ex:
            return list(ex.map(lambda u: np.asarray(loader(u)), uris))

    def _load_numpy(self, dataset) -> Tuple[np.ndarray, np.ndarray]:
        """Decode the URI column to a stacked float32 batch + labels.

        Decoded images are cached per URI on the estimator, so a
        CrossValidator's k folds x m maps + final refit pay ONE decode pass
        over the dataset instead of k+1 (the TPU-side analog of the
        reference broadcasting the decoded arrays once).  The cache is
        keyed by the imageLoader and shared by ``copy()``d estimators
        (Params.copy shallow-copies __dict__) — exactly the fold/map
        copies that would otherwise re-decode.

        The cache is BOUNDED (ADVICE r3: an estimator reused across
        datasets must not accumulate every decoded image for its
        lifetime): a byte-capped LRU, default 2048 MB, tunable via
        ``SPARKDL_DECODE_CACHE_MB`` (0 disables caching).  CV folds /
        param maps re-touch the same URIs, keeping them most-recent."""
        uris = dataset.table.column(self.getInputCol()).to_pylist()
        labels = dataset.table.column(self.getLabelCol()).to_pylist()
        loader = self.getImageLoader()
        cap = int(float(os.environ.get("SPARKDL_DECODE_CACHE_MB", "2048"))
                  * 1_000_000)
        cache = self.__dict__.get("_decode_cache")
        if cache is None or cache[0] is not loader or cache[1].cap_bytes != cap:
            cache = (loader, ByteBoundedLRU(cap))
            self.__dict__["_decode_cache"] = cache
        lru = cache[1]
        unique = list(dict.fromkeys(uris))
        local = {u: lru.get(u) for u in unique}
        missing = [u for u in unique if local[u] is None]
        if missing:
            for u, arr in zip(missing, self._decode_uris(missing, loader)):
                local[u] = arr
                lru.put(u, arr)
        x = np.stack([local[u] for u in uris]).astype(np.float32)
        return x, self._stack_labels(labels)

    def clearDecodeCache(self) -> None:
        """Drop cached decoded images (bounded while alive — see
        ``_load_numpy`` — but freeable eagerly between datasets)."""
        self.__dict__.pop("_decode_cache", None)

    # -- fitting -----------------------------------------------------------
    def _common_fit_kwargs(self) -> Dict:
        fp = self.getFitParams()
        return dict(
            optimizer=self.getOptimizer(),
            loss=self.getLoss(),
            batch_size=self.getBatchSize(),
            epochs=int(fp.get("epochs", 1)),
            checkpoint_dir=fp.get("checkpoint_dir"),
            checkpoint_every_epochs=int(fp.get("checkpoint_every_epochs", 1)))

    def _fit_with_runner(self, runner, common: Dict) -> "ImageFileModel":
        """Shared fit logic: ``runner(fn, params, **kw) -> (fitted, losses)``
        binds the data (in-memory arrays or a streaming source); this method
        owns the BatchNorm-stats branching, the frozen-stats predict closure
        cache, and fitted-model assembly."""
        mf = self.getModelFunction()
        has_stats = (isinstance(mf.variables, dict)
                     and "batch_stats" in mf.variables)
        if self.getTrainBatchStats():
            if mf.train_fn is None or not has_stats:
                raise ValueError(
                    "trainBatchStats=True requires a model with a "
                    "train-mode apply and batch_stats collections "
                    "(e.g. ModelFunction.from_flax on a BatchNorm module)")
            fitted, losses = runner(
                mf.fn, mf.variables["params"],
                train_fn=mf.train_fn,
                stats=mf.variables["batch_stats"], **common)
            new_vars = dict(mf.variables)
            new_vars.update(fitted)  # params + batch_stats
        elif has_stats:
            # Default: BN statistics stay FROZEN structurally — only the
            # params collection trains (inference-mode fine-tuning; the
            # divergence from Keras fit is now a param, not just a note).
            predict = getattr(mf, "_frozen_stats_predict", None)
            if predict is None:
                frozen = {k: v for k, v in mf.variables.items()
                          if k != "params"}

                def predict(p, xb):
                    return mf.fn({**frozen, "params": p}, xb)

                # cache on the ModelFunction so repeated fits (param maps,
                # folds) reuse one closure -> one compiled step
                mf._frozen_stats_predict = predict
            fitted, losses = runner(
                predict, mf.variables["params"], **common)
            new_vars = {k: v for k, v in mf.variables.items()
                        if k != "params"}
            new_vars["params"] = fitted
        else:
            fitted, losses = runner(mf.fn, mf.variables, **common)
            new_vars = fitted
        from sparkdl_tpu.graph.function import ModelFunction

        fitted_mf = ModelFunction(fn=mf.fn, variables=new_vars,
                                  train_fn=mf.train_fn,
                                  input_names=mf.input_names,
                                  output_names=mf.output_names)
        model = ImageFileModel(modelFunction=fitted_mf,
                               trainLosses=losses)
        model._set(inputCol=self.getInputCol(),
                   outputCol=self.getOutputCol(),
                   imageLoader=self.getImageLoader(),
                   batchSize=self.getBatchSize())
        # Keras-backed estimators record the source file so persistence can
        # rebuild the model fn without pickling keras closures.
        if self.hasParam("modelFile") and self.isSet(
                self.getParam("modelFile")):
            model.modelFile = self.getOrDefault(self.getParam("modelFile"))
        return model

    def _fit_on_arrays(self, x: np.ndarray, y: np.ndarray,
                       mesh=None) -> "ImageFileModel":
        fp = self.getFitParams()
        common = self._common_fit_kwargs()
        common.update(shuffle=bool(fp.get("shuffle", True)),
                      seed=int(fp.get("seed", 0)),
                      # k optimizer steps per compiled dispatch (Keras
                      # steps_per_execution; fit_data_parallel docstring)
                      steps_per_execution=int(
                          fp.get("steps_per_execution", 1)))
        if mesh is not None:
            common["mesh"] = mesh

        def runner(fn, params, **kw):
            return fit_data_parallel(fn, params, x, y, **kw)

        return self._fit_with_runner(runner, common)

    def _fit(self, dataset) -> "ImageFileModel":
        self._validateParams()
        if callable(dataset) and not hasattr(dataset, "table"):
            return self._fit_stream(dataset)
        x, y = self._load_numpy(dataset)
        return self._fit_on_arrays(x, y)

    # -- streaming fit (larger-than-RAM datasets) ---------------------------
    def _decode_record_batch(self, rb) -> Tuple[np.ndarray, np.ndarray]:
        """One {inputCol, labelCol} RecordBatch -> (x_chunk, y_chunk).
        No per-URI caching here — by definition the dataset may not fit."""
        uris = rb.column(rb.schema.get_field_index(
            self.getInputCol())).to_pylist()
        labels = rb.column(rb.schema.get_field_index(
            self.getLabelCol())).to_pylist()
        arrays = self._decode_uris(uris, self.getImageLoader())
        return np.stack(arrays).astype(np.float32), self._stack_labels(labels)

    def _fit_stream(self, source) -> "ImageFileModel":
        """Fit from a RE-ITERABLE epoch source for datasets larger than
        host RAM: ``source() -> iterator of pyarrow RecordBatches`` holding
        the URI + label columns (e.g. ``imageIO.iterFileBatches``-style
        readers, per-host sharded via ``distributed.shard_files``).  Each
        epoch re-iterates the source; peak host memory is O(record batch),
        never the dataset (SURVEY.md §7 step 1).  ``fitParams`` may carry
        ``steps_per_epoch`` (REQUIRED multi-controller)."""
        from sparkdl_tpu.parallel.train import fit_data_parallel_stream

        fp = self.getFitParams()
        common = self._common_fit_kwargs()
        common.update(steps_per_epoch=(int(fp["steps_per_epoch"])
                                       if "steps_per_epoch" in fp else None),
                      steps_per_execution=int(
                          fp.get("steps_per_execution", 1)))

        def chunks():
            for rb in source():
                yield self._decode_record_batch(rb)

        def runner(fn, params, **kw):
            return fit_data_parallel_stream(fn, params, chunks, **kw)

        return self._fit_with_runner(runner, common)

    def fitMultiple(self, dataset, paramMaps):
        """One model per param map.  Data is loaded ONCE (the analog of the
        reference's single broadcast) and reused across maps.

        With ``parallelism > 1`` the device mesh is carved into that many
        equal slices and maps fit CONCURRENTLY, one thread per slice —
        the reference fanned maps out as independent Spark tasks; here
        each fan-out lane is an independent sub-mesh running its own
        data-parallel fit (SURVEY.md §2 task-parallelism disposition).
        Model order matches ``paramMaps`` either way.  Single-controller
        only: a multi-process run falls back to sequential (threads would
        issue cross-host collectives in unordered interleavings)."""
        import os

        self._validateParams()
        x, y = self._load_numpy(dataset)
        maps = list(paramMaps)

        def map_estimator(i):
            """Per-map estimator copy with a DISAMBIGUATED checkpoint dir:
            maps sharing one fitParams checkpoint_dir would resume from
            each other's checkpoints (and, parallel, corrupt them)."""
            est = self.copy(maps[i])
            fp = est.getFitParams()
            if len(maps) > 1 and fp.get("checkpoint_dir"):
                fp["checkpoint_dir"] = os.path.join(
                    str(fp["checkpoint_dir"]), f"map_{i:03d}")
                est._set(fitParams=fp)
            return est

        import jax

        want = max(1, int(self.getOrDefault(self.parallelism)))
        if jax.process_count() > 1 and want > 1:
            logger.warning("fitMultiple parallelism=%d ignored in a "
                           "multi-controller run (cross-host collectives "
                           "cannot be interleaved across threads); fitting "
                           "sequentially", want)
            want = 1
        if want <= 1 or len(maps) <= 1:
            for i in range(len(maps)):
                yield i, map_estimator(i)._fit_on_arrays(x, y)
            return
        from sparkdl_tpu.parallel import mesh as mesh_lib

        devs = jax.devices()
        k = min(want, len(maps), len(devs))
        while len(devs) % k:  # equal slices only
            k -= 1
        if k <= 1:
            for i in range(len(maps)):
                yield i, map_estimator(i)._fit_on_arrays(x, y)
            return
        per = len(devs) // k
        logger.info("fitMultiple fan-out: %d maps over %d mesh slices of "
                    "%d device(s)", len(maps), k, per)
        import queue
        from concurrent.futures import ThreadPoolExecutor

        # Meshes are leased from a queue, not indexed by map position:
        # with more maps than slices a freed thread must take a FREE
        # slice, never double-book one still running another fit.
        free_meshes: "queue.Queue" = queue.Queue()
        for g in range(k):
            free_meshes.put(
                mesh_lib.get_mesh(devices=devs[g * per:(g + 1) * per]))

        def work(i):
            mesh = free_meshes.get()
            try:
                return map_estimator(i)._fit_on_arrays(x, y, mesh=mesh)
            finally:
                free_meshes.put(mesh)

        with ThreadPoolExecutor(k) as ex:
            for i, model in enumerate(ex.map(work, range(len(maps)))):
                yield i, model


class ImageFileModel(Model, HasInputCol, HasOutputCol, HasBatchSize,
                     CanLoadImage):
    """Fitted model: applies the trained ModelFunction to images loaded from
    the URI column (the role the returned ``KerasImageFileTransformer``
    played in the reference)."""

    modelFunction = Param(
        "undefined", "modelFunction", "fitted ModelFunction",
        typeConverter=SparkDLTypeConverters.toModelFunction)

    def __init__(self, modelFunction=None, trainLosses=None):
        super().__init__()
        self._setDefault(batchSize=32)
        if modelFunction is not None:
            self._set(modelFunction=modelFunction)
        self.trainLosses = list(trainLosses or [])
        self.modelFile: Optional[str] = None

    def getModelFunction(self):
        return self.getOrDefault(self.modelFunction)

    def _persist(self, path):
        import jax

        mf = self.getModelFunction()
        extra = {"trainLosses": [float(l) for l in self.trainLosses]}
        pickles = {}
        if self.modelFile:
            extra["modelFile"] = self.modelFile
            extra["modelFunction"] = "from-modelFile"
        else:
            from sparkdl_tpu.persistence import modelfunction_payload

            pickles["modelFunction"] = modelfunction_payload(mf)
        if self.isSet(self.getParam("imageLoader")):
            pickles["imageLoader"] = self.getImageLoader()
        host_vars = jax.tree_util.tree_map(np.asarray, mf.variables)
        return extra, {"variables": host_vars}, pickles

    @classmethod
    def _restore(cls, extra, pytree, pickles, path):
        from sparkdl_tpu.graph.function import ModelFunction
        from sparkdl_tpu.persistence import modelfunction_from_payload

        variables = pytree["variables"]
        if "modelFile" in extra:
            base = ModelFunction.from_keras(extra["modelFile"])
            mf = ModelFunction(fn=base.fn, variables=variables,
                               train_fn=base.train_fn,
                               input_names=base.input_names,
                               output_names=base.output_names)
        else:
            mf = modelfunction_from_payload(pickles["modelFunction"],
                                            variables)
        model = cls(modelFunction=mf, trainLosses=extra.get("trainLosses"))
        model.modelFile = extra.get("modelFile")
        if "imageLoader" in pickles:
            model._set(imageLoader=pickles["imageLoader"])
        return model

    def _transform(self, dataset):
        from sparkdl_tpu.transformers.image_file import ImageFileTransformer

        # One persistent transformer per fitted model: repeated transforms
        # (e.g. every CrossValidator evaluation) reuse its engine cache —
        # weights stay device-resident instead of re-uploading per call.
        # Keyed by the params it was built from: Params.copy() shallow-copies
        # __dict__, so a copy with overridden outputCol (or a later set*)
        # must NOT reuse a transformer built for the old columns.  Holding
        # mf/loader in the cache entry keeps their ids from being recycled.
        mf = self.getModelFunction()
        loader = self.getImageLoader()
        key = (self.getInputCol(), self.getOutputCol(), self.getBatchSize(),
               id(mf), id(loader))
        cached = self.__dict__.get("_transformer_cache")
        if cached is not None and cached[0] == key:
            t = cached[1]
        else:
            t = ImageFileTransformer(
                inputCol=self.getInputCol(), outputCol=self.getOutputCol(),
                modelFunction=mf, imageLoader=loader,
                batchSize=self.getBatchSize())
            self.__dict__["_transformer_cache"] = (key, t, mf, loader)
        return t.transform(dataset)


class KerasImageFileEstimator(ImageFileEstimator):
    """Reference-parity flavor: Keras param names + ``modelFile`` input
    (``KerasImageFileEstimator(kerasOptimizer=..., kerasLoss=...,
    kerasFitParams=..., modelFile=...)``)."""

    modelFile = Param(
        "undefined", "modelFile",
        "path to a saved Keras model (.h5/.keras) to fine-tune")

    @keyword_only
    def __init__(self, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 labelCol: Optional[str] = None,
                 modelFile: Optional[str] = None,
                 imageLoader=None,
                 kerasOptimizer=None,
                 kerasLoss: Optional[Any] = None,
                 kerasFitParams: Optional[Dict] = None,
                 batchSize: Optional[int] = None):
        Estimator.__init__(self)
        self._setDefault(batchSize=32, fitParams={},
                         loss="categorical_crossentropy",
                         trainBatchStats=False)
        kw = dict(self._input_kwargs)
        # Map keras-named params onto the native ones.
        if kw.get("kerasOptimizer") is not None:
            kw["optimizer"] = kw.pop("kerasOptimizer")
        else:
            kw.pop("kerasOptimizer", None)
        if kw.get("kerasLoss") is not None:
            kw["loss"] = kw.pop("kerasLoss")
        else:
            kw.pop("kerasLoss", None)
        if kw.get("kerasFitParams") is not None:
            kw["fitParams"] = kw.pop("kerasFitParams")
        else:
            kw.pop("kerasFitParams", None)
        self._set(**kw)

    def getModelFile(self):
        return self.getOrDefault(self.modelFile)

    def getModelFunction(self):
        if not self.isSet(self.modelFunction):
            from sparkdl_tpu.graph.function import ModelFunction

            self._set(modelFunction=ModelFunction.from_keras(
                self.getModelFile()))
        return self.getOrDefault(self.modelFunction)

    def _validateParams(self):
        if not self.isSet(self.modelFunction) and not self.isSet(
                self.getParam("modelFile")):
            raise ValueError(
                "KerasImageFileEstimator requires modelFile (or "
                "modelFunction) to be set")
        return super()._validateParams()
