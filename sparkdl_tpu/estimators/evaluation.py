"""Evaluators for model selection.

The reference leaned on pyspark.ml's evaluators inside ``CrossValidator``
(README tuning example).  These provide the same contract
(``evaluate(dataset) -> float``, ``isLargerBetter``) over our DataFrame.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from sparkdl_tpu.param.params import Param, Params, TypeConverters, keyword_only


class Evaluator(Params):
    def evaluate(self, dataset) -> float:
        raise NotImplementedError

    def isLargerBetter(self) -> bool:
        return True


class MulticlassClassificationEvaluator(Evaluator):
    """accuracy / weightedPrecision / weightedRecall / f1 over prediction vs
    label columns."""

    labelCol = Param("undefined", "labelCol", "true label column",
                     typeConverter=TypeConverters.toString)
    predictionCol = Param("undefined", "predictionCol",
                          "predicted label column",
                          typeConverter=TypeConverters.toString)
    metricName = Param("undefined", "metricName",
                       "accuracy|f1|weightedPrecision|weightedRecall",
                       typeConverter=TypeConverters.toString)

    @keyword_only
    def __init__(self, labelCol: str = "label",
                 predictionCol: str = "prediction",
                 metricName: str = "accuracy"):
        super().__init__()
        self._setDefault(labelCol="label", predictionCol="prediction",
                         metricName="accuracy")
        self._set(**self._input_kwargs)

    def evaluate(self, dataset) -> float:
        y = np.asarray(dataset.column_to_numpy(
            self.getOrDefault(self.labelCol)), dtype=np.int64)
        p = np.asarray(dataset.column_to_numpy(
            self.getOrDefault(self.predictionCol)))
        if p.ndim == 2:
            # probability/score vectors (e.g. ImageFileModel output):
            # argmax to class indices
            p = np.argmax(p, axis=-1)
        p = p.astype(np.int64)
        metric = self.getOrDefault(self.metricName)
        if metric == "accuracy":
            return float((y == p).mean())
        classes = np.unique(np.concatenate([y, p]))
        precisions, recalls, f1s, weights = [], [], [], []
        for c in classes:
            tp = float(((p == c) & (y == c)).sum())
            fp = float(((p == c) & (y != c)).sum())
            fn = float(((p != c) & (y == c)).sum())
            prec = tp / (tp + fp) if tp + fp else 0.0
            rec = tp / (tp + fn) if tp + fn else 0.0
            f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
            precisions.append(prec)
            recalls.append(rec)
            f1s.append(f1)
            weights.append(float((y == c).sum()))
        w = np.asarray(weights) / max(1.0, sum(weights))
        if metric == "weightedPrecision":
            return float(np.dot(w, precisions))
        if metric == "weightedRecall":
            return float(np.dot(w, recalls))
        if metric == "f1":
            return float(np.dot(w, f1s))
        raise ValueError(f"Unknown metricName {metric!r}")


class BinaryClassificationEvaluator(Evaluator):
    """areaUnderROC over a positive-class score column vs binary labels."""

    labelCol = Param("undefined", "labelCol", "true {0,1} label column",
                     typeConverter=TypeConverters.toString)
    rawPredictionCol = Param(
        "undefined", "rawPredictionCol",
        "positive-class score column (float, higher = more positive); a "
        "probability-vector column uses the last element",
        typeConverter=TypeConverters.toString)

    @keyword_only
    def __init__(self, labelCol: str = "label",
                 rawPredictionCol: str = "probability"):
        super().__init__()
        self._setDefault(labelCol="label", rawPredictionCol="probability")
        self._set(**self._input_kwargs)

    def evaluate(self, dataset) -> float:
        y = np.asarray(dataset.column_to_numpy(
            self.getOrDefault(self.labelCol)), dtype=np.int64)
        s = dataset.column_to_numpy(self.getOrDefault(self.rawPredictionCol))
        s = np.asarray(s, dtype=np.float64)
        if s.ndim == 2:
            s = s[:, -1]
        # AUC via rank statistic (ties get average rank)
        order = np.argsort(s, kind="mergesort")
        ranks = np.empty_like(order, dtype=np.float64)
        ranks[order] = np.arange(1, len(s) + 1)
        sorted_s = s[order]
        i = 0
        while i < len(s):
            j = i
            while j + 1 < len(s) and sorted_s[j + 1] == sorted_s[i]:
                j += 1
            if j > i:
                ranks[order[i:j + 1]] = (i + 1 + j + 1) / 2.0
            i = j + 1
        n_pos = int((y == 1).sum())
        n_neg = int((y == 0).sum())
        if not n_pos or not n_neg:
            raise ValueError("AUC needs both positive and negative examples")
        return float(
            (ranks[y == 1].sum() - n_pos * (n_pos + 1) / 2.0)
            / (n_pos * n_neg))
