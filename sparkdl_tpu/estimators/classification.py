"""Classifier heads for transfer learning.

The reference's north-star recipe pairs ``DeepImageFeaturizer`` with a Spark
ML classifier (``LogisticRegression`` in the README's flowers example —
BASELINE.json config #1).  pyspark isn't a dependency here, so the framework
ships its own mesh-trained logistic-regression head with the pyspark.ml
column contract (featuresCol/labelCol/predictionCol/probabilityCol).
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import pyarrow as pa

from sparkdl_tpu.param.params import Param, TypeConverters, keyword_only
from sparkdl_tpu.param.shared import HasLabelCol
from sparkdl_tpu.parallel import mesh as mesh_lib
from sparkdl_tpu.parallel.train import fit_data_parallel
from sparkdl_tpu.transformers.base import Estimator, Model
from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class _HasClassifierCols(HasLabelCol):
    featuresCol = Param("undefined", "featuresCol",
                        "input column of feature vectors",
                        typeConverter=TypeConverters.toString)
    predictionCol = Param("undefined", "predictionCol",
                          "output column of predicted class indices",
                          typeConverter=TypeConverters.toString)
    probabilityCol = Param("undefined", "probabilityCol",
                           "output column of class probabilities",
                           typeConverter=TypeConverters.toString)

    def getFeaturesCol(self):
        return self.getOrDefault(self.featuresCol)

    def getPredictionCol(self):
        return self.getOrDefault(self.predictionCol)

    def getProbabilityCol(self):
        return self.getOrDefault(self.probabilityCol)


class LogisticRegression(Estimator, _HasClassifierCols):
    """Multinomial logistic regression trained data-parallel on the mesh."""

    maxIter = Param("undefined", "maxIter", "training epochs",
                    typeConverter=TypeConverters.toInt)
    regParam = Param("undefined", "regParam", "L2 regularization strength",
                     typeConverter=TypeConverters.toFloat)
    learningRate = Param("undefined", "learningRate", "adam learning rate",
                         typeConverter=TypeConverters.toFloat)
    batchSize = Param("undefined", "batchSize", "global train batch size",
                      typeConverter=TypeConverters.toInt)
    seed = Param("undefined", "seed", "shuffle/init seed",
                 typeConverter=TypeConverters.toInt)
    standardization = Param(
        "undefined", "standardization",
        "standardize features (zero mean / unit variance) before fitting, "
        "folding the scaler back into the returned linear weights — the "
        "pyspark.ml.LogisticRegression default, and what makes tiny- or "
        "wildly-scaled feature columns (e.g. deep-CNN featurizer outputs) "
        "trainable at a fixed learning rate",
        typeConverter=TypeConverters.toBoolean)

    @keyword_only
    def __init__(self, featuresCol: str = "features", labelCol: str = "label",
                 predictionCol: str = "prediction",
                 probabilityCol: str = "probability",
                 maxIter: int = 50, regParam: float = 0.0,
                 learningRate: float = 0.05, batchSize: int = 256,
                 seed: int = 0, standardization: bool = True):
        super().__init__()
        self._setDefault(featuresCol="features", labelCol="label",
                         predictionCol="prediction",
                         probabilityCol="probability", maxIter=50,
                         regParam=0.0, learningRate=0.05, batchSize=256,
                         seed=0, standardization=True)
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, featuresCol: Optional[str] = None,
                  labelCol: Optional[str] = None,
                  predictionCol: Optional[str] = None,
                  probabilityCol: Optional[str] = None,
                  maxIter: Optional[int] = None,
                  regParam: Optional[float] = None,
                  learningRate: Optional[float] = None,
                  batchSize: Optional[int] = None,
                  seed: Optional[int] = None,
                  standardization: Optional[bool] = None):
        return self._set(**self._input_kwargs)

    def _fit(self, dataset) -> "LogisticRegressionModel":
        import jax.numpy as jnp
        import optax

        x = dataset.column_to_numpy(self.getFeaturesCol()).astype(np.float32)
        y = np.asarray(dataset.column_to_numpy(self.getLabelCol()),
                       dtype=np.int32)
        if x.ndim != 2:
            raise ValueError(f"featuresCol must hold vectors; got shape "
                             f"{x.shape}")
        num_classes = int(y.max()) + 1
        mu = np.zeros((x.shape[1],), np.float32)
        sigma = np.ones((x.shape[1],), np.float32)
        if self.getOrDefault(self.standardization):
            mu = x.mean(axis=0)
            sd = x.std(axis=0)
            # constant features train a zero coefficient either way; leave
            # them unscaled so the fold-back below cannot blow up on ~0 std
            sigma = np.where(sd < 1e-7, 1.0, sd).astype(np.float32)
            x = (x - mu) / sigma
        rng = np.random.default_rng(self.getOrDefault(self.seed))
        params = {
            "w": (rng.normal(0, 0.01, (x.shape[1], num_classes))
                  .astype(np.float32)),
            "b": np.zeros((num_classes,), np.float32),
        }
        reg = self.getOrDefault(self.regParam)

        def predict_fn(p, xb):
            return jnp.asarray(xb) @ p["w"] + p["b"]  # logits

        def ce_loss(logits, yb):
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb.astype(jnp.int32))

        # L2 as additive weight decay in the optimizer (keeps the loss
        # per-example clean).
        lr = self.getOrDefault(self.learningRate)
        opt = (optax.chain(optax.add_decayed_weights(reg), optax.adam(lr))
               if reg else optax.adam(lr))

        fitted, losses = fit_data_parallel(
            predict_fn, params, x, y,
            optimizer=opt, loss=ce_loss,
            batch_size=self.getOrDefault(self.batchSize),
            epochs=self.getOrDefault(self.maxIter),
            seed=self.getOrDefault(self.seed))
        logger.info("LogisticRegression fit: %d classes, final loss %.4f",
                    num_classes, losses[-1] if losses else float("nan"))
        if self.getOrDefault(self.standardization):
            # Fold the scaler into the head so the fitted model stays a
            # pure linear (w, b): ((x-mu)/sigma) @ w + b = x @ w' + b'.
            w = np.asarray(fitted["w"])
            fitted = {
                "w": (w / sigma[:, None]).astype(np.float32),
                "b": (np.asarray(fitted["b"])
                      - (mu / sigma) @ w).astype(np.float32),
            }
        model = LogisticRegressionModel(weights=fitted,
                                        numClasses=num_classes)
        model._set(featuresCol=self.getFeaturesCol(),
                   labelCol=self.getLabelCol(),
                   predictionCol=self.getPredictionCol(),
                   probabilityCol=self.getProbabilityCol())
        return model


class LogisticRegressionModel(Model, _HasClassifierCols):
    """Fitted head: adds prediction + probability columns."""

    def __init__(self, weights=None, numClasses: int = 0):
        super().__init__()
        self._setDefault(featuresCol="features", labelCol="label",
                         predictionCol="prediction",
                         probabilityCol="probability")
        self.weights = weights
        self.numClasses = numClasses

    def _persist(self, path):
        return ({"numClasses": int(self.numClasses)},
                {"weights": self.weights}, {})

    @classmethod
    def _restore(cls, extra, pytree, pickles, path):
        return cls(weights=pytree["weights"],
                   numClasses=int(extra["numClasses"]))

    def _transform(self, dataset):
        x = dataset.column_to_numpy(self.getFeaturesCol()).astype(np.float32)
        logits = x @ self.weights["w"] + self.weights["b"]
        z = logits - logits.max(axis=1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(axis=1, keepdims=True)
        pred = p.argmax(axis=1)
        out = dataset.withColumn(
            self.getPredictionCol(), pa.array(pred.astype(np.int64)))
        return out.withColumn(
            self.getProbabilityCol(),
            pa.array([[float(v) for v in row] for row in p],
                     type=pa.list_(pa.float32())))
