"""TPU kernels (pallas) for hot ops the XLA autofuser leaves on the table.

The zoo's compute path is plain jax/flax wherever XLA already emits
optimal code (dense convs ride the MXU untouched); this package holds the
exceptions — ops whose default lowering materializes avoidable HBM
traffic, rewritten as fused pallas kernels with reference-parity jax
fallbacks for CPU/debug.
"""

from sparkdl_tpu.ops.sepconv import (fused_sepconv_flat, pad_to_flat,
                                     sepconv_reference, unflatten)

__all__ = ["fused_sepconv_flat", "pad_to_flat", "sepconv_reference",
           "unflatten"]
