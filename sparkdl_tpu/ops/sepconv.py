"""Fused separable-conv inference kernel (pallas/TPU).

Motivation (measured, PERF.md round 4): Xception — the reference zoo's
depthwise model (``python/sparkdl/transformers/named_image.py``
SUPPORTED_MODELS) — spends its device time in XLA fusions that
materialize the depthwise intermediate in HBM: per separable conv the
default lowering reads the input for the depthwise, writes the depthwise
result, re-reads it for the pointwise matmul, writes the output, and
runs the pre-activation ReLU and inference BatchNorm as extra
elementwise traffic.  On a trace the pure-matmul halves run at MXU peak
(~0.26 ms at 19x19x728, batch 128) while the depthwise-carrying halves
cost 3-5x that.

This kernel computes ``BN(pointwise(depthwise(relu?(x))))`` in ONE HBM
round trip per layer.  The trick that makes it fit Mosaic's alignment
rules is the PADDED-FLAT layout: activations live as ``[N, (H+2)*Wp, C]``
where ``Wp = round_up(W+2, 8)`` — each spatial row padded with the conv
halo and rounded to a full sublane tile.  In that layout a (dy, dx)
kernel-tap shift is a SINGLE sublane rotation of the whole 2-D block
(``pltpu.roll`` by ``dy*Wp+dx``), so the 3x3 depthwise is 9 roll+FMA
passes on the VPU with f32 accumulation, the pointwise is one aligned
MXU ``dot`` over all spatial positions, and the BatchNorm affine
(+ optional post-ReLU) lands on the f32 accumulator.  The epilogue
re-zeros the halo so THE OUTPUT IS ALREADY IN THE NEXT LAYER'S INPUT
LAYOUT: a chain of stride-1 separable convs (Xception's entire middle
flow) runs with no repacking passes between layers at all.

Scope: 3x3, stride 1, SAME, depth_multiplier 1 — every separable conv
in Xception.  Inference only: train mode needs batch statistics, so
callers keep the unfused path there (``models/layers.py``).

The pure-jax twin :func:`sepconv_reference` is the parity oracle and the
non-TPU fallback; ``tests/test_ops_sepconv.py`` pins kernel==reference
on every shape class Xception uses.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def round_up(v: int, m: int) -> int:
    return (v + m - 1) // m * m


def flat_width(w: int) -> int:
    """Padded row length: W + 2 halo columns, rounded to a sublane tile."""
    return round_up(w + 2, 8)


def flat_rows(h: int, row_tile: Optional[int] = None) -> int:
    """Row count of the padded-flat layout: H + 2 halo rows, rounded up to
    a whole number of row tiles when the tiled kernel will consume it."""
    return round_up(h + 2, row_tile) if row_tile else h + 2


def pad_to_flat(x, h: int, w: int, row_tile: Optional[int] = None):
    """[N, H, W, C] -> padded-flat [N, rows*Wp, C] (halo rows/cols = 0).

    ``rows`` is H+2, rounded up to a multiple of ``row_tile`` for the
    row-tiled kernel (extra bottom rows stay zero and are masked)."""
    n, c = x.shape[0], x.shape[-1]
    wp = flat_width(w)
    rows = flat_rows(h, row_tile)
    xp = jnp.pad(x, ((0, 0), (1, rows - h - 1), (1, wp - w - 1), (0, 0)))
    return xp.reshape(n, rows * wp, c)


def unflatten(xf, h: int, w: int):
    """Padded-flat [N, rows*Wp, C] -> [N, H, W, C] (drops halo/pad rows)."""
    n, c = xf.shape[0], xf.shape[-1]
    wp = flat_width(w)
    rows = xf.shape[1] // wp
    return xf.reshape(n, rows, wp, c)[:, 1:h + 1, 1:w + 1, :]


def halo_mask(h: int, w: int):
    """[(H+2)*Wp, 1] f32: 1 on the interior, 0 on the halo — restores the
    kernels' zero-halo contract after a position-wise op touches halo
    positions outside a kernel (e.g. MobileNet's expand matmul on the
    flat layout)."""
    wp = flat_width(w)
    return _interior_mask((h + 2) * wp, wp, h, w).astype(jnp.float32)


def _dw_taps(xt, dwk_ref, wp: int):
    """The 3x3 depthwise as 9 roll+FMA VPU passes over a padded-flat f32
    block: ``out[q] = sum_{dy,dx} in[q + dy*wp + dx] * k[dy,dx]`` — one
    ``pltpu.roll`` (sublane rotation) per tap.  THE layout trick of this
    module, in one place: every kernel variant (plain, tiled, mbconv)
    shares this loop so the delta arithmetic cannot drift."""
    from jax.experimental.pallas import tpu as pltpu

    lo = xt.shape[0]
    acc = jnp.zeros(xt.shape, jnp.float32)
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            # out[q] = in[q + dy*wp + dx]  <=>  roll by the negation
            delta = (-(dy * wp + dx)) % lo
            tap = pltpu.roll(xt, delta, 0) if delta else xt
            acc += tap * dwk_ref[dy + 1, dx + 1, :].astype(jnp.float32)
    return acc


def _interior_mask(n_pos: int, wp: int, h: int, w: int, row0: int = 0):
    """[n_pos, 1] bool: True on interior (non-halo, non-pad) positions of
    a padded-flat block whose first position sits at global row ``row0``
    — the zero-halo output contract, single-sourced for every kernel."""
    pos = jax.lax.broadcasted_iota(jnp.int32, (n_pos, 1), 0)
    r = row0 + pos // wp
    col = pos % wp
    return ((r >= 1) & (r <= h) & (col >= 1) & (col <= w))


def _sepconv_kernel(x_ref, dwk_ref, pw_ref, scale_ref, shift_ref, out_ref,
                    *, h, w, wp, pre_relu, post_relu):
    """One batch element, whole image in padded-flat layout."""
    lo = (h + 2) * wp
    xt = x_ref[0].astype(jnp.float32)  # Mosaic rotate needs 32-bit data
    if pre_relu:
        xt = jnp.maximum(xt, jnp.float32(0))
    acc = _dw_taps(xt, dwk_ref, wp)
    y = jax.lax.dot_general(
        acc.astype(jnp.bfloat16), pw_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    y = y * scale_ref[0, :] + shift_ref[0, :]
    if post_relu:
        y = jnp.maximum(y, 0.0)
    valid = _interior_mask(lo, wp, h, w)
    out_ref[0] = jnp.where(valid, y, 0.0).astype(out_ref.dtype)


# graftlint: allow=SDL007 reason=xf is a chained flat activation the caller may reuse (Xception residual adds); donation would corrupt the residual source
@functools.partial(
    jax.jit,
    static_argnames=("h", "w", "pre_relu", "post_relu", "interpret"))
def _fused_sepconv_tpu(xf, dwk, pw, scale, shift, h, w, pre_relu,
                       post_relu, interpret=False):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, lo, c = xf.shape
    f = pw.shape[-1]
    wp = flat_width(w)
    assert lo == (h + 2) * wp, (lo, h, w, wp)
    kernel = functools.partial(_sepconv_kernel, h=h, w=w, wp=wp,
                               pre_relu=pre_relu, post_relu=post_relu)
    return pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, lo, c), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, 3, c), lambda b: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((c, f), lambda b: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, f), lambda b: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, f), lambda b: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, lo, f), lambda b: (b, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, lo, f), jnp.bfloat16),
        interpret=interpret,
    )(xf.astype(jnp.bfloat16), dwk.astype(jnp.bfloat16),
      pw.astype(jnp.bfloat16),
      scale.reshape(1, f).astype(jnp.float32),
      shift.reshape(1, f).astype(jnp.float32))


def _sepconv_tiled_kernel(above_ref, cur_ref, below_ref, dwk_ref, pw_ref,
                          scale_ref, shift_ref, out_ref,
                          *, h, w, wp, th, pre_relu, post_relu):
    """One (batch, row-tile) cell: TH output rows + 1 halo row each side.

    The working buffer is [(TH+2)*Wp, C] — the previous tile's last row,
    this tile's TH rows, the next tile's first row (fetched as separate
    Wp-row blocks, so halo re-fetch traffic is 2/TH of the tile, not 2x).
    Taps roll the whole buffer like the full-image kernel; outputs are
    computed for the middle TH*Wp positions only, so the roll's wraparound
    touches only the halo slices and every tap a VALID output reads stays
    in-bounds.  Edge tiles fetch clamped (garbage) halo blocks whose
    contributions land exclusively on masked halo/pad rows."""
    import jax.experimental.pallas as pl

    t = pl.program_id(1)
    xt = jnp.concatenate(
        [above_ref[0], cur_ref[0], below_ref[0]], axis=0).astype(jnp.float32)
    if pre_relu:
        xt = jnp.maximum(xt, jnp.float32(0))
    acc = _dw_taps(xt, dwk_ref, wp)
    y = jax.lax.dot_general(
        acc[wp:wp + th * wp].astype(jnp.bfloat16), pw_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    y = y * scale_ref[0, :] + shift_ref[0, :]
    if post_relu:
        y = jnp.maximum(y, 0.0)
    valid = _interior_mask(th * wp, wp, h, w, row0=t * th)
    out_ref[0] = jnp.where(valid, y, 0.0).astype(out_ref.dtype)


# graftlint: allow=SDL007 reason=xf is a chained flat activation the caller may reuse (residual adds), and it feeds all three halo views; donation would corrupt them
@functools.partial(
    jax.jit,
    static_argnames=("h", "w", "th", "pre_relu", "post_relu", "interpret"))
def _fused_sepconv_tpu_tiled(xf, dwk, pw, scale, shift, h, w, th, pre_relu,
                             post_relu, interpret=False):
    """Row-tiled variant for shapes whose full image exceeds VMEM (the
    147^2/74^2 entry-flow sepconvs).  Grid (batch, row-tile); the input
    must be padded-flat with rows = round_up(H+2, th)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, lo, c = xf.shape
    f = pw.shape[-1]
    wp = flat_width(w)
    rows = lo // wp
    assert lo == rows * wp and rows % th == 0, (lo, wp, rows, th)
    assert rows >= h + 2, (rows, h)
    nt = rows // th
    kernel = functools.partial(_sepconv_tiled_kernel, h=h, w=w, wp=wp,
                               th=th, pre_relu=pre_relu, post_relu=post_relu)
    return pl.pallas_call(
        kernel,
        grid=(n, nt),
        in_specs=[
            # prev tile's last row (clamped at the top edge: tile 0 reads
            # row-block 0, whose contribution is masked)
            pl.BlockSpec((1, wp, c),
                         lambda b, t: (b, jnp.maximum(t * th - 1, 0), 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, th * wp, c), lambda b, t: (b, t, 0),
                         memory_space=pltpu.VMEM),
            # next tile's first row (clamped at the bottom edge)
            pl.BlockSpec(
                (1, wp, c),
                lambda b, t: (b, jnp.minimum(t * th + th, rows - 1), 0),
                memory_space=pltpu.VMEM),
            pl.BlockSpec((3, 3, c), lambda b, t: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((c, f), lambda b, t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, f), lambda b, t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, f), lambda b, t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, th * wp, f), lambda b, t: (b, t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, lo, f), jnp.bfloat16),
        interpret=interpret,
    )(xf.astype(jnp.bfloat16), xf.astype(jnp.bfloat16),
      xf.astype(jnp.bfloat16), dwk.astype(jnp.bfloat16),
      pw.astype(jnp.bfloat16),
      scale.reshape(1, f).astype(jnp.float32),
      shift.reshape(1, f).astype(jnp.float32))


def _mbconv_kernel(x_ref, dwk_ref, pw_ref, mid_shift_ref, shift_ref,
                   out_ref, *, h, w, wp):
    """One batch element of the MobileNet inverted-residual tail:
    ``BN(project(relu6(BN(depthwise(x)))))`` with both BN scales already
    FOLDED into ``dwk``/``pw`` by the caller (depthwise and 1x1 convs are
    per-output-channel linear), leaving one mid shift + relu6 clamp
    between the stages and one output shift after the dot."""
    lo = (h + 2) * wp
    xt = x_ref[0].astype(jnp.float32)
    acc = _dw_taps(xt, dwk_ref, wp)
    acc = jnp.clip(acc + mid_shift_ref[0, :], 0.0, 6.0)  # BN shift + relu6
    y = jax.lax.dot_general(
        acc.astype(jnp.bfloat16), pw_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    y = y + shift_ref[0, :]  # project BN (scale folded into pw)
    valid = _interior_mask(lo, wp, h, w)
    out_ref[0] = jnp.where(valid, y, 0.0).astype(out_ref.dtype)


# graftlint: allow=SDL007 reason=xf is a chained flat activation the caller may reuse (MobileNet inverted-residual add); donation would corrupt the residual source
@functools.partial(jax.jit, static_argnames=("h", "w", "interpret"))
def _fused_mbconv_tpu(xf, dwk, pw, mid_shift, shift, h, w,
                      interpret=False):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, lo, c = xf.shape
    f = pw.shape[-1]
    wp = flat_width(w)
    assert lo == (h + 2) * wp, (lo, h, w, wp)
    kernel = functools.partial(_mbconv_kernel, h=h, w=w, wp=wp)
    return pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, lo, c), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, 3, c), lambda b: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((c, f), lambda b: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda b: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, f), lambda b: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, lo, f), lambda b: (b, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, lo, f), jnp.bfloat16),
        interpret=interpret,
    )(xf.astype(jnp.bfloat16), dwk.astype(jnp.bfloat16),
      pw.astype(jnp.bfloat16),
      mid_shift.reshape(1, c).astype(jnp.float32),
      shift.reshape(1, f).astype(jnp.float32))


def mbconv_reference(x, dwk, pw, mid_shift, shift):
    """Pure-jax twin of the mbconv kernel in NHWC (parity oracle /
    non-TPU fallback), on the same FOLDED weights: depthwise 3x3 SAME ->
    +mid_shift -> relu6 -> 1x1 conv -> +shift."""
    cdt = jnp.bfloat16
    c = x.shape[-1]
    y = jax.lax.conv_general_dilated(
        x.astype(cdt), dwk.reshape(3, 3, 1, c).astype(cdt),
        window_strides=(1, 1), padding="SAME", feature_group_count=c,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    y = jnp.clip(y + mid_shift, 0.0, 6.0)
    y = jax.lax.conv_general_dilated(
        y.astype(cdt), pw.reshape(1, 1, c, -1).astype(cdt),
        window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    return (y + shift).astype(cdt)


def fused_mbconv_flat(xf, dwk, pw, mid_shift, shift, h: int, w: int,
                      force: Optional[bool] = None):
    """Fused MobileNet inverted-residual tail on PADDED-FLAT input/output
    (zero-halo contract as :func:`fused_sepconv_flat`).  ``dwk``
    [3,3,C]/[3,3,C,1] and ``pw`` [C,F]/[1,1,C,F] must already carry their
    BN scales (``models.layers.fold_bn_into_conv``); ``mid_shift`` [C] is
    the depthwise BN shift (applied before the relu6 clamp), ``shift``
    [F] the project BN shift (linear bottleneck: no output activation).
    """
    if dwk.ndim == 4:
        dwk = dwk.reshape(3, 3, -1)
    if pw.ndim == 4:
        pw = pw.reshape(pw.shape[-2], pw.shape[-1])
    use_pallas = _on_tpu() if force is None else force
    if use_pallas:
        return _fused_mbconv_tpu(xf, dwk, pw, mid_shift, shift, h, w,
                                 interpret=(force == "interpret"))
    x = unflatten(xf, h, w)
    y = mbconv_reference(x, dwk, pw, mid_shift, shift)
    return pad_to_flat(y, h, w)


def sepconv_reference(x, dwk, pw, scale, shift, pre_relu: bool,
                      post_relu: bool = False):
    """Pure-jax twin of the kernel (parity oracle / non-TPU fallback) in
    NHWC: relu? -> depthwise 3x3 SAME (grouped conv) -> 1x1 conv ->
    y*scale+shift -> relu?.

    ``dwk`` [3,3,C] (keras depthwise kernel, mult 1, squeezed), ``pw``
    [C,F], ``scale``/``shift`` [F] — the inference-mode BatchNorm affine:
    scale = gamma / sqrt(var + eps), shift = beta - mean * scale.
    """
    cdt = jnp.bfloat16
    xt = x.astype(cdt)
    if pre_relu:
        xt = jax.nn.relu(xt)
    c = x.shape[-1]
    y = jax.lax.conv_general_dilated(
        xt, dwk.reshape(3, 3, 1, c).astype(cdt),
        window_strides=(1, 1), padding="SAME", feature_group_count=c,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    y = jax.lax.conv_general_dilated(
        y, pw.reshape(1, 1, c, -1).astype(cdt),
        window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    y = y * scale + shift
    if post_relu:
        y = jax.nn.relu(y)
    return y.astype(cdt)


def _on_tpu() -> bool:
    # capability probe: jax raises RuntimeError when no backend can
    # initialize — any other exception type is a real bug and surfaces
    try:
        return jax.default_backend() in ("tpu", "axon")
    except RuntimeError:
        return False


def fused_sepconv_flat(xf, dwk, pw, scale, shift, h: int, w: int,
                       pre_relu: bool = False, post_relu: bool = False,
                       force: Optional[bool] = None,
                       row_tile: Optional[int] = None):
    """Fused sepconv+BN on PADDED-FLAT input/output (see module doc).

    ``xf`` [N, rows*Wp, C] with zeroed halo; returns [N, rows*Wp, F]
    with zeroed halo — directly consumable by the next stride-1 sepconv.
    ``dwk`` [3,3,C] or [3,3,C,1]; ``pw`` [C,F] or [1,1,C,F].  Dispatches
    to the pallas kernel on TPU backends, to the NHWC reference (with
    pack/unpack) elsewhere; ``force`` overrides, and
    ``force="interpret"`` runs the REAL kernel through the pallas
    interpreter (CI parity on CPU).

    ``row_tile``: process TH rows per grid cell instead of the whole
    image — required when (H+2)*Wp*C exceeds VMEM (the 147^2/74^2
    entry-flow shapes).  The input must have rows = round_up(H+2, TH)
    (``pad_to_flat(..., row_tile=TH)``); chains of equal-shape sepconvs
    still need no repacking.
    """
    if dwk.ndim == 4:
        dwk = dwk.reshape(3, 3, -1)
    if pw.ndim == 4:
        pw = pw.reshape(pw.shape[-2], pw.shape[-1])
    use_pallas = _on_tpu() if force is None else force
    if use_pallas:
        interpret = (force == "interpret")
        if row_tile:
            return _fused_sepconv_tpu_tiled(xf, dwk, pw, scale, shift, h,
                                            w, row_tile, pre_relu,
                                            post_relu, interpret=interpret)
        return _fused_sepconv_tpu(xf, dwk, pw, scale, shift, h, w,
                                  pre_relu, post_relu, interpret=interpret)
    rows = xf.shape[1] // flat_width(w)
    x = unflatten(xf, h, w)
    y = sepconv_reference(x, dwk, pw, scale, shift, pre_relu, post_relu)
    yf = pad_to_flat(y, h, w)
    wp = flat_width(w)
    if rows > h + 2:  # preserve the caller's row padding
        yf = jnp.pad(yf, ((0, 0), (0, (rows - h - 2) * wp), (0, 0)))
    return yf
