"""Fused separable-conv inference kernel (pallas/TPU).

Motivation (measured, PERF.md round 4): Xception — the reference zoo's
depthwise model (``python/sparkdl/transformers/named_image.py``
SUPPORTED_MODELS) — spends its device time in XLA fusions that
materialize the depthwise intermediate in HBM: per separable conv the
default lowering reads the input for the depthwise, writes the depthwise
result, re-reads it for the pointwise matmul, writes the output, and
runs the pre-activation ReLU and inference BatchNorm as extra
elementwise traffic.  On a trace the pure-matmul halves run at MXU peak
(~0.26 ms at 19x19x728, batch 128) while the depthwise-carrying halves
cost 3-5x that.

This kernel computes ``BN(pointwise(depthwise(relu?(x))))`` in ONE HBM
round trip per layer.  The trick that makes it fit Mosaic's alignment
rules is the PADDED-FLAT layout: activations live as ``[N, (H+2)*Wp, C]``
where ``Wp = round_up(W+2, 8)`` — each spatial row padded with the conv
halo and rounded to a full sublane tile.  In that layout a (dy, dx)
kernel-tap shift is a SINGLE sublane rotation of the whole 2-D block
(``pltpu.roll`` by ``dy*Wp+dx``), so the 3x3 depthwise is 9 roll+FMA
passes on the VPU with f32 accumulation, the pointwise is one aligned
MXU ``dot`` over all spatial positions, and the BatchNorm affine
(+ optional post-ReLU) lands on the f32 accumulator.  The epilogue
re-zeros the halo so THE OUTPUT IS ALREADY IN THE NEXT LAYER'S INPUT
LAYOUT: a chain of stride-1 separable convs (Xception's entire middle
flow) runs with no repacking passes between layers at all.

Scope: 3x3, stride 1, SAME, depth_multiplier 1 — every separable conv
in Xception.  Inference only: train mode needs batch statistics, so
callers keep the unfused path there (``models/layers.py``).

The pure-jax twin :func:`sepconv_reference` is the parity oracle and the
non-TPU fallback; ``tests/test_ops_sepconv.py`` pins kernel==reference
on every shape class Xception uses.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def round_up(v: int, m: int) -> int:
    return (v + m - 1) // m * m


def flat_width(w: int) -> int:
    """Padded row length: W + 2 halo columns, rounded to a sublane tile."""
    return round_up(w + 2, 8)


def pad_to_flat(x, h: int, w: int):
    """[N, H, W, C] -> padded-flat [N, (H+2)*Wp, C] (halo rows/cols = 0)."""
    n, c = x.shape[0], x.shape[-1]
    wp = flat_width(w)
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, wp - w - 1), (0, 0)))
    return xp.reshape(n, (h + 2) * wp, c)


def unflatten(xf, h: int, w: int):
    """Padded-flat [N, (H+2)*Wp, C] -> [N, H, W, C] (drops the halo)."""
    n, c = xf.shape[0], xf.shape[-1]
    wp = flat_width(w)
    return xf.reshape(n, h + 2, wp, c)[:, 1:h + 1, 1:w + 1, :]


def _sepconv_kernel(x_ref, dwk_ref, pw_ref, scale_ref, shift_ref, out_ref,
                    *, h, w, wp, pre_relu, post_relu):
    """One batch element, whole image in padded-flat layout."""
    from jax.experimental.pallas import tpu as pltpu

    lo = (h + 2) * wp
    xt = x_ref[0].astype(jnp.float32)  # Mosaic rotate needs 32-bit data
    if pre_relu:
        xt = jnp.maximum(xt, jnp.float32(0))
    acc = jnp.zeros(xt.shape, jnp.float32)
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            # out[q] = in[q + dy*wp + dx]  <=>  jnp.roll by the negation
            delta = (-(dy * wp + dx)) % lo
            tap = pltpu.roll(xt, delta, 0) if delta else xt
            acc += tap * dwk_ref[dy + 1, dx + 1, :].astype(jnp.float32)
    y = jax.lax.dot_general(
        acc.astype(jnp.bfloat16), pw_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    y = y * scale_ref[0, :] + shift_ref[0, :]
    if post_relu:
        y = jnp.maximum(y, 0.0)
    rows = jax.lax.broadcasted_iota(jnp.int32, (lo, 1), 0)
    r, col = rows // wp, rows % wp
    valid = ((r >= 1) & (r <= h) & (col >= 1) & (col <= w))
    out_ref[0] = jnp.where(valid, y, 0.0).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("h", "w", "pre_relu", "post_relu", "interpret"))
def _fused_sepconv_tpu(xf, dwk, pw, scale, shift, h, w, pre_relu,
                       post_relu, interpret=False):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, lo, c = xf.shape
    f = pw.shape[-1]
    wp = flat_width(w)
    assert lo == (h + 2) * wp, (lo, h, w, wp)
    kernel = functools.partial(_sepconv_kernel, h=h, w=w, wp=wp,
                               pre_relu=pre_relu, post_relu=post_relu)
    return pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, lo, c), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, 3, c), lambda b: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((c, f), lambda b: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, f), lambda b: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, f), lambda b: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, lo, f), lambda b: (b, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, lo, f), jnp.bfloat16),
        interpret=interpret,
    )(xf.astype(jnp.bfloat16), dwk.astype(jnp.bfloat16),
      pw.astype(jnp.bfloat16),
      scale.reshape(1, f).astype(jnp.float32),
      shift.reshape(1, f).astype(jnp.float32))


def sepconv_reference(x, dwk, pw, scale, shift, pre_relu: bool,
                      post_relu: bool = False):
    """Pure-jax twin of the kernel (parity oracle / non-TPU fallback) in
    NHWC: relu? -> depthwise 3x3 SAME (grouped conv) -> 1x1 conv ->
    y*scale+shift -> relu?.

    ``dwk`` [3,3,C] (keras depthwise kernel, mult 1, squeezed), ``pw``
    [C,F], ``scale``/``shift`` [F] — the inference-mode BatchNorm affine:
    scale = gamma / sqrt(var + eps), shift = beta - mean * scale.
    """
    cdt = jnp.bfloat16
    xt = x.astype(cdt)
    if pre_relu:
        xt = jax.nn.relu(xt)
    c = x.shape[-1]
    y = jax.lax.conv_general_dilated(
        xt, dwk.reshape(3, 3, 1, c).astype(cdt),
        window_strides=(1, 1), padding="SAME", feature_group_count=c,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    y = jax.lax.conv_general_dilated(
        y, pw.reshape(1, 1, c, -1).astype(cdt),
        window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    y = y * scale + shift
    if post_relu:
        y = jax.nn.relu(y)
    return y.astype(cdt)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def fused_sepconv_flat(xf, dwk, pw, scale, shift, h: int, w: int,
                       pre_relu: bool = False, post_relu: bool = False,
                       force: Optional[bool] = None):
    """Fused sepconv+BN on PADDED-FLAT input/output (see module doc).

    ``xf`` [N, (H+2)*Wp, C] with zeroed halo; returns [N, (H+2)*Wp, F]
    with zeroed halo — directly consumable by the next stride-1 sepconv.
    ``dwk`` [3,3,C] or [3,3,C,1]; ``pw`` [C,F] or [1,1,C,F].  Dispatches
    to the pallas kernel on TPU backends, to the NHWC reference (with
    pack/unpack) elsewhere; ``force`` overrides, and
    ``force="interpret"`` runs the REAL kernel through the pallas
    interpreter (CI parity on CPU).
    """
    if dwk.ndim == 4:
        dwk = dwk.reshape(3, 3, -1)
    if pw.ndim == 4:
        pw = pw.reshape(pw.shape[-2], pw.shape[-1])
    use_pallas = _on_tpu() if force is None else force
    if use_pallas:
        return _fused_sepconv_tpu(xf, dwk, pw, scale, shift, h, w,
                                  pre_relu, post_relu,
                                  interpret=(force == "interpret"))
    x = unflatten(xf, h, w)
    y = sepconv_reference(x, dwk, pw, scale, shift, pre_relu, post_relu)
    return pad_to_flat(y, h, w)
