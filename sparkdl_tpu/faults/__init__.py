"""sparkdl_tpu.faults — deterministic fault injection for the scoring
stack.

The reference leaned on Spark's task-retry/straggler machinery for
resilience (SURVEY.md §5; ``utils/retry`` names the analogy); this
package is the other half of that story: a way to PROVE what the
engine, pipeline, serving, probe, and host-I/O layers do when the
device, a worker thread, or the relay dies mid-flight — without waiting
for the flaky relay to do it for real.

* :class:`FaultPlan` — a seeded, deterministic set of rules, parsed
  from a ``SPARKDL_FAULTS`` spec string (grammar in
  :mod:`~sparkdl_tpu.faults.spec`) or constructed directly in tests.
* :func:`inject` — the hook threaded through the hot paths at named
  sites (:data:`~sparkdl_tpu.faults.spec.SITES`).  With no plan active
  it is one global read + ``None`` check (near-zero, the
  ``SPARKDL_TRACE`` disabled-path budget, guarded by run-tests.sh).
* The error taxonomy (:mod:`~sparkdl_tpu.faults.errors`): transient
  (retryable), fatal/decode (deterministic, ``NON_RETRYABLE``), dead
  (sticky — the circuit-breaker trigger).

Quick use::

    from sparkdl_tpu import faults

    plan = faults.FaultPlan.parse(
        "seed=7;engine.dispatch:error:exc=transient,at=2")
    with faults.active(plan):
        run_workload()
    assert plan.fired("engine.dispatch") == 1

or, process-wide, ``SPARKDL_FAULTS="seed=7;engine.dispatch:error:at=2"``.
"""

from sparkdl_tpu.faults.errors import (InjectedDeadDeviceError,
                                       InjectedDecodeError, InjectedFault,
                                       InjectedFatalError,
                                       InjectedTransientError)
from sparkdl_tpu.faults.plan import (FaultPlan, active, clear, configure,
                                     configure_from_env, current_spec,
                                     get_plan, has_rules, inject)
from sparkdl_tpu.faults.sites import SITE_HELP, validate_site
from sparkdl_tpu.faults.spec import (ACTIONS, SITES, FaultRule,
                                     faults_from_env, format_spec,
                                     parse_spec)

__all__ = [
    "FaultPlan",
    "FaultRule",
    "SITES",
    "SITE_HELP",
    "validate_site",
    "ACTIONS",
    "inject",
    "has_rules",
    "active",
    "configure",
    "configure_from_env",
    "clear",
    "get_plan",
    "current_spec",
    "parse_spec",
    "format_spec",
    "faults_from_env",
    "InjectedFault",
    "InjectedTransientError",
    "InjectedDeadDeviceError",
    "InjectedFatalError",
    "InjectedDecodeError",
]
