"""Deterministic, seeded fault-injection plans + the ``inject`` hook.

The chaos analog of ``SPARKDL_TRACE``: hot paths call
:func:`inject("site")` at named injection points; with no plan active
that is ONE module-global read and a ``None`` check (near-zero, same
budget as the tracer's disabled path), and with a plan active the
site's rules decide — deterministically, from the plan seed and the
site's call counter — whether to raise, stall, or mark the site dead.

Determinism contract: given the same ``(seed, spec)`` and the same
per-site call ORDER, a plan replays the identical firing sequence.
Probabilistic rules (``p=``) draw from a per-rule ``random.Random``
seeded from ``(seed, site, rule index)``, never from global state, so
two plans with the same spec fire identically even when other code
consumes the global RNG in between.

Thread model: ``fire`` takes the plan lock (counters + RNG draws are
shared state); injection sites sit on paths where a lock per call is
noise next to the device/decode work around them, and the DISABLED
path — the only one production traffic sees — takes no lock at all.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

from sparkdl_tpu.analysis import lockcheck
from sparkdl_tpu.faults.errors import (InjectedDeadDeviceError,
                                       InjectedDecodeError, InjectedFault,
                                       InjectedFatalError,
                                       InjectedTransientError)
from sparkdl_tpu.obs.flight import emit as flight_emit
from sparkdl_tpu.faults.sites import validate_site
from sparkdl_tpu.faults.spec import (FaultRule, faults_from_env, format_spec,
                                     parse_spec)

_EXC_BY_KIND = {
    "transient": InjectedTransientError,
    "fatal": InjectedFatalError,
    "dead": InjectedDeadDeviceError,
    "decode": InjectedDecodeError,
}


def _make_exc(kind: str, message: str, site: str, rule: str,
              retry_after_s: float) -> BaseException:
    if kind == "queue_full":
        # Lazy import: faults is a leaf layer the serving stack imports;
        # the reverse edge exists only when a queue_full rule fires.
        from sparkdl_tpu.serving.errors import QueueFullError

        exc = QueueFullError(message, retry_after_s=retry_after_s)
        exc.site = site  # type: ignore[attr-defined]
        exc.rule = rule  # type: ignore[attr-defined]
        return exc
    return _EXC_BY_KIND[kind](message, site=site, rule=rule)


class FaultPlan:
    """A seeded set of :class:`FaultRule` s with per-rule firing state.

    Construct directly in tests (``FaultPlan([FaultRule(...)], seed=7)``
    or from a spec string (``FaultPlan.parse("seed=7;engine.dispatch:"
    "error:at=2")``), then :func:`configure` it — or use the
    :func:`active` context manager, which restores the previous plan on
    exit.
    """

    def __init__(self, rules: Sequence[Union[FaultRule, str]] = (),
                 seed: int = 0):
        self.seed = int(seed)
        self.rules: List[FaultRule] = []
        for r in rules:
            if isinstance(r, str):
                embedded_seed, parsed = parse_spec(r)
                if embedded_seed:
                    # a "seed=N;..." clause inside a rule string must
                    # mean what it means in parse(): determinism parity
                    # between the two construction forms
                    self.seed = embedded_seed
                self.rules.extend(parsed)
            else:
                # re-validate even pre-built FaultRule objects: a rule
                # whose site was mutated after construction must fail
                # HERE, at plan build, not silently never fire
                validate_site(r.site)
                self.rules.append(r)
        self._lock = lockcheck.named_lock("faults.plan")
        self._site_calls: Dict[str, int] = {}
        self._fired: Dict[int, int] = {}       # rule index -> firings
        self._sticky_dead: Dict[str, str] = {}  # site -> clause that died
        import random

        self._rngs = [random.Random(f"{self.seed}:{r.site}:{i}")
                      for i, r in enumerate(self.rules)]

    # -- construction ------------------------------------------------------
    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        seed, rules = parse_spec(spec)
        return cls(rules, seed=seed)

    @property
    def spec(self) -> str:
        """Canonical spec string (round-trips through :meth:`parse`)."""
        return format_spec(self.seed, self.rules)

    # -- introspection -----------------------------------------------------
    def sites(self) -> set:
        return {r.site for r in self.rules}

    def has_rules(self, site: str) -> bool:
        return any(r.site == site for r in self.rules)

    def stats(self) -> Dict[str, Dict[str, int]]:
        """Per-site ``{"calls": N, "fired": N}`` — what chaos tests
        assert to prove the planned faults actually fired."""
        with self._lock:
            out: Dict[str, Dict[str, int]] = {}
            for site, calls in self._site_calls.items():
                out[site] = {"calls": calls, "fired": 0}
            for i, r in enumerate(self.rules):
                if self._fired.get(i):
                    out.setdefault(r.site, {"calls": 0, "fired": 0})
                    out[r.site]["fired"] += self._fired[i]
            return out

    def fired(self, site: Optional[str] = None) -> int:
        """Total rule firings (optionally for one site)."""
        with self._lock:
            return sum(n for i, n in self._fired.items()
                       if site is None or self.rules[i].site == site)

    # -- the hot hook ------------------------------------------------------
    def fire(self, site: str, ctx: Dict[str, Any]) -> None:
        """Advance ``site``'s call counter and run any due rules: raise
        (``error``/``dead``), stall (``sleep``, then keep evaluating), or
        pass.  Called only while the plan is configured.  Every rule
        firing is recorded as a ``fault.fired`` flight event (outside
        the plan lock, BEFORE the sleep/raise takes effect — so the
        black box shows the injected cause ahead of its consequences)."""
        sleep_s = 0.0
        raise_exc: Optional[BaseException] = None
        fired_rules: List[tuple] = []
        with self._lock:
            n = self._site_calls.get(site, 0) + 1
            self._site_calls[site] = n
            dead_clause = self._sticky_dead.get(site)
            if dead_clause is not None:
                raise_exc = InjectedDeadDeviceError(
                    f"injected dead device at {site} (sticky since rule "
                    f"[{dead_clause}] fired; call #{n})",
                    site=site, rule=dead_clause)
            else:
                for i, r in enumerate(self.rules):
                    if r.site != site:
                        continue
                    if not self._due(i, r, n):
                        continue
                    self._fired[i] = self._fired.get(i, 0) + 1
                    fired_rules.append((r.clause, r.action, n))
                    msg = (f"injected {r.action} fault at {site} "
                           f"(rule [{r.clause}], call #{n})")
                    if r.action == "sleep":
                        sleep_s += float(r.params.get("ms", 100.0)) / 1e3
                        continue
                    if r.action == "dead":
                        self._sticky_dead[site] = r.clause
                        raise_exc = InjectedDeadDeviceError(
                            msg, site=site, rule=r.clause)
                        break
                    kind = r.params.get("exc", "transient")
                    raise_exc = _make_exc(
                        kind, msg, site, r.clause,
                        retry_after_s=float(r.params.get("retry_after",
                                                         0.05)))
                    break
        for clause, action, call_n in fired_rules:
            flight_emit("fault.fired", site=site, rule=clause,
                        action=action, call=call_n)
        if sleep_s:
            time.sleep(sleep_s)
        if raise_exc is not None:
            raise raise_exc

    def _due(self, i: int, r: FaultRule, n: int) -> bool:
        """Schedule evaluation for rule ``i`` at site call ``n`` — caller
        holds the lock."""
        times = r.params.get("times")
        if times is not None and self._fired.get(i, 0) >= int(times):
            return False
        at = r.params.get("at")
        if at is not None and n != int(at):
            return False
        every = r.params.get("every")
        if every is not None and n % max(1, int(every)) != 0:
            return False
        p = r.params.get("p")
        if p is not None and self._rngs[i].random() >= float(p):
            return False
        return True


# -- module singleton (the SPARKDL_TRACE pattern) --------------------------
_UNSET = object()   # before the first inject() consults SPARKDL_FAULTS
_PLAN: Any = _UNSET
_PLAN_LOCK = lockcheck.named_lock("faults.configure")


def inject(site: str, **ctx: Any) -> None:
    """The injection hook hot paths call at a named site.

    Disabled path (no plan configured, ``SPARKDL_FAULTS`` unset): one
    global read + identity check + return — guarded by the run-tests.sh
    overhead stage.  The env var is consulted exactly once, on the first
    call, after which the global is either a plan or ``None``.
    """
    plan = _PLAN
    if plan is None:
        return
    if plan is _UNSET:
        plan = configure_from_env()
        if plan is None:
            return
    plan.fire(site, ctx)


def get_plan() -> Optional[FaultPlan]:
    """The active plan (resolving the env on first ask), or None."""
    plan = _PLAN
    if plan is _UNSET:
        return configure_from_env()
    return plan


def has_rules(site: str) -> bool:
    """True iff an active plan has rules for ``site`` — the cheap query
    call sites use to route around fast paths the injection point cannot
    reach (e.g. the native decode core)."""
    plan = get_plan()
    return plan is not None and plan.has_rules(site)


def configure(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install ``plan`` as the process fault plan (None disables)."""
    global _PLAN
    with _PLAN_LOCK:
        _PLAN = plan
    return plan


def clear() -> None:
    """Disable injection (and stop consulting the env until
    :func:`configure_from_env` is called again)."""
    configure(None)


def configure_from_env() -> Optional[FaultPlan]:
    """(Re-)configure from ``SPARKDL_FAULTS``; returns the plan or None
    when the variable is unset/empty."""
    raw = faults_from_env()
    return configure(FaultPlan.parse(raw) if raw else None)


def current_spec() -> Optional[str]:
    """Canonical spec of the active plan (bench lines stamp this as
    ``faults``), or None when injection is off."""
    plan = get_plan()
    return plan.spec if plan is not None else None


@contextlib.contextmanager
def active(plan: FaultPlan) -> Iterator[FaultPlan]:
    """Scope ``plan`` to a ``with`` block, restoring whatever was
    configured before (the test-suite idiom)."""
    global _PLAN
    with _PLAN_LOCK:
        prev = _PLAN
        _PLAN = plan
    try:
        yield plan
    finally:
        with _PLAN_LOCK:
            _PLAN = prev
