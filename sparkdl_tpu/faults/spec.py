"""``SPARKDL_FAULTS`` spec grammar: parse / canonical form.

Grammar (documented in README "Failure model")::

    spec    := clause (";" clause)*
    clause  := "seed=" INT | rule
    rule    := SITE ":" ACTION [":" param ("," param)*]
    param   := KEY "=" VALUE

* ``SITE`` — a registered injection point (:data:`SITES`); a typo'd
  site would otherwise silently never fire, so unknown sites are a
  parse error.
* ``ACTION`` — ``error`` (raise), ``sleep`` (stall ``ms`` then
  continue), ``dead`` (raise once scheduled, then STICKY: every later
  call at the site keeps raising — the dead-device mode).
* schedule params (all optional, AND-combined):
  ``at=N`` fires on exactly the Nth call to the site (1-based);
  ``every=N`` fires on every Nth call; ``p=F`` fires with probability F
  per call, drawn from the rule's OWN seeded RNG so a given
  ``(seed, spec)`` replays the identical firing sequence; ``times=K``
  caps total firings.  With no schedule params the rule fires on every
  call.
* action params: ``ms=F`` (sleep duration, default 100);
  ``exc=transient|fatal|dead|decode|queue_full`` picks the raised type
  for ``error`` rules (default ``transient``); ``retry_after=F``
  (seconds hint carried by ``queue_full``).

Example::

    SPARKDL_FAULTS="seed=7;engine.dispatch:error:exc=transient,at=2;\
serving.admit:error:exc=queue_full,times=3;pipeline.gather:error:at=1"
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# The canonical injection-point registry lives in
# sparkdl_tpu/faults/sites.py (one table, read statically by graftlint
# SDL004); SITES is re-exported here for compatibility with every
# caller that imported it from the spec module since PR 4.
from sparkdl_tpu.faults.sites import SITE_HELP, SITES, validate_site

ACTIONS = ("error", "sleep", "dead")
EXC_KINDS = ("transient", "fatal", "dead", "decode", "queue_full")

_INT_PARAMS = ("at", "every", "times")
_FLOAT_PARAMS = ("p", "ms", "retry_after")


@dataclass
class FaultRule:
    """One parsed rule clause.  Plain data — firing counters live in the
    :class:`~sparkdl_tpu.faults.plan.FaultPlan` so a rule list can be
    reused across plans/replays."""

    site: str
    action: str
    params: Dict[str, float] = field(default_factory=dict)

    def __post_init__(self):
        validate_site(self.site)
        if self.action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {self.action!r} (site {self.site}); "
                f"known actions: {', '.join(ACTIONS)}")
        exc = self.params.get("exc")
        if exc is not None and exc not in EXC_KINDS:
            raise ValueError(
                f"unknown exc kind {exc!r} (site {self.site}); known: "
                f"{', '.join(EXC_KINDS)}")
        if exc == "queue_full" and not self.site.startswith(("serving.",
                                                             "fleet.")):
            # QueueFullError is not an InjectedFault: outside the serving
            # and fleet admission layers it would escape every `except
            # InjectedFault` site handler and crash the host path
            # instead of testing it
            raise ValueError(
                f"exc=queue_full is only meaningful at serving.*/fleet.* "
                f"sites, not {self.site!r}")
        for k in self.params:
            if k != "exc" and k not in _INT_PARAMS + _FLOAT_PARAMS:
                raise ValueError(
                    f"unknown fault param {k!r} (site {self.site}); known: "
                    f"{', '.join(_INT_PARAMS + _FLOAT_PARAMS + ('exc',))}")

    @property
    def clause(self) -> str:
        """Canonical spec text for this rule (the round-trippable form
        error messages and ``format_spec`` use)."""
        if not self.params:
            return f"{self.site}:{self.action}"
        parts = []
        for k in sorted(self.params):
            v = self.params[k]
            if isinstance(v, float) and v == int(v) and k not in ("p",):
                v = int(v)
            parts.append(f"{k}={v}")
        return f"{self.site}:{self.action}:{','.join(parts)}"


def parse_spec(text: str) -> Tuple[int, List[FaultRule]]:
    """Parse a ``SPARKDL_FAULTS`` spec string into ``(seed, rules)``.

    Raises ``ValueError`` with the offending clause on any grammar
    error — a malformed chaos spec must fail loudly at configure time,
    never degrade into a no-fault run.
    """
    seed = 0
    rules: List[FaultRule] = []
    for raw in (text or "").split(";"):
        clause = raw.strip()
        if not clause:
            continue
        if clause.startswith("seed="):
            try:
                seed = int(clause[len("seed="):])
            except ValueError:
                raise ValueError(f"bad seed clause {clause!r}") from None
            continue
        bits = clause.split(":", 2)
        if len(bits) < 2:
            raise ValueError(
                f"bad fault clause {clause!r}: expected "
                f"'site:action[:k=v,...]' or 'seed=N'")
        site, action = bits[0].strip(), bits[1].strip()
        params: Dict[str, float] = {}
        if len(bits) == 3 and bits[2].strip():
            for pair in bits[2].split(","):
                if "=" not in pair:
                    raise ValueError(
                        f"bad fault param {pair!r} in clause {clause!r}")
                k, v = (s.strip() for s in pair.split("=", 1))
                try:
                    if k == "exc":
                        params[k] = v  # type: ignore[assignment]
                    elif k in _INT_PARAMS:
                        params[k] = int(v)
                    else:
                        # floats, plus unknown keys coerced so FaultRule
                        # validation can name them
                        params[k] = float(v)
                except ValueError:
                    # the env is parsed lazily at the first inject(), so
                    # a bare int()/float() error would surface from deep
                    # inside a hot path with no hint WHAT failed
                    raise ValueError(
                        f"bad fault param value {pair!r} in clause "
                        f"{clause!r}") from None
        rules.append(FaultRule(site=site, action=action, params=params))
    return seed, rules


def format_spec(seed: int, rules: List[FaultRule]) -> str:
    """Canonical spec string for ``(seed, rules)`` — what bench lines
    stamp as ``faults: <spec>`` so an injected-chaos run is
    self-describing."""
    clauses = [f"seed={seed}"] if seed else []
    clauses.extend(r.clause for r in rules)
    return ";".join(clauses)


def faults_from_env() -> Optional[str]:
    """The raw ``SPARKDL_FAULTS`` value, or None when unset/empty — the
    one env read every gate shares."""
    import os

    raw = os.environ.get("SPARKDL_FAULTS", "").strip()
    return raw or None
