"""Canonical fault-site registry — THE one list of injection points.

Every ``faults.inject("<site>")``/``has_rules("<site>")`` call in the
stack and every site named in a ``SPARKDL_FAULTS`` spec must come from
this table.  Both halves are enforced: spec parsing and
``FaultPlan``/``FaultRule`` construction reject unknown sites at
CONSTRUCTION time (:func:`validate_site`), and graftlint rule SDL004
statically checks the code-side strings against this file (read with
``ast``, never imported) — so a typo'd site can neither be spec'd nor
silently compiled into a hot path where it would never fire.

Keep the table sorted by layer; the value is the one-line operator
description ``tools/graftlint.py --list-rules``-style tooling and the
README's failure-model table can render.
"""

from __future__ import annotations

from typing import Tuple

#: site -> operator-facing description of what fires there.
SITE_HELP = {
    "engine.dispatch": "InferenceEngine H2D + program launch attempt",
    "engine.gather": ("InferenceEngine result force (D2H) — where a "
                      "dying device surfaces under async dispatch"),
    "pipeline.prepare": "PipelinedRunner host-prepare stage loop",
    "pipeline.dispatch": "PipelinedRunner dispatch stage loop",
    "pipeline.gather": "PipelinedRunner gather stage loop",
    "serving.admit": "DynamicBatcher.submit admission",
    "serving.model": "Server model-call attempt (watchdog-timed)",
    "batch.topoff": ("ragged top-off pull in Server._execute — a sleep "
                     "rule holds a forming batch open before dispatch; "
                     "an error rule aborts the pull, which must degrade "
                     "to baseline padding (base batch still dispatches, "
                     "no request lost)"),
    "compile.cache": ("persistent compile-cache configure/validation "
                      "(parallel.compile_cache) — an injected error is "
                      "a corrupt cache dir/manifest, which must degrade "
                      "to fresh compiles, never take down serving"),
    "cache.hit": ("InferenceCache hit return path — an injected error "
                  "corrupts the copy handed back, which the output-"
                  "digest re-check must catch (entry invalidated, "
                  "request re-dispatched)"),
    "cache.stampede": ("single-flight leader dispatch window in "
                       "Server.submit — a sleep rule holds the leader "
                       "open so follower coalescing is observable; an "
                       "error rule is a leader failure every follower "
                       "must see (and that must cache nothing)"),
    "head.dispatch": ("HeadBank vmapped head-pass dispatch (gather-by-"
                      "tenant-index over the stacked bank) — an error "
                      "rule fails that head pass only; the backbone "
                      "program and the bank state are untouched"),
    "head.swap": ("head-bank mutation attempt (add/swap/evict of one "
                  "tenant's head) — fires BEFORE any state changes, so "
                  "an injected fault aborts the mutation with the bank "
                  "unchanged and the old head still serving"),
    "fleet.admit": "Fleet front-door admission (tenant quota/priority gate)",
    "fleet.canary": "Fleet canary routing decision during a rollout",
    "fleet.swap": "Fleet version swap attempt (rollout promote/rollback)",
    "stream.source": ("StreamSource poll mid-iteration (a sleep is a "
                      "stalled source the watchdog must catch; a "
                      "transient error is a flaky feed the re-poll "
                      "backoff absorbs)"),
    "stream.commit": ("StreamScorer between output-artifact write and "
                      "journal commit — the exactly-once crash window"),
    "stream.resume": ("journal replay of an uncommitted chunk at "
                      "restart (redelivery-time failure)"),
    "twin.tick": ("traffic-twin virtual tick boundary — a sleep rule "
                  "stretches wall time without moving virtual time "
                  "(the determinism contract must hold); an error rule "
                  "is a control-plane crash mid-day"),
    "twin.arrival": ("traffic-twin per-arrival submit into the real "
                     "fleet — a transient error rule drops that "
                     "arrival at the door (scored as a shed, the "
                     "scenario replay stays deterministic)"),
    "probe.device": "__graft_entry__ device-count relay probe",
    "bench.relay_probe": "bench.py relay profile probe",
    "io.decode": "host image decode, per row",
    "cost.attr": ("cost-ledger attribution of a settled batch or cache "
                  "hit (observability: callers degrade to an error "
                  "counter, a ledger failure never fails the request)"),
}

#: Registered injection sites, in layer order (the tuple every public
#: surface has exported since PR 4 — now derived from SITE_HELP so the
#: registry cannot drift from its documentation).
SITES: Tuple[str, ...] = tuple(SITE_HELP)


def validate_site(site: str) -> str:
    """Return ``site`` if registered, else raise ``ValueError`` naming
    the known sites — the construction-time gate ``FaultRule``,
    ``FaultPlan``, and spec parsing all share."""
    if site not in SITE_HELP:
        raise ValueError(
            f"unknown fault site {site!r}; known sites: "
            f"{', '.join(SITES)}")
    return site
