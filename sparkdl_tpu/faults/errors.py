"""Injected-fault error taxonomy.

Every exception the fault harness raises is a distinct type so the code
under test can be asserted to ROUTE it correctly: transient faults must
be retried (``utils.retry`` treats :class:`InjectedTransientError` like
any retryable runtime error), deterministic faults must fail fast
(:class:`InjectedFatalError` / :class:`InjectedDecodeError` subclass
``ValueError``, which sits in ``utils.retry.NON_RETRYABLE``), and a
sticky dead device (:class:`InjectedDeadDeviceError`) must eventually
trip the engine's circuit breaker rather than retry forever.

All carry ``site`` (the injection point that fired) and ``rule`` (the
canonical spec clause), so a chaos-test failure message names exactly
which planned fault produced it.
"""

from __future__ import annotations


class InjectedFault(RuntimeError):
    """Base class of every fault the harness injects."""

    def __init__(self, message: str, site: str = "", rule: str = ""):
        super().__init__(message)
        self.site = site
        self.rule = rule


class InjectedTransientError(InjectedFault):
    """A one-off device/runtime hiccup: the retryable kind (plain
    ``RuntimeError`` lineage, so retry budgets see it as transient)."""


class InjectedDeadDeviceError(InjectedFault):
    """A sticky device death: once a ``dead`` rule fires, EVERY later
    call at its site raises this — the repeated-identical-failure
    pattern circuit breakers exist to cut short."""


class InjectedFatalError(InjectedFault, ValueError):
    """A deterministic failure (bad shapes/params): subclasses
    ``ValueError`` so ``utils.retry.NON_RETRYABLE`` fails it fast —
    retrying would reproduce the identical error."""


class InjectedDecodeError(InjectedFault, ValueError):
    """A corrupt-input decode failure mid-stream; the host I/O layer's
    drop-to-null contract must absorb it row-wise, never kill the
    stream."""
