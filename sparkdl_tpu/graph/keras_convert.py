"""Keras model -> jax ModelFunction conversion.

The successor of ``GraphFunction.fromKeras`` + ``KSessionWrap``
(``python/sparkdl/graph/builder.py``, ``transformers/keras_utils.py``): the
reference froze a Keras/TF-1.x session graph to a GraphDef; here we walk the
Keras-3 functional graph once at conversion time and emit a pure jax
function plus a weight pytree — jit/shard-ready for the mesh engine, no TF
runtime on the execution path.

Supported layer set covers the reference's tested surface (tiny MLPs/CNNs in
``keras_tensor_test.py`` / ``keras_image_test.py`` plus the zoo layer types);
unsupported layers fail loudly at conversion, not at trace time.

Inference semantics: Dropout/GaussianNoise are identity; BatchNorm uses
moving statistics.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from sparkdl_tpu.graph.function import ModelFunction

# ---------------------------------------------------------------------------
# activations


def _activation_fn(act) -> Callable:
    import jax
    import jax.numpy as jnp

    name = getattr(act, "__name__", None) or str(act)
    table = {
        "linear": lambda x: x,
        "relu": jax.nn.relu,
        "relu6": jax.nn.relu6,
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "softmax": jax.nn.softmax,
        "softplus": jax.nn.softplus,
        "softsign": jax.nn.soft_sign,
        "elu": jax.nn.elu,
        "selu": jax.nn.selu,
        "gelu": jax.nn.gelu,
        "silu": jax.nn.silu,
        "swish": jax.nn.silu,
        "exponential": jnp.exp,
        "hard_sigmoid": jax.nn.hard_sigmoid,
        "leaky_relu": jax.nn.leaky_relu,
        "log_softmax": jax.nn.log_softmax,
    }
    if name not in table:
        raise NotImplementedError(f"Unsupported Keras activation {name!r}")
    return table[name]


# ---------------------------------------------------------------------------
# per-layer converters: (layer, params_for_layer, list_of_inputs) -> output


def _conv_padding(layer):
    pad = layer.padding
    if isinstance(pad, str):
        return pad.upper()
    raise NotImplementedError(f"Unsupported padding {pad!r}")


def _conv2d(layer, p, xs):
    import jax.lax as lax
    import jax.numpy as jnp

    (x,) = xs
    if getattr(layer, "dilation_rate", (1, 1)) not in ((1, 1), 1):
        raise NotImplementedError("Dilated Conv2D not supported yet")
    y = lax.conv_general_dilated(
        x, jnp.asarray(p["kernel"]),
        window_strides=tuple(layer.strides),
        padding=_conv_padding(layer),
        feature_group_count=getattr(layer, "groups", 1) or 1,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if layer.use_bias:
        y = y + p["bias"]
    return _activation_fn(layer.activation)(y)


def _depthwise_conv2d(layer, p, xs):
    import jax.lax as lax
    import jax.numpy as jnp

    (x,) = xs
    dw = jnp.asarray(p["kernel"])  # [H,W,Cin,mult]
    kh, kw, cin, mult = dw.shape
    y = lax.conv_general_dilated(
        x, dw.reshape(kh, kw, 1, cin * mult),
        window_strides=tuple(layer.strides),
        padding=_conv_padding(layer),
        feature_group_count=cin,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if layer.use_bias:
        y = y + p["bias"]
    return _activation_fn(layer.activation)(y)


def _separable_conv2d(layer, p, xs):
    import jax.lax as lax
    import jax.numpy as jnp

    (x,) = xs
    dw = jnp.asarray(p["depthwise_kernel"])
    kh, kw, cin, mult = dw.shape
    y = lax.conv_general_dilated(
        x, dw.reshape(kh, kw, 1, cin * mult),
        window_strides=tuple(layer.strides),
        padding=_conv_padding(layer),
        feature_group_count=cin,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    y = lax.conv_general_dilated(
        y, jnp.asarray(p["pointwise_kernel"]),
        window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if layer.use_bias:
        y = y + p["bias"]
    return _activation_fn(layer.activation)(y)


def _dense(layer, p, xs):
    (x,) = xs
    y = x @ p["kernel"]
    if layer.use_bias:
        y = y + p["bias"]
    return _activation_fn(layer.activation)(y)


def _batchnorm(layer, p, xs):
    import jax.numpy as jnp

    (x,) = xs
    axis = layer.axis if isinstance(layer.axis, int) else layer.axis[0]
    if axis < 0:
        axis += x.ndim
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]

    def r(v):
        return jnp.asarray(v).reshape(shape)

    y = (x - r(p["moving_mean"])) / jnp.sqrt(r(p["moving_variance"]) + layer.epsilon)
    if layer.scale:
        y = y * r(p["gamma"])
    if layer.center:
        y = y + r(p["beta"])
    return y


def _pool2d(layer, xs, kind: str):
    from flax import linen as nn

    (x,) = xs
    window = tuple(layer.pool_size)
    strides = tuple(layer.strides) if layer.strides else window
    padding = layer.padding.upper()
    if kind == "max":
        return nn.max_pool(x, window, strides=strides, padding=padding)
    return nn.avg_pool(x, window, strides=strides, padding=padding,
                       count_include_pad=False)


def _zero_padding2d(layer, xs):
    import jax.numpy as jnp

    (x,) = xs
    ((t, b), (l, r)) = layer.padding
    return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)))


def _upsampling2d(layer, xs):
    import jax.numpy as jnp

    (x,) = xs
    if getattr(layer, "interpolation", "nearest") != "nearest":
        raise NotImplementedError("Only nearest UpSampling2D supported")
    sh, sw = layer.size
    return jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2)


def _convert_node(layer, p, xs):
    """Dispatch one layer application. ``p`` is the layer's param dict (or
    empty).  Returns a single jax value (multi-output layers unsupported)."""
    import jax.numpy as jnp

    t = type(layer).__name__
    if t in ("Conv2D",):
        return _conv2d(layer, p, xs)
    if t == "DepthwiseConv2D":
        return _depthwise_conv2d(layer, p, xs)
    if t == "SeparableConv2D":
        return _separable_conv2d(layer, p, xs)
    if t == "Dense":
        return _dense(layer, p, xs)
    if t == "BatchNormalization":
        return _batchnorm(layer, p, xs)
    if t == "MaxPooling2D":
        return _pool2d(layer, xs, "max")
    if t == "AveragePooling2D":
        return _pool2d(layer, xs, "avg")
    if t == "GlobalAveragePooling2D":
        return jnp.mean(xs[0], axis=(1, 2),
                        keepdims=getattr(layer, "keepdims", False))
    if t == "GlobalMaxPooling2D":
        return jnp.max(xs[0], axis=(1, 2),
                       keepdims=getattr(layer, "keepdims", False))
    if t == "Activation":
        return _activation_fn(layer.activation)(xs[0])
    if t == "ReLU":
        # Full keras semantics: f(x) = max_value-clipped relu above
        # threshold, negative_slope below it.
        x = xs[0]
        thr = float(getattr(layer, "threshold", 0.0) or 0.0)
        slope = float(getattr(layer, "negative_slope", 0.0) or 0.0)
        y = jnp.where(x >= thr, x, slope * (x - thr))
        if layer.max_value is not None:
            y = jnp.minimum(y, layer.max_value)
        return y
    if t == "LeakyReLU":
        import jax

        return jax.nn.leaky_relu(xs[0], layer.negative_slope)
    if t == "Softmax":
        import jax

        return jax.nn.softmax(xs[0], axis=layer.axis)
    if t == "Flatten":
        return xs[0].reshape(xs[0].shape[0], -1)
    if t == "Reshape":
        return xs[0].reshape((xs[0].shape[0],) + tuple(layer.target_shape))
    if t == "Permute":
        return jnp.transpose(xs[0], (0,) + tuple(layer.dims))
    if t in ("Dropout", "GaussianNoise", "GaussianDropout", "SpatialDropout2D",
             "ActivityRegularization"):
        return xs[0]  # identity at inference
    if t == "Add":
        return sum(xs[1:], xs[0])
    if t == "Subtract":
        return xs[0] - xs[1]
    if t == "Multiply":
        y = xs[0]
        for x in xs[1:]:
            y = y * x
        return y
    if t == "Average":
        return sum(xs[1:], xs[0]) / len(xs)
    if t == "Maximum":
        y = xs[0]
        for x in xs[1:]:
            y = jnp.maximum(y, x)
        return y
    if t == "Concatenate":
        return jnp.concatenate(xs, axis=layer.axis)
    if t == "ZeroPadding2D":
        return _zero_padding2d(layer, xs)
    if t == "UpSampling2D":
        return _upsampling2d(layer, xs)
    if t == "Rescaling":
        return xs[0] * layer.scale + layer.offset
    raise NotImplementedError(
        f"Keras layer type {t!r} (layer {layer.name!r}) is not supported by "
        f"the jax converter yet")


# every layer type _convert_node can lower (InputLayer is skipped upstream)
_SUPPORTED_TYPES = frozenset({
    "Conv2D", "DepthwiseConv2D", "SeparableConv2D", "Dense",
    "BatchNormalization", "MaxPooling2D", "AveragePooling2D",
    "GlobalAveragePooling2D", "GlobalMaxPooling2D", "Activation", "ReLU",
    "LeakyReLU", "Softmax", "Flatten", "Reshape", "Permute", "Dropout",
    "GaussianNoise", "GaussianDropout", "SpatialDropout2D",
    "ActivityRegularization", "Add", "Subtract", "Multiply", "Average",
    "Maximum", "Concatenate", "ZeroPadding2D", "UpSampling2D", "Rescaling",
})

# layer types whose weights we collect, keyed by their keras weight names
_PARAM_NAMES = {
    "Conv2D": ("kernel", "bias"),
    "DepthwiseConv2D": ("kernel", "bias"),
    "SeparableConv2D": ("depthwise_kernel", "pointwise_kernel", "bias"),
    "Dense": ("kernel", "bias"),
    "BatchNormalization": ("gamma", "beta", "moving_mean", "moving_variance"),
}


def _collect_params(layer) -> Dict[str, np.ndarray]:
    names = _PARAM_NAMES.get(type(layer).__name__)
    if not names:
        return {}
    out = {}
    for name in names:
        var = getattr(layer, name, None)
        if var is not None:
            out[name] = np.asarray(var)
    return out


def keras_to_model_function(model_or_path, *, jit: bool = False) -> ModelFunction:
    """Convert a Keras model (object or .h5/.keras file path) into a
    :class:`ModelFunction` with a weight pytree keyed by layer name.

    Single-input models accept a plain array; multi-input models accept a
    dict keyed by input name.  Multi-output models return a dict keyed by
    output name.
    """
    import keras

    if isinstance(model_or_path, (str, bytes)):
        model = keras.models.load_model(model_or_path, compile=False)
    else:
        model = model_or_path
    if not getattr(model, "built", True):
        raise ValueError("Keras model must be built before conversion")
    if not hasattr(model, "_nodes_by_depth"):
        # Sequential models gain a functional graph once called/built.
        if hasattr(model, "_functional") and model._functional is not None:
            model = model._functional
        else:
            raise ValueError(
                "Model has no functional graph; call it on a batch first")

    # Validate the whole graph eagerly: unsupported layers must fail at
    # conversion, not deep inside a later jit trace.
    unsupported = sorted({
        f"{type(layer).__name__}({layer.name})"
        for layer in model.layers
        if type(layer).__name__ not in _SUPPORTED_TYPES
        and type(layer).__name__ != "InputLayer"})
    if unsupported:
        raise NotImplementedError(
            f"Keras layers not supported by the jax converter: {unsupported}")

    # Collect weights once: {layer_name: {weight_name: array}}
    params: Dict[str, Dict[str, np.ndarray]] = {}
    for layer in model.layers:
        p = _collect_params(layer)
        if p:
            if layer.name in params:
                raise ValueError(f"Duplicate layer name {layer.name!r}")
            params[layer.name] = p

    # Record the graph structure as plain data (no keras objects captured in
    # the traced fn beyond layer configs read at trace time).
    input_keys = [t.name for t in model.inputs]
    output_keys = [t.name for t in model.outputs]
    nodes_by_depth = model._nodes_by_depth

    def fn(variables, x):
        # normalize input to {tensor_name: value}
        if isinstance(x, dict):
            values = dict(x)
            missing = set(input_keys) - set(values)
            if missing:
                raise ValueError(f"Missing model inputs: {sorted(missing)}")
        else:
            if len(input_keys) != 1:
                raise ValueError(
                    f"Model has {len(input_keys)} inputs; pass a dict")
            values = {input_keys[0]: x}

        computed = {k: values[k] for k in input_keys}
        for depth in sorted(nodes_by_depth.keys(), reverse=True):
            for node in nodes_by_depth[depth]:
                if node.is_input:
                    continue
                layer = node.operation
                xs = [computed[t.name] for t in node.input_tensors]
                out = _convert_node(layer, variables.get(layer.name, {}), xs)
                outs = node.output_tensors
                if len(outs) != 1:
                    raise NotImplementedError(
                        f"Multi-output layer {layer.name!r} unsupported")
                computed[outs[0].name] = out
        if len(output_keys) == 1:
            return computed[output_keys[0]]
        return {k: computed[k] for k in output_keys}

    mf = ModelFunction(fn=fn, variables=params,
                       input_names=tuple(input_keys),
                       output_names=tuple(output_keys))
    if jit:
        mf = ModelFunction(fn=mf.jit(), variables=params,
                           input_names=tuple(input_keys),
                           output_names=tuple(output_keys))
    return mf
