"""ModelFunction: the composable unit of computation.

The TPU-native successor of the reference's ``GraphFunction``
(``python/sparkdl/graph/builder.py``): where the reference serialized TF
``GraphDef`` fragments and spliced them together by tensor name
(``IsolatedSession.importGraphFunction``), a ModelFunction is a pure
jax-traceable function plus its variable pytree.  Composition is ordinary
function composition — XLA fuses the composed program into one kernel
schedule, which is exactly what the reference's graph-splicing tried to
approximate at the GraphDef level.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence


@dataclass
class ModelFunction:
    """A jit-traceable ``fn(variables, x) -> y`` with bound variables.

    ``input_names``/``output_names`` keep the reference's feed/fetch naming
    contract (``GraphFunction(graph_def, input_names, output_names)``) so
    stages can validate column wiring the way ``validated_input/output`` did.
    """

    fn: Callable[[Any, Any], Any]
    variables: Any = field(default_factory=dict)
    input_names: Sequence[str] = ("input",)
    output_names: Sequence[str] = ("output",)
    # Optional train-mode apply: ``train_fn(variables, x) ->
    # (pred, new_batch_stats)`` — set for models with BatchNorm whose
    # statistics can update during fine-tuning (estimator trainBatchStats).
    train_fn: Optional[Callable[[Any, Any], Any]] = None

    def __call__(self, x):
        return self.fn(self.variables, x)

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_callable(cls, fn: Callable[[Any], Any], *,
                      input_names=("input",), output_names=("output",)):
        """Wrap a variable-free function (e.g. a preprocessing lambda)."""
        return cls(fn=lambda _v, x: fn(x), variables={},
                   input_names=input_names, output_names=output_names)

    @classmethod
    def from_flax(cls, module, variables, *,
                  method_kwargs: Optional[dict] = None,
                  input_names=("input",), output_names=("output",)):
        """Bind a flax module's apply (inference mode by default).  Modules
        carrying ``batch_stats`` also get a train-mode apply so BatchNorm
        statistics can update during estimator fits (trainBatchStats)."""
        kw = dict(method_kwargs or {})

        def fn(v, x):
            return module.apply(v, x, **kw)

        train_fn = None
        if isinstance(variables, dict) and "batch_stats" in variables:
            tkw = {k: v for k, v in kw.items() if k != "train"}

            def train_fn(v, x):
                pred, mutated = module.apply(
                    v, x, train=True, mutable=["batch_stats"], **tkw)
                return pred, mutated["batch_stats"]

        return cls(fn=fn, variables=variables, train_fn=train_fn,
                   input_names=input_names, output_names=output_names)

    @classmethod
    def from_keras(cls, model_or_path, **kwargs):
        """Convert a Keras model (object or saved file) — the successor of
        ``GraphFunction.fromKeras``.  See graph.keras_convert."""
        from sparkdl_tpu.graph.keras_convert import keras_to_model_function

        return keras_to_model_function(model_or_path, **kwargs)

    # -- composition -------------------------------------------------------
    def compose(self, other: "ModelFunction") -> "ModelFunction":
        """``self`` then ``other`` — the successor of the reference's
        GraphDef splicing (``builder.py — importGraphFunction`` chains).
        Variables of both stages ride along as a two-slot pytree."""
        f, g = self, other

        def fn(v, x):
            return g.fn(v["g"], f.fn(v["f"], x))

        return ModelFunction(
            fn=fn, variables={"f": f.variables, "g": g.variables},
            input_names=f.input_names, output_names=g.output_names)

    def jit(self):
        """Eagerly jit-compile (otherwise the engine jits with shardings)."""
        import jax

        # graftlint: allow=SDL007 reason=generic API: the caller owns both variables and x across calls; donation is decided at the engine layer
        return jax.jit(self.fn)
