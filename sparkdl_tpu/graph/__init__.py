"""Model-graph layer.

Replaces the reference's TF-graph management layer (SURVEY.md §1 L2:
``python/sparkdl/graph/`` — ``IsolatedSession``, ``GraphFunction``,
``TFInputGraph``, name utils).  JAX's functional model removes the
global-graph/session problem ``IsolatedSession`` existed to solve; what
survives is the *composable, serializable unit of computation* —
:class:`ModelFunction` — and the legacy-format importers.
"""

from sparkdl_tpu.graph.function import ModelFunction

__all__ = ["ModelFunction"]
