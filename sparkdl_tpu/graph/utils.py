"""Graph-name utilities.

Counterpart of ``python/sparkdl/graph/utils.py`` (C10): the ``"op"`` vs
``"op:0"`` tensor-name normalization used everywhere feeds and fetches are
wired.  Kept API-compatible (op_name / tensor_name / validated_input /
validated_output) because the TFInputGraph importers speak the same naming.
"""

from __future__ import annotations

from typing import Iterable, List


def op_name(name: str) -> str:
    """Strip the output slot: ``"dense/BiasAdd:0" -> "dense/BiasAdd"``."""
    if not isinstance(name, str):
        raise TypeError(f"Expected a tensor/op name string, got {name!r}")
    return name.split(":")[0]


def tensor_name(name: str) -> str:
    """Canonical tensor name with output slot: ``"x" -> "x:0"``."""
    if not isinstance(name, str):
        raise TypeError(f"Expected a tensor/op name string, got {name!r}")
    parts = name.split(":")
    if len(parts) == 1:
        return f"{name}:0"
    if len(parts) == 2 and parts[1].isdigit():
        return name
    raise ValueError(f"Invalid tensor name {name!r}")


def output_index(name: str) -> int:
    parts = name.split(":")
    return int(parts[1]) if len(parts) == 2 else 0


def validated_input(name: str, known_ops: Iterable[str]) -> str:
    op = op_name(name)
    if op not in set(known_ops):
        raise ValueError(
            f"Input {name!r} does not reference a graph op; graph has e.g. "
            f"{sorted(set(known_ops))[:10]}")
    return tensor_name(name)


def validated_output(name: str, known_ops: Iterable[str]) -> str:
    op = op_name(name)
    if op not in set(known_ops):
        raise ValueError(
            f"Output {name!r} does not reference a graph op; graph has e.g. "
            f"{sorted(set(known_ops))[:10]}")
    return tensor_name(name)
