"""Frozen TF GraphDef -> jax interpreter.

The TPU replacement for the reference's GraphDef execution path: where the
reference shipped frozen GraphDefs to per-executor TF C++ sessions
(``TFInputGraph`` consumed by ``tf_tensor.py``/``tf_image.py`` through
TensorFrames — SURVEY.md §3.5), this walks the frozen GraphDef ONCE and
emits a pure jax function over a constant pytree, so legacy TF-1.x models
run as first-class XLA:TPU programs.

Scope: the inference op set the reference's tests exercise (dense/conv
nets: MatMul/Conv2D/BiasAdd/activations/pooling/BN/reshape/concat and
elementwise math).  Unsupported ops fail loudly at import, never at trace
time.  Graphs must be frozen (variables -> constants) — ``input.py`` does
that with the TF CPU runtime before handing the GraphDef here.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence

import numpy as np

from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.graph.utils import op_name, output_index, tensor_name

_NHWC = ("NHWC", "HWIO", "NHWC")


def _attr(node, key, default=None):
    if key in node.attr:
        return node.attr[key]
    return default


def _attr_list_int(node, key) -> List[int]:
    a = _attr(node, key)
    return list(a.list.i) if a is not None else []


def _attr_s(node, key, default=b"") -> bytes:
    a = _attr(node, key)
    return a.s if a is not None else default


def _attr_i(node, key, default=0) -> int:
    a = _attr(node, key)
    return a.i if a is not None else default


def _attr_f(node, key, default=0.0) -> float:
    a = _attr(node, key)
    return a.f if a is not None else default


def _attr_b(node, key, default=False) -> bool:
    a = _attr(node, key)
    return a.b if a is not None else default


def _padding(node) -> str:
    pad = _attr_s(node, "padding", b"SAME").decode()
    if pad not in ("SAME", "VALID"):
        raise NotImplementedError(f"Unsupported padding {pad!r}")
    return pad


def _require_nhwc(node):
    fmt = _attr_s(node, "data_format", b"NHWC").decode()
    if fmt not in ("NHWC", ""):
        raise NotImplementedError(
            f"{node.op} node {node.name!r} uses data_format {fmt}; only "
            f"NHWC graphs are supported")


def _pool(x, node, kind: str):
    from flax import linen as nn

    _require_nhwc(node)
    ksize = _attr_list_int(node, "ksize")
    strides = _attr_list_int(node, "strides")
    window = (ksize[1], ksize[2])
    st = (strides[1], strides[2])
    if kind == "max":
        return nn.max_pool(x, window, strides=st, padding=_padding(node))
    return nn.avg_pool(x, window, strides=st, padding=_padding(node),
                       count_include_pad=False)


def _reduce(jnp_fn, x, axes, node):
    axes = tuple(int(a) for a in np.asarray(axes).reshape(-1))
    return jnp_fn(x, axis=axes, keepdims=_attr_b(node, "keep_dims"))


class _Interpreter:
    """Builds handler closures per node; executed under jax tracing."""

    def __init__(self):
        import jax
        import jax.numpy as jnp

        self.jax = jax
        self.jnp = jnp

    def run_node(self, node, inputs: List[Any]) -> Any:
        jnp = self.jnp
        jax = self.jax
        op = node.op
        if op in ("Identity", "StopGradient", "PreventGradient", "Snapshot",
                  "CheckNumerics", "NoOp", "PlaceholderWithDefault"):
            return inputs[0] if inputs else None
        if op == "MatMul":
            a, b = inputs
            if _attr_b(node, "transpose_a"):
                a = a.T
            if _attr_b(node, "transpose_b"):
                b = b.T
            return a @ b
        if op == "BiasAdd":
            _require_nhwc(node)
            return inputs[0] + inputs[1]
        if op in ("Add", "AddV2"):
            return inputs[0] + inputs[1]
        if op == "AddN":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if op == "Sub":
            return inputs[0] - inputs[1]
        if op == "Mul":
            return inputs[0] * inputs[1]
        if op in ("RealDiv", "Div"):
            return inputs[0] / inputs[1]
        if op == "Maximum":
            return jnp.maximum(inputs[0], inputs[1])
        if op == "Minimum":
            return jnp.minimum(inputs[0], inputs[1])
        if op == "Square":
            return inputs[0] * inputs[0]
        if op == "Sqrt":
            return jnp.sqrt(inputs[0])
        if op == "Rsqrt":
            return 1.0 / jnp.sqrt(inputs[0])
        if op == "Exp":
            return jnp.exp(inputs[0])
        if op == "Log":
            return jnp.log(inputs[0])
        if op == "Neg":
            return -inputs[0]
        if op == "Abs":
            return jnp.abs(inputs[0])
        if op == "Pow":
            return inputs[0] ** inputs[1]
        if op == "Relu":
            return jax.nn.relu(inputs[0])
        if op == "Relu6":
            return jax.nn.relu6(inputs[0])
        if op == "LeakyRelu":
            return jax.nn.leaky_relu(inputs[0], _attr_f(node, "alpha", 0.2))
        if op == "Elu":
            return jax.nn.elu(inputs[0])
        if op == "Selu":
            return jax.nn.selu(inputs[0])
        if op == "Sigmoid":
            return jax.nn.sigmoid(inputs[0])
        if op == "Tanh":
            return jnp.tanh(inputs[0])
        if op == "Softplus":
            return jax.nn.softplus(inputs[0])
        if op == "Softmax":
            return jax.nn.softmax(inputs[0], axis=-1)
        if op == "LogSoftmax":
            return jax.nn.log_softmax(inputs[0], axis=-1)
        if op == "Conv2D":
            import jax.lax as lax

            strides = _attr_list_int(node, "strides")
            dil = _attr_list_int(node, "dilations") or [1, 1, 1, 1]
            fmt = _attr_s(node, "data_format", b"NHWC").decode()
            if fmt != "NHWC":
                raise NotImplementedError(f"Conv2D data_format {fmt}")
            return lax.conv_general_dilated(
                inputs[0], inputs[1],
                window_strides=(strides[1], strides[2]),
                padding=_padding(node),
                rhs_dilation=(dil[1], dil[2]),
                dimension_numbers=_NHWC)
        if op == "DepthwiseConv2dNative":
            import jax.lax as lax

            strides = _attr_list_int(node, "strides")
            k = inputs[1]
            kh, kw, cin, mult = k.shape
            return lax.conv_general_dilated(
                inputs[0], k.reshape(kh, kw, 1, cin * mult),
                window_strides=(strides[1], strides[2]),
                padding=_padding(node),
                feature_group_count=cin,
                dimension_numbers=_NHWC)
        if op == "MaxPool":
            return _pool(inputs[0], node, "max")
        if op == "AvgPool":
            return _pool(inputs[0], node, "avg")
        if op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
            _require_nhwc(node)
            x, gamma, beta, mean, var = inputs[:5]
            eps = _attr_f(node, "epsilon", 1e-3)
            return (x - mean) / jnp.sqrt(var + eps) * gamma + beta
        if op == "Mean":
            return _reduce(jnp.mean, inputs[0], inputs[1], node)
        if op == "Sum":
            return _reduce(jnp.sum, inputs[0], inputs[1], node)
        if op == "Max":
            return _reduce(jnp.max, inputs[0], inputs[1], node)
        if op == "Min":
            return _reduce(jnp.min, inputs[0], inputs[1], node)
        if op == "Reshape":
            shape = [int(v) for v in np.asarray(inputs[1]).reshape(-1)]
            return inputs[0].reshape(shape)
        if op == "Squeeze":
            dims = _attr_list_int(node, "squeeze_dims")
            return jnp.squeeze(inputs[0],
                               axis=tuple(dims) if dims else None)
        if op == "ExpandDims":
            return jnp.expand_dims(inputs[0], int(np.asarray(inputs[1])))
        if op == "ConcatV2":
            axis = int(np.asarray(inputs[-1]))
            return jnp.concatenate(inputs[:-1], axis=axis)
        if op == "Pad":
            pads = np.asarray(inputs[1]).tolist()
            return jnp.pad(inputs[0], pads)
        if op == "Transpose":
            perm = [int(v) for v in np.asarray(inputs[1]).reshape(-1)]
            return jnp.transpose(inputs[0], perm)
        if op == "Cast":
            import tensorflow as tf

            dst = tf.dtypes.as_dtype(_attr(node, "DstT").type).as_numpy_dtype
            return inputs[0].astype(dst)
        raise NotImplementedError(
            f"TF op {op!r} (node {node.name!r}) is not supported by the "
            f"GraphDef->jax importer")


# Every op run_node can lower — membership checked eagerly at import.
_SUPPORTED_OPS = frozenset({
    "Identity", "StopGradient", "PreventGradient", "Snapshot",
    "CheckNumerics", "NoOp", "PlaceholderWithDefault",
    "MatMul", "Add", "AddV2", "BiasAdd", "AddN", "Sub", "Mul", "RealDiv",
    "Div", "Maximum", "Minimum", "Square", "Sqrt", "Rsqrt", "Exp", "Log",
    "Neg", "Abs", "Pow",
    "Relu", "Relu6", "LeakyRelu", "Elu", "Selu", "Sigmoid", "Tanh",
    "Softplus", "Softmax", "LogSoftmax",
    "Conv2D", "DepthwiseConv2dNative", "MaxPool", "AvgPool",
    "FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3",
    "Mean", "Sum", "Max", "Min",
    "Reshape", "Squeeze", "ExpandDims", "ConcatV2", "Pad", "Transpose",
    "Cast",
})

_STRUCTURAL = frozenset({"Placeholder", "Const"})

# Input slots that must be STATIC (python/numpy) values at trace time:
# shapes, axes, permutations, pad widths.  These are resolved from the
# graph's constants on the host, never from the traced pytree.
_STATIC_ARG_SLOTS = {
    "Reshape": (1,),
    "ExpandDims": (1,),
    "Pad": (1,),
    "Transpose": (1,),
    "Mean": (1,),
    "Sum": (1,),
    "Max": (1,),
    "Min": (1,),
}


def graphdef_to_jax(graph_def, feed_names: Sequence[str],
                    fetch_names: Sequence[str]) -> ModelFunction:
    """Compile a FROZEN GraphDef into a ModelFunction.

    ``feed_names``/``fetch_names`` accept either ``"op"`` or ``"op:k"``
    forms (the reference's naming contract, ``graph/utils.py``).
    Constants become the ModelFunction's variable pytree (so big weight
    tensors live in the params slot, not baked into the traced program).
    """
    from tensorflow.python.framework import tensor_util

    nodes = {n.name: n for n in graph_def.node}
    feeds = [tensor_name(f) for f in feed_names]
    fetches = [tensor_name(f) for f in fetch_names]
    for name in feeds + fetches:
        if op_name(name) not in nodes:
            raise ValueError(
                f"{name!r} not found in graph (ops: "
                f"{sorted(nodes)[:10]}...)")

    # Validate support + collect constants eagerly (fail at import, never
    # at trace time).
    interp = _Interpreter()
    consts: Dict[str, np.ndarray] = {}
    feed_ops = {op_name(f) for f in feeds}
    unsupported = sorted({
        f"{n.op}({n.name})" for n in graph_def.node
        if n.op not in _SUPPORTED_OPS and n.op not in _STRUCTURAL})
    if unsupported:
        raise NotImplementedError(
            f"TF ops not supported by the GraphDef->jax importer: "
            f"{unsupported}")
    # The interpreter materializes output slot 0 only; any reference to a
    # secondary output (e.g. FusedBatchNorm's batch-mean "bn:1") must fail
    # HERE, not as an IndexError mid-trace.
    multi_out = sorted({
        ref for n in graph_def.node for ref in n.input
        if not ref.startswith("^") and output_index(ref) > 0
    } | {f for f in fetches if output_index(f) > 0})
    if multi_out:
        raise NotImplementedError(
            f"References to secondary node outputs are not supported: "
            f"{multi_out}")
    for n in graph_def.node:
        if n.op == "Const":
            consts[n.name] = tensor_util.MakeNdarray(n.attr["value"].tensor)
        elif n.op == "Placeholder" and n.name not in feed_ops:
            raise ValueError(
                f"Graph placeholder {n.name!r} is not covered by "
                f"feed_names {list(feed_names)}")

    def fn(variables, x):
        if isinstance(x, dict):
            values = {tensor_name(k): v for k, v in x.items()}
        else:
            if len(feeds) != 1:
                raise ValueError(
                    f"Graph has {len(feeds)} feeds; pass a dict")
            values = {feeds[0]: x}

        computed: Dict[str, Any] = {}

        def lookup(name: str):
            # name is canonical "op:0" (multi-output refs rejected above)
            return values[name] if name in values else computed[name]

        def dynamic_refs(node):
            data_refs = [r for r in node.input if not r.startswith("^")]
            static_slots = set(_STATIC_ARG_SLOTS.get(node.op, ()))
            if node.op == "ConcatV2":
                static_slots.add(len(data_refs) - 1)
            return data_refs, static_slots

        def get(ref: str):
            # node-input refs look like "name", "name:k", or "^ctrl"
            if ref.startswith("^"):
                return None
            target = tensor_name(ref)
            if target in values or target in computed:
                return lookup(target)
            # Iterative post-order evaluation: a few-hundred-node sequential
            # chain (typical for real zoo graphs) would exceed Python's
            # recursion limit under recursive descent.
            stack = [op_name(target)]
            while stack:
                nname = stack[-1]
                key0 = f"{nname}:0"
                if key0 in computed or key0 in values:
                    stack.pop()
                    continue
                node = nodes[nname]
                if node.op == "Placeholder":
                    raise ValueError(f"Placeholder {node.name} unfed")
                if node.op == "Const":
                    computed[key0] = variables["consts"][node.name]
                    stack.pop()
                    continue
                data_refs, static_slots = dynamic_refs(node)
                pending = [
                    op_name(r) for j, r in enumerate(data_refs)
                    if j not in static_slots
                    and tensor_name(r) not in values
                    and tensor_name(r) not in computed]
                if pending:
                    stack.extend(pending)
                    continue
                ins = [
                    static_lookup(r, node) if j in static_slots
                    else lookup(tensor_name(r))
                    for j, r in enumerate(data_refs)]
                computed[key0] = interp.run_node(node, ins)
                stack.pop()
            return lookup(target)

        def static_lookup(ref: str, node):
            name = op_name(ref)
            # follow Identity chains to the underlying Const
            seen = set()
            while name in nodes and nodes[name].op == "Identity" \
                    and name not in seen:
                seen.add(name)
                name = op_name(nodes[name].input[0])
            if name in consts:
                return consts[name]
            raise NotImplementedError(
                f"{node.op} node {node.name!r} has a dynamic "
                f"shape/axis operand {ref!r}; only constant operands are "
                f"supported")

        outs = [get(f) for f in fetches]
        if len(outs) == 1:
            return outs[0]
        return {orig: o for orig, o in zip(fetch_names, outs)}

    return ModelFunction(fn=fn, variables={"consts": consts},
                         input_names=tuple(feed_names),
                         output_names=tuple(fetch_names))
