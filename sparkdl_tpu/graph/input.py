"""TFInputGraph: uniform import of legacy TF model formats.

Counterpart of ``python/sparkdl/graph/input.py`` (C9): the same six factory
constructors over live graphs, GraphDefs, TF ``Saver`` checkpoints and
SavedModels (with or without signature_defs), producing one canonical form.
The reference froze to a GraphDef and shipped it to executor sessions; here
the frozen GraphDef is compiled to a jax :class:`ModelFunction`
(graph.tf_import) so legacy models run on the TPU mesh like native ones.

The TF 2.x CPU runtime is used ONLY at import time (reading checkpoints,
freezing variables); it never touches the execution path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.graph.tf_import import graphdef_to_jax
from sparkdl_tpu.graph.utils import op_name, tensor_name


def _tf():
    import os

    os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
    import tensorflow as tf

    return tf


@dataclass
class TFInputGraph:
    """A frozen GraphDef + feed/fetch naming, ready to compile to jax.

    ``input_mapping``/``output_mapping`` translate signature keys (or raw
    names) to graph tensor names — the role of the reference's
    feed/fetch-mapping builders.
    """

    graph_def: object
    input_mapping: Dict[str, str]    # logical name -> graph tensor name
    output_mapping: Dict[str, str]   # graph tensor name -> logical name
    _model_function: Optional[ModelFunction] = field(default=None, repr=False)

    # -- canonical consumption --------------------------------------------
    @property
    def input_names(self) -> List[str]:
        return list(self.input_mapping)

    @property
    def output_names(self) -> List[str]:
        return list(self.output_mapping.values())

    def model_function(self) -> ModelFunction:
        """Compile (once) to a jax ModelFunction keyed by LOGICAL names."""
        if self._model_function is None:
            feeds = list(self.input_mapping.values())
            fetches = list(self.output_mapping)
            raw = graphdef_to_jax(self.graph_def, feeds, fetches)
            logical_in = {v: k for k, v in self.input_mapping.items()}
            out_map = dict(self.output_mapping)

            def fn(variables, x):
                if isinstance(x, dict):
                    x = {self.input_mapping.get(k, k): v
                         for k, v in x.items()}
                y = raw.fn(variables, x)
                if isinstance(y, dict):
                    return {out_map.get(k, k): v for k, v in y.items()}
                return y

            self._model_function = ModelFunction(
                fn=fn, variables=raw.variables,
                input_names=tuple(logical_in[f] for f in feeds),
                output_names=tuple(out_map[f] for f in fetches))
        return self._model_function

    # -- constructors (the reference's six) --------------------------------
    @classmethod
    def fromGraph(cls, graph, sess, feed_names: Sequence[str],
                  fetch_names: Sequence[str]) -> "TFInputGraph":
        """From a live tf.compat.v1 Graph + Session (variables frozen)."""
        frozen = _freeze(sess, graph.as_graph_def(add_shapes=True),
                         fetch_names)
        return cls(
            graph_def=frozen,
            input_mapping={n: tensor_name(n) for n in feed_names},
            output_mapping={tensor_name(n): n for n in fetch_names})

    @classmethod
    def fromGraphDef(cls, graph_def, feed_names: Sequence[str],
                     fetch_names: Sequence[str]) -> "TFInputGraph":
        """From an already-frozen GraphDef."""
        return cls(
            graph_def=graph_def,
            input_mapping={n: tensor_name(n) for n in feed_names},
            output_mapping={tensor_name(n): n for n in fetch_names})

    @classmethod
    def fromCheckpoint(cls, checkpoint_dir: str, feed_names: Sequence[str],
                       fetch_names: Sequence[str]) -> "TFInputGraph":
        """From a TF Saver checkpoint directory (latest checkpoint +
        ``.meta`` graph)."""
        graph_def, _ = _load_checkpoint(checkpoint_dir, fetch_names)
        return cls(
            graph_def=graph_def,
            input_mapping={n: tensor_name(n) for n in feed_names},
            output_mapping={tensor_name(n): n for n in fetch_names})

    @classmethod
    def fromCheckpointWithSignature(cls, checkpoint_dir: str,
                                    signature_def_key: str) -> "TFInputGraph":
        """From a checkpoint whose MetaGraph carries a signature_def."""
        graph_def, meta = _load_checkpoint(checkpoint_dir, None,
                                           signature_def_key)
        in_map, out_map = _signature_mappings(meta, signature_def_key)
        return cls(graph_def=graph_def, input_mapping=in_map,
                   output_mapping=out_map)

    @classmethod
    def fromSavedModel(cls, saved_model_dir: str, tag_set: str,
                       feed_names: Sequence[str],
                       fetch_names: Sequence[str]) -> "TFInputGraph":
        """From a SavedModel with explicit feed/fetch names."""
        graph_def, _ = _load_saved_model(saved_model_dir, tag_set,
                                         fetch_names)
        return cls(
            graph_def=graph_def,
            input_mapping={n: tensor_name(n) for n in feed_names},
            output_mapping={tensor_name(n): n for n in fetch_names})

    @classmethod
    def fromSavedModelWithSignature(cls, saved_model_dir: str, tag_set: str,
                                    signature_def_key: str) -> "TFInputGraph":
        """From a SavedModel using its signature_def feeds/fetches."""
        graph_def, meta = _load_saved_model(saved_model_dir, tag_set, None,
                                            signature_def_key)
        in_map, out_map = _signature_mappings(meta, signature_def_key)
        return cls(graph_def=graph_def, input_mapping=in_map,
                   output_mapping=out_map)


# ---------------------------------------------------------------------------
# TF-side loading/freezing helpers


def _freeze(sess, graph_def, fetch_names: Sequence[str]):
    tf = _tf()

    out_ops = [op_name(n) for n in fetch_names]
    return tf.compat.v1.graph_util.convert_variables_to_constants(
        sess, graph_def, out_ops)


def _get_signature(meta, signature_def_key: str):
    # NB: protobuf map __getitem__ silently CREATES missing entries; always
    # gate on membership first.
    if signature_def_key not in meta.signature_def:
        raise ValueError(
            f"signature_def {signature_def_key!r} not found; available: "
            f"{sorted(meta.signature_def)}")
    return meta.signature_def[signature_def_key]


def _signature_fetches(meta, signature_def_key: str) -> List[str]:
    return [v.name for v in _get_signature(meta, signature_def_key).outputs.values()]


def _signature_mappings(meta, signature_def_key: str
                        ) -> Tuple[Dict[str, str], Dict[str, str]]:
    sig = _get_signature(meta, signature_def_key)
    in_map = {k: v.name for k, v in sig.inputs.items()}
    out_map = {v.name: k for k, v in sig.outputs.items()}
    return in_map, out_map


def _load_checkpoint(checkpoint_dir: str,
                     fetch_names: Optional[Sequence[str]],
                     signature_def_key: Optional[str] = None):
    tf = _tf()

    ckpt = tf.train.latest_checkpoint(checkpoint_dir)
    if ckpt is None:
        raise ValueError(f"No checkpoint found under {checkpoint_dir!r}")
    # Read the stored MetaGraphDef (it carries any signature_defs; a fresh
    # export_meta_graph would not).
    from tensorflow.python.framework import meta_graph as _mg

    meta = _mg.read_meta_graph_file(ckpt + ".meta")
    graph = tf.compat.v1.Graph()
    with graph.as_default():
        with tf.compat.v1.Session(graph=graph) as sess:
            saver = tf.compat.v1.train.import_meta_graph(meta,
                                                         clear_devices=True)
            saver.restore(sess, ckpt)
            if fetch_names is None:
                fetch_names = _signature_fetches(meta, signature_def_key)
            frozen = _freeze(sess, graph.as_graph_def(add_shapes=True),
                             fetch_names)
    return frozen, meta


def _load_saved_model(saved_model_dir: str, tag_set: str,
                      fetch_names: Optional[Sequence[str]],
                      signature_def_key: Optional[str] = None):
    tf = _tf()

    tags = tag_set.split(",") if isinstance(tag_set, str) else list(tag_set)
    graph = tf.compat.v1.Graph()
    with graph.as_default():
        with tf.compat.v1.Session(graph=graph) as sess:
            meta = tf.compat.v1.saved_model.loader.load(
                sess, tags, saved_model_dir)
            if fetch_names is None:
                fetch_names = _signature_fetches(meta, signature_def_key)
            frozen = _freeze(sess, graph.as_graph_def(add_shapes=True),
                             fetch_names)
    return frozen, meta


# Back-compat alias used by the package exports (reference exported the
# class under this name).
ModelInput = TFInputGraph
