"""Arrow-backed columnar DataFrame — the Spark-DataFrame stand-in.

The reference's entire API surface is ``Transformer.transform(df) -> df`` over
Spark DataFrames.  The TPU framework is Spark-independent: this module gives a
small pyarrow-Table-backed DataFrame with the operations the pipeline stages
need (select / withColumn / repartition / batch iteration), so the framework
runs standalone; when pyspark is present the same stages can be bridged via
pandas-UDFs (see ``sparkdl_tpu.udf``).
"""

from sparkdl_tpu.frame.dataframe import DataFrame, Row

__all__ = ["DataFrame", "Row"]
