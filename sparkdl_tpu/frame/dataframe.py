"""A minimal columnar DataFrame over pyarrow.

Design notes (TPU-first):
  * Chunking is explicit: a frame is a ``pyarrow.Table`` whose record batches
    play the role Spark partitions played in the reference — transformers
    process the frame batch-wise and the inference engine re-buckets rows into
    fixed device batch shapes (padding the tail) so XLA never recompiles.
  * No lazy plan/optimizer: the reference's laziness came from Spark; here
    stages run eagerly over Arrow batches, which keeps host->device pipelining
    in our control (see sparkdl_tpu.parallel.engine).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np
import pyarrow as pa


class Row(dict):
    """Dict-like row with attribute access (quacks like pyspark.sql.Row)."""

    def __getattr__(self, item):
        try:
            return self[item]
        except KeyError:
            raise AttributeError(item)


class _StructView(dict):
    """Zero-copy row view of an Arrow struct column handed to ``map_rows``
    fns.  Behaves as the plain dict the row path produced, except binary
    children are ``memoryview`` slices over the Arrow value buffer (wrap
    with ``bytes()`` when a real bytes object is required — numpy/PIL/io
    consumers take memoryview directly).  Identity is tracked so a fn that
    returns the view unchanged lets the column be re-emitted without any
    Python->Arrow round trip; any in-place MUTATION marks the view dirty
    so the passthrough is defeated and the mutation is preserved (the old
    to_pylist path's behavior)."""

    __slots__ = ("_src", "_idx", "_dirty")

    def _touch(self):
        self._dirty = True

    def __setitem__(self, k, v):
        self._touch()
        super().__setitem__(k, v)

    def __delitem__(self, k):
        self._touch()
        super().__delitem__(k)

    def update(self, *a, **kw):
        self._touch()
        super().update(*a, **kw)

    def pop(self, *a):
        self._touch()
        return super().pop(*a)

    def popitem(self):
        self._touch()
        return super().popitem()

    def clear(self):
        self._touch()
        super().clear()

    def setdefault(self, k, default=None):
        if k not in self:
            self._touch()
        return super().setdefault(k, default)

    def __ior__(self, other):
        # dict.__ior__ bypasses the Python-level update override
        self._touch()
        return super().__ior__(other)


def _struct_view_rows(arr: "pa.StructArray"):
    """Per-row dict views of a flat struct column, read from Arrow buffers.

    The to_pylist row path costs ~0.2 ms/row on 299^2 image structs (it
    copies the MB-scale binary child into fresh bytes per row); buffer
    views cost ~0.006 ms/row.  Returns None when a child type is outside
    this fast path (nested lists/structs, ...) — caller falls back to
    to_pylist.
    """
    n = len(arr)
    cols = []
    for i in range(arr.type.num_fields):
        f = arr.type.field(i)
        child = arr.field(i)
        t = f.type
        if pa.types.is_binary(t) or pa.types.is_large_binary(t):
            if child.null_count:  # per-CHILD fallback: the other children
                cols.append((f.name, "py", child.to_pylist()))  # stay fast
                continue
            bufs = child.buffers()
            odt = np.int64 if pa.types.is_large_binary(t) else np.int32
            offs = np.frombuffer(bufs[1], odt)[
                child.offset:child.offset + n + 1]
            data_mv = memoryview(bufs[2]) if bufs[2] is not None else \
                memoryview(b"")
            cols.append((f.name, "bin", (offs, data_mv)))
        elif child.null_count == 0 and (
                pa.types.is_integer(t) or pa.types.is_floating(t)):
            np_child = child.to_numpy(zero_copy_only=False)
            cols.append((f.name, "num", np_child))
        elif (pa.types.is_string(t) or pa.types.is_large_string(t)
              or pa.types.is_boolean(t) or pa.types.is_integer(t)
              or pa.types.is_floating(t) or pa.types.is_null(t)):
            cols.append((f.name, "py", child.to_pylist()))
        else:
            return None
    valid = np.asarray(arr.is_valid()) if arr.null_count else None
    rows = []
    for i in range(n):
        if valid is not None and not valid[i]:
            rows.append(None)
            continue
        view = _StructView()
        for name, kind, c in cols:
            if kind == "num":
                view[name] = c[i].item()
            elif kind == "bin":
                offs, mv = c
                view[name] = mv[offs[i]:offs[i + 1]]
            else:
                view[name] = c[i]
        view._src = arr
        view._idx = i
        view._dirty = False  # population above set it; arm tracking now
        rows.append(view)
    return rows


def _passthrough_source(vals):
    """The untouched source StructArray iff every mapped value is the
    row-aligned ``_StructView`` of one source column (None only where the
    source row itself is null); else None and the caller materializes."""
    src = None
    for i, v in enumerate(vals):
        if isinstance(v, _StructView):
            if (v._dirty or v._idx != i
                    or (src is not None and v._src is not src)):
                return None
            src = v._src
        elif v is not None:
            return None
    if src is None or len(src) != len(vals):
        return None
    if src.null_count:
        valid = np.asarray(src.is_valid())
        for i, v in enumerate(vals):
            if v is None and valid[i]:
                return None  # fn nulled a live row: must materialize
    elif any(v is None for v in vals):
        return None
    return src


def _promote_schema(schema: Optional[pa.Schema],
                    t: pa.Table) -> pa.Schema:
    """Widen the running ``schema`` with ``t``'s (null -> concrete,
    int -> float, ...) — the shared promotion rule of the batch-wise
    mappers.  Inferring each batch independently and unifying is what
    keeps a later float batch from being silently TRUNCATED against an
    int-pinned first batch (``from_pylist(schema=...)`` coerces 3.5 -> 3
    without raising)."""
    if schema is None:
        return t.schema
    if t.schema != schema:
        return pa.unify_schemas([schema, t.schema],
                                promote_options="permissive")
    return schema


def _concat_conforming(tables: List[pa.Table], schema: pa.Schema) -> pa.Table:
    """Concat per-batch tables under the unified ``schema``: a batch may
    lack a column some other batch produced — null-fill it (the pinned-
    schema behavior) before the ordered cast."""
    def conform(t: pa.Table) -> pa.Table:
        for field in schema:
            if field.name not in t.column_names:
                t = t.append_column(field.name,
                                    pa.nulls(len(t), field.type))
        return t.select([f.name for f in schema]).cast(schema)

    return pa.concat_tables([conform(t) for t in tables])


def _to_table(data) -> pa.Table:
    if isinstance(data, pa.Table):
        return data
    if isinstance(data, pa.RecordBatch):
        return pa.Table.from_batches([data])
    try:
        import pandas as pd
        if isinstance(data, pd.DataFrame):
            return pa.Table.from_pandas(data, preserve_index=False)
    except ImportError:
        pass
    if isinstance(data, dict):
        return pa.table(data)
    if isinstance(data, list):  # list of dict rows
        return pa.Table.from_pylist(data)
    raise TypeError(f"Cannot build DataFrame from {type(data).__name__}")


class DataFrame:
    """Immutable columnar frame backed by a ``pyarrow.Table``."""

    def __init__(self, data):
        self._table = _to_table(data)

    # -- construction ------------------------------------------------------
    @staticmethod
    def from_pandas(pdf) -> "DataFrame":
        return DataFrame(pa.Table.from_pandas(pdf, preserve_index=False))

    @staticmethod
    def from_rows(rows: List[dict], schema: Optional[pa.Schema] = None) -> "DataFrame":
        if schema is not None:
            return DataFrame(pa.Table.from_pylist(rows, schema=schema))
        return DataFrame(pa.Table.from_pylist(rows))

    # -- introspection -----------------------------------------------------
    @property
    def table(self) -> pa.Table:
        return self._table

    @property
    def schema(self) -> pa.Schema:
        return self._table.schema

    @property
    def columns(self) -> List[str]:
        return self._table.column_names

    def count(self) -> int:
        return self._table.num_rows

    def __len__(self) -> int:
        return self._table.num_rows

    def __repr__(self):
        return f"DataFrame[{', '.join(f'{f.name}: {f.type}' for f in self.schema)}] ({len(self)} rows)"

    # -- relational ops ----------------------------------------------------
    def select(self, *cols: str) -> "DataFrame":
        return DataFrame(self._table.select(list(cols)))

    def drop(self, *cols: str) -> "DataFrame":
        keep = [c for c in self.columns if c not in cols]
        return DataFrame(self._table.select(keep))

    def withColumn(self, name: str, values) -> "DataFrame":
        """Append/replace a column.  ``values`` may be a pyarrow Array /
        ChunkedArray, numpy array (any rank: rank 2 becomes a
        ``list<leaf dtype>`` column, rank>=3 nests ``fixed_size_list``
        per trailing dim, leaf dtype preserved), or Python list."""
        if isinstance(values, (pa.Array, pa.ChunkedArray)):
            arr = values
        elif isinstance(values, np.ndarray):
            if values.ndim == 1:
                arr = pa.array(values)
            elif values.ndim == 2:
                # list-of-leaf-dtype column (rows stay 1-D arrays, so
                # pyarrow keeps the numpy leaf dtype)
                arr = pa.array(list(values))
            else:
                # rank>=3: pa.array refuses >1-D elements — build nested
                # fixed_size_list layers over the flattened values buffer
                # (leaf dtype preserved, no per-row Python round trip)
                arr = pa.array(np.ascontiguousarray(values).reshape(-1))
                for dim in reversed(values.shape[1:]):
                    arr = pa.FixedSizeListArray.from_arrays(arr, int(dim))
        else:
            arr = pa.array(values)
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        t = self._table
        if name in t.column_names:
            # Replace in place, preserving schema position (pyspark semantics).
            idx = t.column_names.index(name)
            return DataFrame(t.set_column(idx, name, arr))
        return DataFrame(t.append_column(name, arr))

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        names = [new if c == old else c for c in self.columns]
        return DataFrame(self._table.rename_columns(names))

    def filter(self, mask) -> "DataFrame":
        """Filter by boolean mask (numpy array / list / pyarrow bool array)."""
        if isinstance(mask, (list, np.ndarray)):
            mask = pa.array(np.asarray(mask, dtype=bool))
        return DataFrame(self._table.filter(mask))

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self._table.slice(0, n))

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(pa.concat_tables([self._table, other._table],
                                          promote_options="default"))

    def repartition(self, n: int) -> "DataFrame":
        """Re-chunk into ``n`` roughly equal record batches.  Partition-count
        variation is the reference's stand-in for multi-node behavior in tests
        (SURVEY.md §4) — preserved here for the same purpose."""
        n = max(1, min(int(n), max(1, len(self))))
        rows = len(self)
        sizes = [rows // n + (1 if i < rows % n else 0) for i in range(n)]
        combined = self._table.combine_chunks()
        batches, off = [], 0
        for s in sizes:
            if s == 0:
                continue
            batches.append(combined.slice(off, s))
            off += s
        return DataFrame(pa.concat_tables(batches) if batches else combined)

    @property
    def num_partitions(self) -> int:
        col0 = self._table.column(0) if self._table.num_columns else None
        return col0.num_chunks if col0 is not None else 1

    # -- materialization ---------------------------------------------------
    def collect(self) -> List[Row]:
        return [Row(r) for r in self._table.to_pylist()]

    def to_pandas(self):
        return self._table.to_pandas()

    def toPandas(self):
        return self.to_pandas()

    def dropna(self, *cols: str) -> "DataFrame":
        """Drop rows that are null in any of ``cols`` (all columns if none
        given).  Nulls arise by design — e.g. undecodable images become null
        structs (see image.io.readImagesWithCustomFn)."""
        import pyarrow.compute as pc

        names = list(cols) if cols else self.columns
        mask = None
        for c in names:
            valid = pc.is_valid(self._table.column(c))
            mask = valid if mask is None else pc.and_(mask, valid)
        return DataFrame(self._table.filter(mask)) if mask is not None else self

    def column_to_numpy(self, name: str) -> np.ndarray:
        """Materialize a column as numpy; list<float> columns stack to 2-D.

        Uniform-length list columns are read from the Arrow values buffer
        directly (one reshape — no per-row Python list round trip; measured
        ~100x faster than ``to_pylist`` on a 16k x 784 float column, the
        config-3 bench shape).  Ragged columns fall back to the row path
        and raise the same stacking error numpy would.
        """
        col = self._table.column(name)
        if col.null_count:
            raise ValueError(
                f"Column {name!r} contains {col.null_count} null(s); filter "
                f"them first (e.g. df.dropna({name!r}))")
        pytype = col.type
        if pa.types.is_list(pytype) or pa.types.is_fixed_size_list(pytype):
            dtype = pytype.value_type.to_pandas_dtype()
            chunks = (col.chunks if isinstance(col, pa.ChunkedArray)
                      else [col])
            parts = []
            for arr in chunks:  # per chunk: no combine_chunks 2GB overflow
                if len(arr) == 0:
                    continue
                if arr.flatten().null_count:
                    # inner nulls: keep the row path's loud semantics
                    # (TypeError for ints; the buffer path would smuggle
                    # them through as INT64_MIN/NaN)
                    parts.append(np.asarray(arr.to_pylist(), dtype=dtype))
                    continue
                if pa.types.is_fixed_size_list(pytype):
                    width = pytype.list_size
                else:
                    widths = np.diff(np.asarray(arr.offsets))
                    if not (widths == widths[0]).all():
                        # ragged rows: numpy row path (raises like np.stack)
                        parts.append(np.asarray(arr.to_pylist(),
                                                dtype=dtype))
                        continue
                    width = int(widths[0])
                flat = arr.flatten().to_numpy(zero_copy_only=False)
                parts.append(np.ascontiguousarray(flat).reshape(
                    -1, width).astype(dtype, copy=False))
            if not parts:
                # empty column: match the old to_pylist path's (0,) shape
                # when the row width is unknowable; fixed-size lists keep
                # their declared width
                if pa.types.is_fixed_size_list(pytype):
                    return np.zeros((0, pytype.list_size), dtype=dtype)
                return np.zeros((0,), dtype=dtype)
            out = parts[0] if len(parts) == 1 else np.vstack(parts)
            if not out.flags.writeable:
                # zero-copy view over the Arrow buffer: hand out a fresh
                # array (the old row path always did), so caller mutation
                # can neither raise nor write through to the table
                out = out.copy()
            return out
        return col.to_numpy(zero_copy_only=False)

    # -- batch protocol ----------------------------------------------------
    def iter_batches(self, batch_size: Optional[int] = None) -> Iterator[pa.RecordBatch]:
        """Iterate record batches; respects existing chunking unless a
        ``batch_size`` re-slicing is requested."""
        if batch_size is None:
            yield from self._table.to_batches()
        else:
            yield from self._table.to_batches(max_chunksize=batch_size)

    def map_blocks(self, fn: Callable[[pa.RecordBatch], pa.RecordBatch],
                   batch_size: int = 1024) -> "DataFrame":
        """Block-wise map: ``fn`` receives one arrow RecordBatch at a time
        and returns a RecordBatch (column layout may change).

        The vectorized counterpart of the reference's TensorFrames
        ``map_blocks`` executor path (``tensorframes.map_blocks`` —
        SURVEY.md §2 C11 ``blocked=True``): no per-row Python objects —
        ``fn`` works on columnar data.  Per-output-batch schemas are
        PROMOTED (null -> concrete, int -> float, missing column ->
        null-filled) exactly like ``map_rows`` — a later batch whose fn
        output widens a column must widen the frame, not raise (or
        truncate) against a schema pinned by the first batch."""
        out: List[pa.Table] = []
        schema: Optional[pa.Schema] = None
        for rb in self.iter_batches(batch_size):
            res = fn(rb)
            if not isinstance(res, pa.RecordBatch):
                raise TypeError(
                    f"map_blocks fn must return a pyarrow.RecordBatch, got "
                    f"{type(res).__name__}")
            t = pa.Table.from_batches([res])
            schema = _promote_schema(schema, t)
            out.append(t)
        if schema is None:
            return DataFrame.from_rows([])
        return DataFrame(_concat_conforming(out, schema))

    def map_rows(self, fn: Callable[[Row], dict],
                 batch_size: int = 1024,
                 materialize: bool = False) -> "DataFrame":
        """Row-wise map producing a new frame (host-side; used for cheap
        struct manipulation like resize UDFs, never for model compute).

        Processed BATCH-WISE: rows of one record batch are materialized,
        mapped, and converted back to arrow before the next batch is
        touched — peak Python-object residency is O(batch_size), not the
        table.  Each batch's schema is inferred INDEPENDENTLY and the
        running schema is promoted (null -> concrete, int -> float, ...)
        via ``unify_schemas`` whenever a later batch widens a column —
        matching the old whole-table inference.  (Building later batches
        directly against the pinned schema would silently TRUNCATE, e.g.
        float 3.5 -> int 3, because ``from_pylist(schema=...)`` coerces
        without raising.)

        Struct columns (e.g. image structs) are read ZERO-COPY: ``fn``
        receives dict views over the Arrow buffers (binary children as
        ``memoryview`` — wrap with ``bytes()`` if needed), and a struct
        the fn returns untouched is re-emitted without a Python->Arrow
        round trip, so mapping scalar columns next to an image column no
        longer pays per-row image materialization (~0.2 ms/row at 299^2
        — PERF.md "Zero-copy map_rows").

        ``materialize=True`` opts OUT of the zero-copy struct views and
        restores plain ``to_pylist`` dicts — binary struct children come
        back as real ``bytes`` instead of ``memoryview`` — for
        compatibility-sensitive row fns (``.decode()``, use as dict keys,
        pickling) at the old per-row materialization cost."""
        out_tables: List[pa.Table] = []
        schema: Optional[pa.Schema] = None
        for rb in self.iter_batches(batch_size):
            n = rb.num_rows
            if n == 0:
                continue
            col_rows: Dict[str, list] = {}
            for j, name in enumerate(rb.schema.names):
                a = rb.column(j)
                views = (_struct_view_rows(a)
                         if pa.types.is_struct(a.type) and not materialize
                         else None)
                col_rows[name] = (views if views is not None
                                  else a.to_pylist())
            names = rb.schema.names
            mapped = [fn(Row({nm: col_rows[nm][i] for nm in names}))
                      for i in range(n)]
            keys: List[str] = []
            for m in mapped:
                for k in m:
                    if k not in keys:
                        keys.append(k)
            pass_cols = {
                k: src for k in keys
                if (src := _passthrough_source(
                    [m.get(k) for m in mapped])) is not None}
            if len(pass_cols) < len(keys):
                t_plain = pa.Table.from_pylist(
                    [{k: v for k, v in m.items() if k not in pass_cols}
                     for m in mapped])
                t = pa.table(
                    [pass_cols[k] if k in pass_cols else t_plain.column(k)
                     for k in keys], names=keys)
            else:
                t = pa.table(list(pass_cols.values()),
                             names=list(pass_cols))
            schema = _promote_schema(schema, t)
            out_tables.append(t)
        if schema is None:
            return DataFrame.from_rows([])
        return DataFrame(_concat_conforming(out_tables, schema))
