"""SDL001/SDL002 — thread lifecycle and lockset discipline.

* **SDL001** — every constructed ``threading.Thread``/``Timer`` must be
  daemonized or joined.  The PR-4 wedged-queue lesson: a non-daemon
  stage thread that is not joined on every exit path outlives its run,
  blocks interpreter exit, and wedges the next run's queues.  The check
  is lexical: the thread must be constructed with ``daemon=True``, have
  ``<t>.daemon = True`` set, or have ``<t>.join(...)`` called — in the
  enclosing function for a local binding, anywhere in the class for a
  ``self.<x>`` binding (start/join commonly split across ``__init__``
  and ``close``).  A thread object that is never bound to a name cannot
  be joined at all and must be a daemon.

* **SDL002** — Eraser-style (Savage et al., SOSP 1997) intra-class
  lockset check: an attribute that is EVER written under ``with
  self.<lock>:`` (outside ``__init__``) is lock-guarded shared state,
  and every other write to it (outside ``__init__``, where the object
  is not yet shared) must also hold the lock.  Lock attributes are
  recognized by construction (``threading.Lock/RLock/Condition`` or the
  :mod:`~sparkdl_tpu.analysis.lockcheck` ``named_*`` factories) or by
  name (``*lock*``/``*cond*``/``*mutex*``).
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from sparkdl_tpu.analysis.core import Finding, LintContext, Module

_THREAD_CTORS = {"Thread", "Timer"}
_LOCK_CTORS = {"Lock", "RLock", "Condition",
               "named_lock", "named_rlock", "named_condition"}
_LOCKISH_NAME = re.compile(r"lock|cond|mutex", re.IGNORECASE)


def _call_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _is_thread_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        return (f.attr in _THREAD_CTORS and isinstance(f.value, ast.Name)
                and f.value.id == "threading")
    return isinstance(f, ast.Name) and f.id in _THREAD_CTORS


def _daemon_kwarg_true(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon":
            return isinstance(kw.value, ast.Constant) and bool(kw.value.value)
    return False


def _enclosing(module: Module, node: ast.AST, kinds) -> Optional[ast.AST]:
    cur = module.parent(node)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        cur = module.parent(cur)
    return None


def _is_self_attr(node: ast.AST, attr: Optional[str] = None) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == "self"
            and (attr is None or node.attr == attr))


def _name_is_handled(scope: ast.AST, name: str) -> bool:
    """``name.join(...)`` called or ``name.daemon = True`` set anywhere
    in ``scope``."""
    for n in ast.walk(scope):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == "join"
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == name):
            return True
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if (isinstance(t, ast.Attribute) and t.attr == "daemon"
                        and isinstance(t.value, ast.Name)
                        and t.value.id == name
                        and isinstance(n.value, ast.Constant)
                        and bool(n.value.value)):
                    return True
    return False


def _container_binding(module: Module,
                       call: ast.Call) -> Optional[tuple]:
    """For a thread constructed inside a list/tuple literal or a
    comprehension, the ``(scope-search node, name)`` the container is
    assigned to — the ``threads = [Thread(...), ...]`` pool pattern."""
    node: ast.AST = call
    parent = module.parent(node)
    seen_container = False
    while isinstance(parent, (ast.List, ast.Tuple, ast.ListComp,
                              ast.comprehension, ast.IfExp)):
        seen_container = seen_container or not isinstance(parent, ast.IfExp)
        node = parent
        parent = module.parent(parent)
    if not seen_container:
        return None
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
            and isinstance(parent.targets[0], ast.Name):
        return parent, parent.targets[0].id
    return None


def _list_is_joined(scope: ast.AST, list_name: str) -> bool:
    """A ``for t in <list_name>: ... t.join()`` loop exists in scope."""
    for n in ast.walk(scope):
        if not isinstance(n, ast.For):
            continue
        if not (isinstance(n.iter, ast.Name) and n.iter.id == list_name
                and isinstance(n.target, ast.Name)):
            continue
        if _name_is_handled(n, n.target.id):
            return True
    return False


def _self_attr_is_handled(cls: ast.AST, attr: str) -> bool:
    """``self.<attr>.join(...)`` called or ``self.<attr>.daemon = True``
    set anywhere in the class."""
    for n in ast.walk(cls):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == "join"
                and _is_self_attr(n.func.value, attr)):
            return True
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if (isinstance(t, ast.Attribute) and t.attr == "daemon"
                        and _is_self_attr(t.value, attr)
                        and isinstance(n.value, ast.Constant)
                        and bool(n.value.value)):
                    return True
    return False


def rule_sdl001(module: Module, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(module.tree):
        if not _is_thread_ctor(node):
            continue
        if _daemon_kwarg_true(node):
            continue
        parent = module.parent(node)
        scope = _enclosing(module, node,
                           (ast.FunctionDef, ast.AsyncFunctionDef)) \
            or module.tree
        handled = False
        binding = "an unbound"
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            target = parent.targets[0]
            if isinstance(target, ast.Name):
                binding = f"local {target.id!r}"
                handled = _name_is_handled(scope, target.id)
            elif _is_self_attr(target):
                binding = f"attribute 'self.{target.attr}'"
                cls = _enclosing(module, node, (ast.ClassDef,))
                handled = cls is not None and _self_attr_is_handled(
                    cls, target.attr)
        else:
            pool = _container_binding(module, node)
            if pool is not None:
                binding = f"pooled (list {pool[1]!r})"
                handled = _list_is_joined(scope, pool[1])
        if not handled:
            findings.append(Finding(
                "SDL001", module.path, node.lineno,
                f"{binding} thread is neither daemon=True nor joined; a "
                f"non-daemon thread that can outlive its run wedges "
                f"queues and interpreter exit (join it on every exit "
                f"path, or daemonize)"))
    return findings


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attribute names on ``self`` that hold locks: assigned from a lock
    constructor/factory, or lock-ish by name."""
    out: Set[str] = set()
    for n in ast.walk(cls):
        if not isinstance(n, ast.Assign):
            continue
        for t in n.targets:
            if not _is_self_attr(t):
                continue
            if _LOCKISH_NAME.search(t.attr):
                out.add(t.attr)
            elif (isinstance(n.value, ast.Call)
                  and _call_name(n.value) in _LOCK_CTORS):
                out.add(t.attr)
    return out


def _with_holds_self_lock(node: ast.With, locks: Set[str]) -> bool:
    for item in node.items:
        expr = item.context_expr
        # `with self._lock:` and `with self._lock.something():` both
        # count only for the bare-attribute form — acquire() aliases etc.
        # stay out of scope for a lexical checker.
        if isinstance(expr, ast.Attribute) and _is_self_attr(expr) \
                and expr.attr in locks:
            return True
    return False


def rule_sdl002(module: Module, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for cls in ast.walk(module.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _lock_attrs(cls)
        if not locks:
            continue
        # (attr, line, under_lock, in_init) for every `self.<attr>` write
        writes: List[tuple] = []

        def visit(node: ast.AST, under: bool, in_init: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                in_init = in_init or node.name == "__init__"
            if isinstance(node, ast.With) and _with_holds_self_lock(
                    node, locks):
                under = True
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if _is_self_attr(t) and t.attr not in locks:
                    writes.append((t.attr, t.lineno, under, in_init))
            for child in ast.iter_child_nodes(node):
                # nested ClassDefs get their own pass from the outer loop
                if isinstance(child, ast.ClassDef):
                    continue
                visit(child, under, in_init)

        for stmt in cls.body:
            visit(stmt, False, False)
        guarded = {a for a, _, under, in_init in writes
                   if under and not in_init}
        for attr, line, under, in_init in writes:
            if attr in guarded and not under and not in_init:
                findings.append(Finding(
                    "SDL002", module.path, line,
                    f"'self.{attr}' is written under a lock elsewhere in "
                    f"{cls.name} but written here without one — either "
                    f"hold the lock or stop pretending the attribute is "
                    f"lock-guarded"))
    return findings
