"""SDL004 — every fault-site string must exist in the canonical registry.

The chaos layer's whole value is that a spec'd site FIRES; a typo'd
site in an ``inject("...")``/``has_rules("...")`` call would silently
never fire and turn a chaos run vacuous (spec-side typos already fail
at parse time — this closes the code-side half).  The registry is the
``SITE_HELP`` table in ``sparkdl_tpu/faults/sites.py``, read HERE with
``ast`` — the linter never imports the package under analysis.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, List, Optional, Set

from sparkdl_tpu.analysis.core import Finding, LintContext, Module

_SITE_CALLS = {"inject", "has_rules"}


def load_site_registry_file(path: str) -> Optional[Set[str]]:
    """Parse ONE registry file (``--sites-file``): the keys of its
    ``SITE_HELP`` dict literal, falling back to a ``SITES`` tuple
    literal.  None when the file holds neither."""
    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        names = {t.id for t in node.targets if isinstance(t, ast.Name)}
        if "SITE_HELP" in names and isinstance(node.value, ast.Dict):
            keys = {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
            if keys:
                return keys
        if "SITES" in names and isinstance(node.value, ast.Tuple):
            keys = {e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)}
            if keys:
                return keys
    return None


def load_site_registry(targets: Iterable[str]) -> Optional[Set[str]]:
    """Auto-locate ``faults/sites.py`` under the DIRECTORY targets and
    extract its site set (plain-file targets contribute only if they
    are themselves a ``sites.py`` — linting ``bench.py`` must not walk
    the whole checkout).  None when no registry file is found; pass an
    explicit file through :func:`load_site_registry_file` instead."""
    candidates: List[str] = []
    for t in targets:
        if os.path.isfile(t):
            if os.path.basename(t) == "sites.py":
                candidates.append(t)
            continue
        direct = os.path.join(t, "faults", "sites.py")
        if os.path.isfile(direct):
            candidates.append(direct)
            continue
        for dirpath, dirnames, filenames in os.walk(t):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            if "sites.py" in filenames and \
                    os.path.basename(dirpath) == "faults":
                candidates.append(os.path.join(dirpath, "sites.py"))
    for path in candidates:
        sites = load_site_registry_file(path)
        if sites:
            return sites
    return None


def rule_sdl004(module: Module, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    sites = ctx.sites
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        if name not in _SITE_CALLS or not node.args:
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue
        if sites is None:
            findings.append(Finding(
                "SDL004", module.path, node.lineno,
                f"fault site {first.value!r} used but no canonical "
                f"registry (faults/sites.py SITE_HELP) was found under "
                f"the lint targets — site strings cannot be verified"))
            continue
        if first.value not in sites:
            known = ", ".join(sorted(sites))
            findings.append(Finding(
                "SDL004", module.path, node.lineno,
                f"unknown fault site {first.value!r} — a typo'd site "
                f"never fires and makes chaos runs vacuous; register it "
                f"in faults/sites.py or fix the name (known: {known})"))
    return findings
