"""SDL004 — every fault-site string must exist in the canonical registry.

The chaos layer's whole value is that a spec'd site FIRES; a typo'd
site in an ``inject("...")``/``has_rules("...")`` call would silently
never fire and turn a chaos run vacuous (spec-side typos already fail
at parse time — this closes the code-side half).  The registry is the
``SITE_HELP`` table in ``sparkdl_tpu/faults/sites.py``, read HERE with
``ast`` — the linter never imports the package under analysis.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from sparkdl_tpu.analysis.core import (Finding, LintContext, Module,
                                       load_name_registry_file,
                                       locate_name_registry)

_SITE_CALLS = {"inject", "has_rules"}


def load_site_registry_file(path: str) -> Optional[Set[str]]:
    """Parse ONE registry file (``--sites-file``): the keys of its
    ``SITE_HELP`` dict literal, falling back to a ``SITES`` tuple
    literal.  None when the file holds neither."""
    return load_name_registry_file(path, "SITE_HELP", "SITES")


def load_site_registry(targets: Iterable[str]) -> Optional[Set[str]]:
    """Auto-locate ``faults/sites.py`` under the DIRECTORY targets and
    extract its site set (plain-file targets contribute only if they
    are themselves a ``sites.py`` — linting ``bench.py`` must not walk
    the whole checkout).  None when no registry file is found; pass an
    explicit file through :func:`load_site_registry_file` instead."""
    return locate_name_registry(targets, "faults", "sites.py",
                                "SITE_HELP", "SITES")


def rule_sdl004(module: Module, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    sites = ctx.sites
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        if name not in _SITE_CALLS or not node.args:
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue
        if sites is None:
            findings.append(Finding(
                "SDL004", module.path, node.lineno,
                f"fault site {first.value!r} used but no canonical "
                f"registry (faults/sites.py SITE_HELP) was found under "
                f"the lint targets — site strings cannot be verified"))
            continue
        if first.value not in sites:
            known = ", ".join(sorted(sites))
            findings.append(Finding(
                "SDL004", module.path, node.lineno,
                f"unknown fault site {first.value!r} — a typo'd site "
                f"never fires and makes chaos runs vacuous; register it "
                f"in faults/sites.py or fix the name (known: {known})"))
    return findings
