"""sparkdl_tpu.analysis — graftlint: project-native static analysis +
runtime concurrency checking.

PRs 1–4 grew a genuinely concurrent scoring stack (batcher/dispatcher/
worker/pipeline threads, ~19 lock sites, named fault sites, paired
spans); this package turns those invariants from tribal memory into
machine-checked rules, run by ``run-tests.sh`` on every invocation and
by ``tools/graftlint.py`` standalone:

====== ==================================================================
code   invariant
====== ==================================================================
SDL000 every allow pragma carries a ``reason=`` (meta-rule)
SDL001 started threads are daemonized or joined (PR 4's wedged-queue
       lesson)
SDL002 an attribute ever written under ``with self._lock:`` is never
       written without it (Eraser-style lockset, per class)
SDL003 broad/bare ``except`` re-raises, logs via ``utils.logging``, or
       carries an allow pragma
SDL004 fault-site strings exist in ``faults/sites.py`` (no typo'd
       chaos sites)
SDL005 metric/span names match ``dotted.lowercase``; opened spans are
       closable on every path
SDL006 ``time.time()`` never feeds a latency subtraction
       (``perf_counter``/``monotonic`` only)
SDL007 every ``jax.jit`` call site passes an explicit
       ``donate_argnums``/``donate_argnames`` (empty = decided "no");
       the lowered-program half is graftcheck GC001
SDL008 flight-event strings exist in ``obs/flight.py`` ``EVENT_HELP``
       (no typo'd black-box events — the SDL004 pattern for the
       incident recorder)
====== ==================================================================

Suppress with ``# graftlint: allow=SDLxxx reason=<why>`` on the
offending line or the line above.  The runtime half —
:mod:`~sparkdl_tpu.analysis.lockcheck`, gated by ``SPARKDL_LOCKCHECK=1``
— wraps the stack's locks and fails on acquisition-order cycles under
the chaos suite's injected schedules.

Everything is stdlib-only and nothing here imports the code under
analysis, so ``tools/graftlint.py`` runs in milliseconds with no jax
initialization.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Set

from sparkdl_tpu.analysis.core import (Finding, LintContext, Module,
                                       collect_files, load_module,
                                       run_rules)
from sparkdl_tpu.analysis.rules_flight import (load_event_registry,
                                               load_event_registry_file,
                                               rule_sdl008)
from sparkdl_tpu.analysis.rules_hygiene import rule_sdl003, rule_sdl006
from sparkdl_tpu.analysis.rules_jit import rule_sdl007
from sparkdl_tpu.analysis.rules_obs import (rule_sdl005_names,
                                            rule_sdl005_pairing)
from sparkdl_tpu.analysis.rules_sites import (load_site_registry,
                                              load_site_registry_file,
                                              rule_sdl004)
from sparkdl_tpu.analysis.rules_threads import rule_sdl001, rule_sdl002

__all__ = [
    "Finding",
    "LintContext",
    "ALL_RULES",
    "RULE_HELP",
    "lint_source",
    "lint_paths",
    "load_site_registry",
    "load_site_registry_file",
    "load_event_registry",
    "load_event_registry_file",
]

ALL_RULES = (
    rule_sdl001,
    rule_sdl002,
    rule_sdl003,
    rule_sdl004,
    rule_sdl005_names,
    rule_sdl005_pairing,
    rule_sdl006,
    rule_sdl007,
    rule_sdl008,
)

RULE_HELP = {
    "SDL000": "allow pragmas must carry reason=<why>",
    "SDL001": "started threads must be daemonized or joined",
    "SDL002": "lock-guarded attributes are never written lock-free",
    "SDL003": "broad except must re-raise, log, or carry a pragma",
    "SDL004": "fault-site strings must exist in faults/sites.py",
    "SDL005": "metric/span names dotted-lowercase; spans always closed",
    "SDL006": "time.time() never feeds a latency subtraction",
    "SDL007": "every jax.jit site decides donation explicitly",
    "SDL008": "flight-event strings must exist in obs/flight.py",
}


def lint_source(source: str, path: str = "<string>",
                sites: Optional[Set[str]] = None,
                events: Optional[Set[str]] = None) -> List[Finding]:
    """Lint one in-memory snippet (the test-fixture entry point).
    ``sites``/``events`` are the fault-site registry and flight-event
    catalog SDL004/SDL008 check against; None means "no registry
    found", which each rule reports on any use."""
    try:
        module = load_module(source, path)
    except SyntaxError as e:
        return [Finding("SDL000", path, e.lineno or 1,
                        f"syntax error: {e.msg}")]
    return run_rules(module, ALL_RULES,
                     LintContext(sites=sites, events=events))


def lint_paths(targets: Iterable[str],
               sites: Optional[Set[str]] = None,
               events: Optional[Set[str]] = None) -> List[Finding]:
    """Lint files/directories.  The fault-site registry and flight-event
    catalog are auto-located under the targets unless passed
    explicitly."""
    targets = list(targets)
    if sites is None:
        sites = load_site_registry(targets)
    if events is None:
        events = load_event_registry(targets)
    ctx = LintContext(sites=sites, events=events)
    findings: List[Finding] = []
    for path in collect_files(targets):
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        try:
            module = load_module(source, path)
        except SyntaxError as e:
            findings.append(Finding("SDL000", path, e.lineno or 1,
                                    f"syntax error: {e.msg}"))
            continue
        findings.extend(run_rules(module, ALL_RULES, ctx))
    return findings
