"""graftlint core: file loading, pragma handling, rule dispatch.

A dependency-free (stdlib ``ast``) analysis engine in the
fixpoint-on-every-commit spirit of Facebook Infer (Calcagno et al.,
NASA FM 2015): the rules encode THIS project's hard-won invariants —
joined threads, guarded attributes, registered fault sites, paired
spans, monotonic timing — so a refactor that silently reintroduces a
PR-1..4 bug class fails ``run-tests.sh`` instead of waiting for the
next incident.

Suppression pragma (one per line, reason REQUIRED)::

    risky_thing()  # graftlint: allow=SDL003 reason=probe must not raise

The pragma suppresses the named rule(s) on its own line and on the line
directly below it (so a pragma can sit on its own line above a long
statement).  A pragma with no reason is itself a finding (``SDL000``) —
an unexplained exemption is exactly the "memory of whoever wrote it"
this tool exists to replace.

The engine imports nothing from the rest of ``sparkdl_tpu`` and never
imports the code under analysis — linting a file cannot initialize jax,
load weights, or run module side effects.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set

__all__ = [
    "Finding",
    "Module",
    "LintContext",
    "load_module",
    "collect_files",
    "run_rules",
]

#: pragma grammar (after a comment-leading "graftlint:" marker):
#: ``allow=SDL001[,SDL005] reason=<text>``
_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*allow=(?P<codes>[A-Za-z0-9_,]+)"
    r"(?:\s+reason=(?P<reason>\S.*))?")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    code: str       # e.g. "SDL003"
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


@dataclass
class Module:
    """One parsed source file plus its pragma table."""

    path: str
    source: str
    tree: ast.AST
    lines: List[str]
    # line number -> codes allowed on that line (and the line below)
    pragmas: Dict[int, Set[str]] = field(default_factory=dict)
    # pragma lines missing the mandatory reason
    bad_pragmas: List[int] = field(default_factory=list)
    parents: Dict[int, ast.AST] = field(default_factory=dict)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(id(node))


@dataclass
class LintContext:
    """Cross-file state the rules share: the canonical fault-site
    registry (None = SDL004 cannot run and reports that once) and the
    flight-event catalog (None = SDL008 likewise)."""

    sites: Optional[Set[str]] = None
    events: Optional[Set[str]] = None


def _scan_pragmas(source: str) -> tuple:
    """Pragmas from REAL comment tokens (``tokenize``), so pragma-shaped
    text inside string literals neither suppresses nor triggers
    anything."""
    pragmas: Dict[int, Set[str]] = {}
    bad: List[int] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [(tok.start[0], tok.string) for tok in tokens
                    if tok.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return pragmas, bad  # unparseable source is reported elsewhere
    for line, text in comments:
        m = _PRAGMA_RE.search(text)
        if not m:
            continue
        if not m.group("reason"):
            bad.append(line)
            continue
        codes = {c.strip().upper() for c in m.group("codes").split(",")
                 if c.strip()}
        pragmas[line] = codes
    return pragmas, bad


def load_module(source: str, path: str) -> Module:
    """Parse one file into a :class:`Module` (raises ``SyntaxError`` on
    unparseable input — callers surface it as an ``SDL000`` finding)."""
    tree = ast.parse(source, filename=path)
    lines = source.splitlines()
    pragmas, bad = _scan_pragmas(source)
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return Module(path=path, source=source, tree=tree, lines=lines,
                  pragmas=pragmas, bad_pragmas=bad, parents=parents)


def collect_files(targets: Iterable[str]) -> List[str]:
    """Expand file/directory targets into a sorted ``*.py`` list
    (skipping ``__pycache__`` and hidden directories)."""
    out: List[str] = []
    for target in targets:
        if os.path.isfile(target):
            out.append(target)
            continue
        for dirpath, dirnames, filenames in os.walk(target):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def load_name_registry_file(path: str, dict_name: str,
                            tuple_name: str) -> Optional[Set[str]]:
    """Parse ONE registry file with ``ast`` (never by import): the keys
    of a ``dict_name`` dict literal, falling back to a ``tuple_name``
    tuple literal.  None when the file holds neither.  Shared by the
    SDL004 fault-site and SDL008 flight-event loaders — one
    implementation, so a blind spot (e.g. annotated assignments are
    invisible) exists once, not per registry."""
    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        names = {t.id for t in node.targets if isinstance(t, ast.Name)}
        if dict_name in names and isinstance(node.value, ast.Dict):
            keys = {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
            if keys:
                return keys
        if tuple_name in names and isinstance(node.value, ast.Tuple):
            keys = {e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)}
            if keys:
                return keys
    return None


def locate_name_registry(targets: Iterable[str], parent_dir: str,
                         basename: str, dict_name: str,
                         tuple_name: str) -> Optional[Set[str]]:
    """Auto-locate ``<parent_dir>/<basename>`` under the DIRECTORY
    targets and extract its name set (plain-file targets contribute
    only when they ARE a ``basename`` — linting ``bench.py`` must not
    walk the whole checkout).  None when no registry file is found."""
    candidates: List[str] = []
    for t in targets:
        if os.path.isfile(t):
            if os.path.basename(t) == basename:
                candidates.append(t)
            continue
        direct = os.path.join(t, parent_dir, basename)
        if os.path.isfile(direct):
            candidates.append(direct)
            continue
        for dirpath, dirnames, filenames in os.walk(t):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            if basename in filenames and \
                    os.path.basename(dirpath) == parent_dir:
                candidates.append(os.path.join(dirpath, basename))
    for path in candidates:
        names = load_name_registry_file(path, dict_name, tuple_name)
        if names:
            return names
    return None


def _suppressed(module: Module, finding: Finding) -> bool:
    for line in (finding.line, finding.line - 1):
        codes = module.pragmas.get(line)
        if codes and finding.code in codes:
            return True
    return False


def run_rules(module: Module, rules, ctx: LintContext) -> List[Finding]:
    """All findings for one module: rule output minus pragma-suppressed,
    plus ``SDL000`` for every reason-less pragma (never suppressible —
    the whole point is that exemptions carry their why)."""
    findings: List[Finding] = []
    for rule in rules:
        for f in rule(module, ctx):
            if not _suppressed(module, f):
                findings.append(f)
    for line in module.bad_pragmas:
        findings.append(Finding(
            "SDL000", module.path, line,
            "graftlint pragma without a reason= clause; every exemption "
            "must say why (allow=SDLxxx reason=<text>)"))
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings
