"""SDL008 — every flight-event string must exist in the one catalog.

The flight recorder's whole value is that an incident's state changes
are FOUND at post-mortem time; a typo'd event name in a
``flight_emit("...")``/``flight.emit("...")`` call would raise at the
first real incident (``validate_event`` is the runtime half) — or, on a
path no test drives, silently compile into an instrumentation site
``tools/blackbox.py`` can never reconstruct.  The catalog is the
``EVENT_HELP`` table in ``sparkdl_tpu/obs/flight.py``, read HERE with
``ast`` — the linter never imports the package under analysis (the
SDL004 pattern, applied to the recorder).

Only the recorder's own spellings are matched (the bare
``flight_emit`` import alias and the ``flight.emit`` module attribute)
— ``emit`` is too common a name to claim outright (``bench.py`` has had
its own ``emit()`` since PR 0).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from sparkdl_tpu.analysis.core import (Finding, LintContext, Module,
                                       load_name_registry_file,
                                       locate_name_registry)


def _is_event_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id == "flight_emit"
    if isinstance(f, ast.Attribute) and f.attr == "emit":
        return isinstance(f.value, ast.Name) and f.value.id == "flight"
    return False


def load_event_registry_file(path: str) -> Optional[Set[str]]:
    """Parse ONE catalog file (``--events-file``): the keys of its
    ``EVENT_HELP`` dict literal, falling back to an ``EVENTS`` tuple
    literal.  None when the file holds neither."""
    return load_name_registry_file(path, "EVENT_HELP", "EVENTS")


def load_event_registry(targets: Iterable[str]) -> Optional[Set[str]]:
    """Auto-locate ``obs/flight.py`` under the DIRECTORY targets and
    extract its event catalog (plain-file targets contribute only when
    they are themselves a ``flight.py`` — the SDL004 locator policy)."""
    return locate_name_registry(targets, "obs", "flight.py",
                                "EVENT_HELP", "EVENTS")


def rule_sdl008(module: Module, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call) or not _is_event_call(node):
            continue
        if not node.args:
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue  # dynamic names hit validate_event at runtime
        if ctx.events is None:
            findings.append(Finding(
                "SDL008", module.path, node.lineno,
                f"flight event {first.value!r} emitted but no catalog "
                f"(obs/flight.py EVENT_HELP) was found under the lint "
                f"targets — event names cannot be verified"))
            continue
        if first.value not in ctx.events:
            known = ", ".join(sorted(ctx.events))
            findings.append(Finding(
                "SDL008", module.path, node.lineno,
                f"unknown flight event {first.value!r} — an uncataloged "
                f"event either raises at the first real incident or "
                f"records something blackbox can never explain; register "
                f"it in obs/flight.py EVENT_HELP or fix the name "
                f"(known: {known})"))
    return findings
