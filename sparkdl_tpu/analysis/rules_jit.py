"""SDL007 — every jit call site makes an explicit donation decision.

Buffer donation is the cheapest device-memory win the stack has
(ROADMAP item 3): a dispatch-path program that forgets
``donate_argnums`` silently doubles its peak residency, and nothing at
runtime ever complains.  The rule forces the decision to be VISIBLE at
every ``jax.jit`` call site:

* pass ``donate_argnums=...`` / ``donate_argnames=...`` explicitly — an
  explicit empty tuple counts: it says "considered, and no donation is
  safe here", which is a decision, not an omission; or
* carry ``# graftlint: allow=SDL007 reason=<why donation is unsafe or
  pointless>``.

Both the direct form (``jax.jit(fn, ...)``) and the decorator-factory
form (``functools.partial(jax.jit, ...)`` — ops/sepconv.py's idiom) are
checked.  The deeper program-level half of this invariant — whether a
DECLARED donation actually establishes an input/output alias once
lowered — is graftcheck GC001 (``analysis.program``); SDL007 is the
source-level gate that keeps new call sites from skipping the question
entirely.
"""

from __future__ import annotations

import ast
from typing import List, Set

from sparkdl_tpu.analysis.core import Finding, LintContext, Module

_DONATE_KW = {"donate_argnums", "donate_argnames"}


def _jit_name_tables(tree: ast.AST) -> tuple:
    """``(jax_module_aliases, direct_jit_names, partial_names)``: names
    the ``jax`` module is bound to, names ``jax.jit`` itself is bound to
    (``from jax import jit [as j]``), and names ``functools.partial`` is
    callable under (``functools`` aliases handled at the call site)."""
    jax_mods: Set[str] = set()
    direct: Set[str] = set()
    functools_mods: Set[str] = set()
    partial_names: Set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            for alias in n.names:
                if alias.name == "jax":
                    jax_mods.add(alias.asname or "jax")
                elif alias.name == "functools":
                    functools_mods.add(alias.asname or "functools")
        elif isinstance(n, ast.ImportFrom):
            if n.module == "jax":
                for alias in n.names:
                    if alias.name == "jit":
                        direct.add(alias.asname or "jit")
            elif n.module == "functools":
                for alias in n.names:
                    if alias.name == "partial":
                        partial_names.add(alias.asname or "partial")
    return jax_mods, direct, functools_mods, partial_names


def rule_sdl007(module: Module, ctx: LintContext) -> List[Finding]:
    jax_mods, direct, functools_mods, partial_names = _jit_name_tables(
        module.tree)

    def is_jit_ref(node: ast.AST) -> bool:
        if isinstance(node, ast.Attribute):
            return (node.attr == "jit" and isinstance(node.value, ast.Name)
                    and node.value.id in jax_mods)
        return isinstance(node, ast.Name) and node.id in direct

    def is_partial_ref(node: ast.AST) -> bool:
        if isinstance(node, ast.Attribute):
            return (node.attr == "partial"
                    and isinstance(node.value, ast.Name)
                    and node.value.id in functools_mods)
        return isinstance(node, ast.Name) and node.id in partial_names

    findings: List[Finding] = []

    def report(form: str, lineno: int) -> None:
        findings.append(Finding(
            "SDL007", module.path, lineno,
            f"{form} without an explicit donate_argnums/donate_argnames; "
            f"decide donation at every jit site (an explicit empty tuple "
            f"records 'no donation is safe here') or annotate why the "
            f"question does not apply"))

    for node in ast.walk(module.tree):
        # the bare decorator form has NO Call node: @jax.jit / @jit
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if is_jit_ref(dec):
                    report("@jax.jit (bare decorator)", dec.lineno)
            continue
        if not isinstance(node, ast.Call):
            continue
        if is_jit_ref(node.func):
            form = "jax.jit(...)"
        elif (is_partial_ref(node.func) and node.args
                and is_jit_ref(node.args[0])):
            form = "functools.partial(jax.jit, ...)"
        else:
            continue
        if any(kw.arg in _DONATE_KW for kw in node.keywords):
            continue
        report(form, node.lineno)
    return findings
