"""Runtime lock-order checking for the scoring stack (``SPARKDL_LOCKCHECK``).

The dynamic half of graftlint: the static rules (SDL001/SDL002) prove
threads are joined and guarded attributes stay guarded, but a lock-order
DEADLOCK only shows up when two threads interleave acquisitions — which
is exactly what the chaos suite's injected schedules provoke.  Following
the lockset idea of Eraser (Savage et al., SOSP 1997) applied to ORDER
rather than ownership: every instrumented acquisition records an edge
``held -> wanted`` in a process-global graph of lock NAMES (lock
classes, not instances — two engines' breaker locks are one node), and
an acquisition that would close a cycle raises :class:`LockOrderError`
BEFORE blocking, naming the full cycle.  A schedule that merely
*could* deadlock is enough to fail — the probe never has to actually
wedge.

Gate: the stack creates every lock through :func:`named_lock` /
:func:`named_rlock` / :func:`named_condition`.  With ``SPARKDL_LOCKCHECK``
unset (production) these return PLAIN ``threading`` primitives — zero
wrapper, zero per-acquire cost, the same disabled-path budget as
``SPARKDL_TRACE``/``SPARKDL_FAULTS``.  With ``SPARKDL_LOCKCHECK=1`` (the
run-tests.sh chaos stage) they return checked wrappers.  Tests flip the
gate programmatically with :func:`enable` / :func:`disable` and isolate
state with :func:`reset`.

Everything here is stdlib-only and imports nothing from the rest of
``sparkdl_tpu`` — the lock factories sit below every other layer.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set

__all__ = [
    "LockOrderError",
    "named_lock",
    "named_rlock",
    "named_condition",
    "enabled",
    "enable",
    "disable",
    "reset",
    "order_graph",
]

_ON = ("1", "true", "on", "yes")

# None = consult the env on first ask; True/False = pinned by enable()/
# disable() (tests) or by the first env read.
_enabled: Optional[bool] = None

# name -> names acquired while it was held.  Guarded by _graph_lock; the
# graph lock is only ever held for O(edges) bookkeeping, never while
# blocking on an instrumented lock.
_edges: Dict[str, Set[str]] = {}
_graph_lock = threading.Lock()
_held = threading.local()  # per-thread stack of held lock names


class LockOrderError(RuntimeError):
    """Acquiring ``wanted`` while holding ``held`` closes a cycle in the
    process's lock-acquisition-order graph — two threads running these
    paths concurrently can deadlock.  ``cycle`` is the full name path
    ``wanted -> ... -> held -> wanted``."""

    def __init__(self, wanted: str, held: str, cycle: List[str]):
        super().__init__(
            f"lock-order cycle: acquiring {wanted!r} while holding "
            f"{held!r} inverts the established order "
            f"{' -> '.join(cycle)} -> {cycle[0]} — two threads on these "
            f"paths can deadlock")
        self.wanted = wanted
        self.held = held
        self.cycle = cycle


def enabled() -> bool:
    """Whether lock instrumentation is on (``SPARKDL_LOCKCHECK`` truthy,
    read once, or pinned by :func:`enable`/:func:`disable`)."""
    global _enabled
    if _enabled is None:
        raw = os.environ.get("SPARKDL_LOCKCHECK", "").strip().lower()
        _enabled = raw in _ON
    return _enabled


def enable() -> None:
    """Turn instrumentation on for locks created FROM NOW ON (tests)."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Clear the recorded order graph (test isolation).  Locks already
    created keep reporting into the fresh graph."""
    with _graph_lock:
        _edges.clear()


def order_graph() -> Dict[str, List[str]]:
    """Copy of the acquisition-order graph, ``{held: [acquired, ...]}``
    — what the chaos suite can dump on failure."""
    with _graph_lock:
        return {k: sorted(v) for k, v in _edges.items()}


def _stack() -> List[str]:
    s = getattr(_held, "stack", None)
    if s is None:
        s = _held.stack = []
    return s


def _path_between(src: str, dst: str) -> Optional[List[str]]:
    """DFS path ``src -> ... -> dst`` in the edge graph — caller holds
    ``_graph_lock``."""
    seen = set()
    stack = [(src, [src])]
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        if node in seen:
            continue
        seen.add(node)
        for nxt in _edges.get(node, ()):
            stack.append((nxt, path + [nxt]))
    return None


def _note_acquire(name: str, check: bool = True) -> None:
    """Record that this thread is acquiring ``name`` with its current
    held set; raise :class:`LockOrderError` when the new edge closes a
    cycle.  Re-entrant / same-name acquisitions (two instances of one
    lock class) are skipped — instance granularity would flood the graph
    with self-edges that cannot deadlock across classes."""
    held = _stack()
    if check and held:
        with _graph_lock:
            for h in held:
                if h == name or name in _edges.get(h, ()):
                    continue
                # would h -> name close a cycle (a name -> ... -> h path)?
                cycle = _path_between(name, h)
                if cycle is not None:
                    raise LockOrderError(name, h, cycle)
                _edges.setdefault(h, set()).add(name)
    held.append(name)


def _note_release(name: str) -> None:
    held = _stack()
    if held and held[-1] == name:
        held.pop()
    elif name in held:  # out-of-order release: tolerate, stay consistent
        held.remove(name)


class _CheckedLock:
    """Order-checking wrapper with the ``threading.Lock``/``RLock``
    surface the stack uses (``acquire``/``release``/context manager/
    ``locked``)."""

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _note_acquire(self.name)
        ok = self._inner.acquire(blocking, timeout)
        if not ok:
            _note_release(self.name)
        return ok

    def release(self) -> None:
        self._inner.release()
        _note_release(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<CheckedLock {self.name!r} {self._inner!r}>"


class _CheckedCondition:
    """Order-checking ``threading.Condition`` wrapper.  ``wait`` releases
    the underlying lock, so the held-stack entry is popped for the wait
    and re-pushed (without re-checking: waking up re-acquires the SAME
    lock, which established no new ordering) when it returns."""

    def __init__(self, name: str, inner: threading.Condition):
        self.name = name
        self._inner = inner

    def acquire(self, *args) -> bool:
        _note_acquire(self.name)
        ok = self._inner.acquire(*args)
        if not ok:
            _note_release(self.name)
        return ok

    def release(self) -> None:
        self._inner.release()
        _note_release(self.name)

    def wait(self, timeout: Optional[float] = None) -> bool:
        _note_release(self.name)
        try:
            return self._inner.wait(timeout)
        finally:
            _note_acquire(self.name, check=False)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        _note_release(self.name)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            _note_acquire(self.name, check=False)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<CheckedCondition {self.name!r} {self._inner!r}>"


def named_lock(name: str):
    """A ``threading.Lock`` registered under ``name`` in the order
    checker when ``SPARKDL_LOCKCHECK`` is on; a PLAIN ``threading.Lock``
    otherwise (zero added cost — the production path)."""
    if not enabled():
        return threading.Lock()
    return _CheckedLock(name, threading.Lock())


def named_rlock(name: str):
    """:func:`named_lock` for ``threading.RLock`` (re-entrant holds of
    the same instance are order-neutral and skipped by the checker)."""
    if not enabled():
        return threading.RLock()
    return _CheckedLock(name, threading.RLock())


def named_condition(name: str):
    """:func:`named_lock` for ``threading.Condition``."""
    if not enabled():
        return threading.Condition()
    return _CheckedCondition(name, threading.Condition())
