"""SDL003/SDL006 — exception hygiene and monotonic timing.

* **SDL003** — a broad handler (bare ``except:``, ``except Exception``,
  ``except BaseException``) must re-raise, log through a
  ``utils.logging`` logger, or carry an allow pragma with a reason.
  Swallowing everything silently is how injected chaos faults — and
  real device deaths — disappear into "it returned None".

* **SDL006** — ``time.time()`` is banned in latency paths: wall clock
  steps under NTP slew and is not monotonic, so a latency computed from
  it can be negative or wildly wrong exactly when the fleet is under
  stress.  The rule flags any ``time.time()`` value that feeds a
  subtraction (the latency idiom); plain wall-clock STAMPS (log/artifact
  timestamps that are never differenced) stay legal.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from sparkdl_tpu.analysis.core import Finding, LintContext, Module

_BROAD = {"Exception", "BaseException"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name) and t.id in _BROAD:
        return True
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in t.elts)
    return False


def _handler_recovers(handler: ast.ExceptHandler) -> bool:
    for n in ast.walk(handler):
        if isinstance(n, ast.Raise):
            return True
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr in _LOG_METHODS):
            return True
    return False


def rule_sdl003(module: Module, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ExceptHandler) or not _is_broad(node):
            continue
        if _handler_recovers(node):
            continue
        what = ("bare except" if node.type is None else
                f"except {ast.unparse(node.type)}")
        findings.append(Finding(
            "SDL003", module.path, node.lineno,
            f"broad handler ({what}) neither re-raises nor logs; "
            f"narrow the exception type, log via utils.logging, or "
            f"annotate why swallowing is deliberate"))
    return findings


def _time_aliases(tree: ast.AST) -> tuple:
    """``(module_aliases, direct_names)`` for the wall clock: names the
    ``time`` MODULE is bound to (``import time [as time_lib]`` — the
    alias engine.py actually uses) and names the ``time.time`` FUNCTION
    is bound to (``from time import time [as now]``)."""
    modules: Set[str] = set()
    direct: Set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            for alias in n.names:
                if alias.name == "time":
                    modules.add(alias.asname or "time")
        elif isinstance(n, ast.ImportFrom) and n.module == "time":
            for alias in n.names:
                if alias.name == "time":
                    direct.add(alias.asname or "time")
    return modules, direct


def _make_is_wall_clock(tree: ast.AST):
    modules, direct = _time_aliases(tree)

    def is_wall_clock(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        if isinstance(f, ast.Attribute):
            return (f.attr == "time" and isinstance(f.value, ast.Name)
                    and f.value.id in modules)
        return isinstance(f, ast.Name) and f.id in direct

    return is_wall_clock


def _scope_of(module: Module, node: ast.AST) -> ast.AST:
    cur = module.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = module.parent(cur)
    return module.tree


def rule_sdl006(module: Module, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    is_wall_clock = _make_is_wall_clock(module.tree)
    scopes: List[ast.AST] = [module.tree]
    scopes.extend(n for n in ast.walk(module.tree)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)))
    for scope in scopes:
        # names bound (in this scope, not nested ones) from the wall clock
        wall: Set[str] = set()
        wall_line = {}
        for n in ast.walk(scope):
            if n is not scope and _scope_of(module, n) is not scope:
                continue
            if isinstance(n, ast.Assign) and is_wall_clock(n.value):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        wall.add(t.id)
                        wall_line[t.id] = n.lineno
        for n in ast.walk(scope):
            if not isinstance(n, ast.BinOp) or not isinstance(n.op, ast.Sub):
                continue
            if _scope_of(module, n) is not scope:
                continue
            involved: Optional[int] = None
            for side in (n.left, n.right):
                if is_wall_clock(side):
                    involved = side.lineno
                elif isinstance(side, ast.Name) and side.id in wall:
                    involved = wall_line.get(side.id, n.lineno)
            if involved is not None:
                findings.append(Finding(
                    "SDL006", module.path, n.lineno,
                    "latency computed from time.time(); wall clock is "
                    "not monotonic (NTP slew) — use time.perf_counter() "
                    "or time.monotonic() for durations (wall-clock "
                    "stamps that are never differenced are fine)"))
    return findings
