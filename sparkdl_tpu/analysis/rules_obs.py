"""SDL005 — observability naming schema + span open/close pairing.

* Names passed to ``Metrics`` recorders (``incr``/``gauge``/
  ``record_time``/``observe``) and to tracer span constructors
  (``span``/``start_span``) must match the project's dotted-lowercase
  schema ``segment(.segment)*`` with ``[a-z0-9_]`` segments — the
  exporters (Prometheus text, Chrome trace, trace_summary) key on these
  strings, so one camelCase stray forks a time series forever.

* A span that is OPENED must be closable: ``tracer.span(...)`` /
  ``tracer.start_span(...)`` results must be used as a context manager,
  stored somewhere that outlives the call (attribute/subscript/arg/
  return — the cross-thread handoff pattern), or explicitly
  ``.finish()``-ed in the same function.  A span discarded or left in a
  dead local never closes, never records, and silently truncates every
  trace tree under it.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from sparkdl_tpu.analysis.core import Finding, LintContext, Module

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)*$")
_METRIC_METHODS = {"incr", "gauge", "record_time", "observe"}
_SPAN_METHODS = {"span", "start_span"}


def _method_call(node: ast.AST, methods) -> Optional[str]:
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr in methods):
        return node.func.attr
    return None


def rule_sdl005_names(module: Module, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(module.tree):
        method = _method_call(node, _METRIC_METHODS | _SPAN_METHODS)
        if method is None or not node.args:
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue  # dynamic names are the caller's problem
        if not _NAME_RE.match(first.value):
            findings.append(Finding(
                "SDL005", module.path, node.lineno,
                f"{method}() name {first.value!r} breaks the "
                f"dotted-lowercase schema ([a-z0-9_] segments joined by "
                f"'.'); exporters key on these strings — one stray "
                f"spelling forks the series forever"))
    return findings


def _escapes(module: Module, call: ast.Call, scope: ast.AST) -> bool:
    """The span value leaves the expression: ``with`` item, attribute/
    subscript store, call argument, return/yield, or container literal."""
    node: ast.AST = call
    parent = module.parent(node)
    while parent is not None:
        if isinstance(parent, ast.withitem):
            return True
        if isinstance(parent, ast.Call) and node is not parent.func:
            return True  # passed as an argument
        if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom)):
            return True
        if isinstance(parent, (ast.List, ast.Tuple, ast.Dict, ast.Set)):
            return True
        if isinstance(parent, ast.Assign):
            return any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in parent.targets)
        if isinstance(parent, (ast.IfExp, ast.BoolOp, ast.NamedExpr)):
            node = parent
            parent = module.parent(parent)
            continue
        if parent is scope or isinstance(
                parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.stmt)):
            return False
        node = parent
        parent = module.parent(parent)
    return False


def _assigned_name(module: Module, call: ast.Call) -> Optional[str]:
    node: ast.AST = call
    parent = module.parent(node)
    while isinstance(parent, (ast.IfExp, ast.BoolOp, ast.NamedExpr)):
        node = parent
        parent = module.parent(parent)
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
            and isinstance(parent.targets[0], ast.Name):
        return parent.targets[0].id
    return None


def _finished_in(scope: ast.AST, name: str) -> bool:
    for n in ast.walk(scope):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == "finish"
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == name):
            return True
        # handing the local onward (arg/return/attribute store) also
        # moves close responsibility with it
        if (isinstance(n, ast.Call)
                and any(isinstance(a, ast.Name) and a.id == name
                        for a in n.args)):
            return True
        if (isinstance(n, ast.Return) and isinstance(n.value, ast.Name)
                and n.value.id == name):
            return True
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Name) \
                and n.value.id == name and any(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    for t in n.targets):
            return True
    return False


def _scope_of(module: Module, node: ast.AST) -> ast.AST:
    cur = module.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = module.parent(cur)
    return module.tree


def rule_sdl005_pairing(module: Module, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(module.tree):
        method = _method_call(node, _SPAN_METHODS)
        if method is None:
            continue
        scope = _scope_of(module, node)
        if _escapes(module, node, scope):
            continue
        name = _assigned_name(module, node)
        if name is not None and _finished_in(scope, name):
            continue
        findings.append(Finding(
            "SDL005", module.path, node.lineno,
            f"{method}() result is never closed: use it as a context "
            f"manager, call .finish() on it in this function, or hand "
            f"it somewhere that owns the close — an unclosed span "
            f"records nothing and truncates its whole subtree"))
    return findings
