"""The stack's program inventory: every jit program the scoring and
training layers construct, as :class:`~sparkdl_tpu.analysis.program.
audit.ProgramSpec`s built from the SAME constructors the runtime uses
(``parallel.engine.build_dispatch_jit``, ``serving.server.bucket_plan``,
``transformers.named_image.zoo_model_fn``, ``parallel.train.
make_train_step``, the ``ops.sepconv`` kernel jits) — so the audited
program set cannot drift from the served one.

Abstract by construction: model variables come from
``ModelSpec.abstract_variables()`` (``jax.eval_shape`` over ``init`` —
shape/dtype only), batches are ``ShapeDtypeStruct``s, and nothing is
ever placed on a device.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from sparkdl_tpu.analysis.program.audit import ProgramSpec

#: The donation exemption every zoo dispatch program records (GC001):
#: proved by the audit itself — jax reports the donated uint8 batch
#: unusable because no f32/bf16 output can alias it.
ZOO_DONATE_REASON = (
    "uint8 image batch cannot alias the float feature output (smaller, "
    "different dtype); XLA drops the donation, so the engine leaves "
    "donate_batch off for zoo programs")

SEPCONV_DONATE_REASON = (
    "chained padded-flat activations; callers reuse the input "
    "(residual adds), so donation would corrupt the residual source")

#: Canonical kernel audit shapes: Xception middle flow (sepconv), entry
#: flow block under row tiling, MobileNetV2 inverted-residual tail.
_KERNEL_SHAPES = {
    "sepconv": dict(b=8, h=19, w=19, c=728, f=728),
    "sepconv_tiled": dict(b=8, h=74, w=74, c=256, f=256, th=8),
    "mbconv": dict(b=8, h=28, w=28, c=192, f=32),
}


def _cast_floating_avals(avals, dtype):
    """ShapeDtypeStruct twin of the engine's ``_cast_floating``: the
    audited variables must carry the dtype the engine would actually
    place on device under a compute-dtype knob."""
    import jax
    import jax.numpy as jnp

    def cast(leaf):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            return jax.ShapeDtypeStruct(leaf.shape, dtype)
        return leaf

    return jax.tree_util.tree_map(cast, avals)


def _mesh_axes(mesh) -> Dict[str, int]:
    return {str(name): int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)}


def zoo_dispatch_specs(max_batch_size: int = 32,
                       models: Optional[Sequence[str]] = None,
                       compute_dtype: str = "bfloat16",
                       mesh=None) -> List[ProgramSpec]:
    """One spec per (zoo model x serving bucket x cut): the engine
    program exactly as ``_zoo_engine`` + ``InferenceEngine`` build it
    (fused preprocess, compute-dtype cast, replicated params, data-axis
    batch sharding) — the featurizer cut at every compiled shape in the
    serving bucket plan, the predictor cut (``Server(featurize=False)``,
    the serving default) at the largest bucket, and the grouped
    ``batches_per_dispatch`` ``lax.map`` program for one representative
    model."""
    import jax.numpy as jnp

    from sparkdl_tpu.models import SUPPORTED_MODELS, get_model_spec
    from sparkdl_tpu.parallel.engine import (effective_device_batch,
                                             resolve_engine_mesh)
    from sparkdl_tpu.serving.server import bucket_plan

    mesh = resolve_engine_mesh(mesh)
    buckets = bucket_plan(max_batch_size, mesh=mesh)
    names = list(models) if models else list(SUPPORTED_MODELS)
    axes = _mesh_axes(mesh)
    cdt = jnp.bfloat16 if compute_dtype == "bfloat16" else None
    specs: List[ProgramSpec] = []
    # one abstract-variables eval_shape per model, shared by its buckets
    # and cuts
    memo: Dict[str, Any] = {}

    def avals(name: str):
        mspec = get_model_spec(name)
        if name not in memo:
            av = mspec.abstract_variables()
            if cdt is not None:
                av = _cast_floating_avals(av, cdt)
            memo[name] = av
        return memo[name]

    def build(name: str, bucket: int, featurize: bool, group_k: int = 0):
        def _build():
            import jax
            import numpy as np

            from sparkdl_tpu.parallel.engine import (
                build_dispatch_jit, build_grouped_dispatch_jit)
            from sparkdl_tpu.transformers.named_image import zoo_model_fn

            mspec = get_model_spec(name)
            fn = zoo_model_fn(name, featurize=featurize, compute_dtype=cdt)
            h, w = mspec.input_size
            if group_k:
                jitted = build_grouped_dispatch_jit(
                    fn, mesh, donate_batch=False,
                    batches_per_dispatch=group_k)
                batch = jax.ShapeDtypeStruct((group_k, bucket, h, w, 3),
                                             np.uint8)
            else:
                jitted = build_dispatch_jit(fn, mesh, donate_batch=False)
                batch = jax.ShapeDtypeStruct((bucket, h, w, 3), np.uint8)
            return jitted, (avals(name), batch)

        return _build

    base = dict(kind="dispatch", compute_dtype=compute_dtype, donate=(),
                donate_reason=ZOO_DONATE_REASON, mesh_axes=axes)
    for name in names:
        canonical = get_model_spec(name).name  # registry casing
        for b in buckets:
            specs.append(ProgramSpec(
                name=f"zoo/{canonical}/featurize/{compute_dtype}/b{b}",
                build=build(canonical, b, featurize=True),
                batch_rows=b,
                shardings=("replicated", "batch"),
                group=f"zoo/{canonical}/featurize/{compute_dtype}",
                model=canonical, bucket=b, **base))
        # the predictor cut (serving default) at ONE fixed canonical
        # bucket (b32, mesh-rounded — stable across --max-batch subset
        # audits); no model/bucket tags: GC004's pad accounting is
        # cut-independent and already gated by the featurize set above
        pb = effective_device_batch(32, mesh)
        specs.append(ProgramSpec(
            name=f"zoo/{canonical}/predict/{compute_dtype}/b{pb}",
            build=build(canonical, pb, featurize=False),
            batch_rows=pb,
            shardings=("replicated", "batch"),
            group=f"zoo/{canonical}/predict/{compute_dtype}", **base))
    # the grouped lax.map dispatch program (SPARKDL_BATCHES_PER_DISPATCH
    # > 1): the wrapper is model-independent, so ONE representative
    # (MobileNetV2, the cheapest trace) at a FIXED canonical shape
    # (b32 x k4, like the train specs) — stable across subset audits,
    # so narrowed runs still line up with the committed baseline
    rep = "MobileNetV2"
    if any(get_model_spec(n).name == rep for n in names):
        specs.append(ProgramSpec(
            name=f"zoo/{rep}/featurize/{compute_dtype}/b32xk4",
            build=build(rep, 32, featurize=True, group_k=4),
            batch_rows=32 * 4,
            shardings=("replicated", "stacked_batch"),
            group=f"zoo/{rep}/featurize/{compute_dtype}/grouped", **base))
    return specs


def fleet_dispatch_specs(models: Optional[Sequence[str]] = None,
                         max_batch_size: int = 32,
                         compute_dtype: str = "bfloat16",
                         mesh=None) -> List[ProgramSpec]:
    """Every program a ``serving.fleet.Fleet`` can construct for its
    zoo-backed entries — the fleet enumeration hook graftcheck audits.

    BY CONSTRUCTION this is the existing zoo × serving-bucket-plan
    program set, nothing more: a fleet entry resolves its fn exactly
    once through ``named_image.zoo_serving_bundle`` (→ ``zoo_model_fn``,
    the same constructor :func:`zoo_dispatch_specs` lowers), every
    version of the entry reuses that one fn object with new WEIGHTS
    only, and each version's ``Server`` compiles through the same
    ``bucket_plan`` × ``build_dispatch_jit`` path.  New versions and
    hot-swaps therefore add NO programs to the inventory —
    ``PROGRAMS.lock.json`` regenerates only if the underlying zoo ×
    bucket set itself changes (tests pin the set equality and match the
    audited executable keys/fingerprints against the committed
    lockfile).

    The head fan-out tier (``Fleet.add_fanout_model``) keeps the same
    property by a different split: its backbone is one ordinary
    dispatch program and ALL tenant heads share one vmapped gather
    program, audited separately by :func:`headfanout_dispatch_specs` —
    head add/swap/evict changes weights and bank capacity, never the
    program set."""
    return zoo_dispatch_specs(max_batch_size=max_batch_size,
                              models=models, compute_dtype=compute_dtype,
                              mesh=mesh)


#: The head fan-out proof model's shape (ISSUE 17): a 12 → 16 feature
#: backbone (output WIDER than the input row, so the batch donation can
#: never alias — the recorded GC001 exemption below) in front of 64
#: stacked per-tenant 16 → 4 heads — the smallest program pair that
#: pins the tier's two claims chip-free: the backbone-cut program's
#: StableHLO fingerprint is what ``serving.cache.
#: lockfile_model_fingerprint("headfanout")`` resolves (the feature-cut
#: cache namespace and the head-swap proof both key on it), and the ONE
#: vmapped gather program serves every tenant's head.
HEADFANOUT_DIM_IN = 12
HEADFANOUT_DIM_FEAT = 16
HEADFANOUT_CLASSES = 4
HEADFANOUT_TENANTS = 64

HEADFANOUT_DONATE_REASON = (
    "the (b, 12) f32 row batch cannot alias the (b, 16) feature output "
    "(the feature cut widens it), and the fan-out program's gathered "
    "head inputs are read by every padded row — XLA would drop either "
    "donation, so the serving tier leaves both off")


def headfanout_dispatch_specs(batch_rows: int = 32,
                              tenants: int = HEADFANOUT_TENANTS,
                              mesh=None) -> List[ProgramSpec]:
    """The shared-backbone head fan-out programs (ISSUE 17), built
    through the EXACT runtime constructors: the backbone feature cut
    via ``build_dispatch_jit`` over ``parallel.engine.
    head_fanout_backbone_fn`` (the module-level fn the tests, the bench
    and ``HeadFanoutServer`` smoke paths all serve), and the stacked
    head bank's single vmapped gather program via
    ``build_head_fanout_jit`` over ``parallel.engine.dense_head_row``
    at the canonical 64-tenant capacity.  The backbone record carries
    ``model="headfanout"`` so ``lockfile_model_fingerprint`` resolves
    the tier's committed backbone identity — the fingerprint the
    feature-cut cache namespace and ``head_swap_report``'s
    ``fingerprint_pinned`` witness both pin against; the head program
    deliberately does NOT (head-program evolution must never rotate
    the backbone's feature namespace).  Neither spec records a
    ``bucket``: the fan-out tier reuses the serving bucket plan, whose
    pad accounting GC004 already gates through the zoo set."""
    from sparkdl_tpu.parallel.engine import (effective_device_batch,
                                             resolve_engine_mesh)

    mesh = resolve_engine_mesh(mesh)
    axes = _mesh_axes(mesh)
    b = effective_device_batch(batch_rows, mesh)

    def build_backbone():
        import jax
        import numpy as np

        from sparkdl_tpu.parallel.engine import (build_dispatch_jit,
                                                 head_fanout_backbone_fn)

        jitted = build_dispatch_jit(head_fanout_backbone_fn, mesh,
                                    donate_batch=False)
        variables = {"backbone": jax.ShapeDtypeStruct(
            (HEADFANOUT_DIM_IN, HEADFANOUT_DIM_FEAT), np.float32)}
        batch = jax.ShapeDtypeStruct((b, HEADFANOUT_DIM_IN), np.float32)
        return jitted, (variables, batch)

    def build_heads():
        import jax
        import numpy as np

        from sparkdl_tpu.parallel.engine import (build_head_fanout_jit,
                                                 dense_head_row)

        jitted = build_head_fanout_jit(dense_head_row, mesh)
        stacked = {
            "kernel": jax.ShapeDtypeStruct(
                (tenants, HEADFANOUT_DIM_FEAT, HEADFANOUT_CLASSES),
                np.float32),
            "bias": jax.ShapeDtypeStruct((tenants, HEADFANOUT_CLASSES),
                                         np.float32),
        }
        idx = jax.ShapeDtypeStruct((b,), np.int32)
        feats = jax.ShapeDtypeStruct((b, HEADFANOUT_DIM_FEAT), np.float32)
        return jitted, (stacked, idx, feats)

    base = dict(kind="dispatch", donate=(),
                donate_reason=HEADFANOUT_DONATE_REASON, mesh_axes=axes)
    return [
        ProgramSpec(name=f"headfanout/backbone/f32/b{b}",
                    build=build_backbone, batch_rows=b,
                    shardings=("replicated", "batch"),
                    group="headfanout/backbone/f32",
                    model="headfanout", **base),
        ProgramSpec(name=f"headfanout/heads/k{tenants}/f32/b{b}",
                    build=build_heads, batch_rows=b,
                    shardings=("replicated", "batch", "batch"),
                    group=f"headfanout/heads/k{tenants}/f32", **base),
    ]


def generic_dispatch_specs(feature_dim: int = 16,
                           mesh=None) -> List[ProgramSpec]:
    """The donated GENERIC serving program (ISSUE 13 satellite):
    ``Server`` auto-donates the per-dispatch batch buffer for non-zoo
    float-input models whenever its eval-shape probe proves XLA will
    consume the donation (``Server._probe_donate``), and this spec
    audits that claim — a square float linear head built through the
    SAME ``build_dispatch_jit(donate_batch=True)`` constructor the
    serving path uses, declaring ``donate=(1,)`` with NO recorded
    exemption, so GC001 fails loudly if the donation ever stops
    aliasing.  The zoo programs stay donate-off (their uint8 batch can
    never alias — ``ZOO_DONATE_REASON``); this is the program shape
    where donation is actually consumable, pinned in the lockfile."""
    from sparkdl_tpu.parallel.engine import (effective_device_batch,
                                             resolve_engine_mesh)

    mesh = resolve_engine_mesh(mesh)
    axes = _mesh_axes(mesh)
    b = effective_device_batch(32, mesh)

    def _build():
        import jax
        import numpy as np

        from sparkdl_tpu.parallel.engine import build_dispatch_jit

        def fn(v, x):
            import jax.numpy as jnp

            return jnp.tanh(x @ v["w"])

        jitted = build_dispatch_jit(fn, mesh, donate_batch=True)
        variables = {"w": jax.ShapeDtypeStruct(
            (feature_dim, feature_dim), np.float32)}
        batch = jax.ShapeDtypeStruct((b, feature_dim), np.float32)
        return jitted, (variables, batch)

    return [ProgramSpec(
        name=f"serving/generic/tanh_linear/f32/b{b}",
        kind="dispatch", build=_build, donate=(1,),
        batch_rows=b, mesh_axes=axes,
        shardings=("replicated", "batch"),
        group="serving/generic/tanh_linear/f32")]


#: the wide-dense proof model's shape: a (in, out) f32 kernel of 64 MB
#: — over the 32 MB GC005 replicated budget — with a SMALL contraction
#: dim and a WIDE output dim, so the tensor-parallel split (output
#: columns across the model axis) leaves every output element's
#: accumulation order untouched and sharded serving is BIT-IDENTICAL
#: to the single-device replicated oracle (tests pin this at runtime)
WIDE_DENSE_IN = 128
WIDE_DENSE_OUT = 131072


def sharded_dispatch_specs(feature_dim_in: int = WIDE_DENSE_IN,
                           feature_dim_out: int = WIDE_DENSE_OUT,
                           batch_rows: int = 32) -> List[ProgramSpec]:
    """The tensor-parallel dispatch programs (ISSUE 14): a synthetic
    WIDE-DENSE head whose single kernel (128 x 131072 f32 = 64 MB at
    the defaults) busts graftcheck's 32 MB replicated-param budget on
    any model-axis mesh — the smallest model that PROVES the HBM claim
    chip-free.  Each spec builds through the same
    ``build_dispatch_jit(param_shardings=...)`` constructor the engine
    uses, with the layout from ``mesh.resolve_param_shardings`` under
    the default rules (kernel split on its output dim, bias/scalars
    replicated), on the model-axis meshes the 8-virtual-device audit
    topology supports: ``dp1tp8`` (pure tensor parallel) and
    ``dp2tp4`` (mixed).  GC005 then verifies the claim: no replicated
    leaf above budget (the kernel now costs bytes/model_axis per
    chip), every split dim divides, mhlo.sharding present — where the
    same program under ``shardings=("replicated", "batch")`` is the
    budget-buster negative fixture the tests pin.  The batch is
    donated (f32 in, f32 out — but note the output is WIDER than the
    batch, so XLA cannot alias it; the recorded reason below is the
    GC001 exemption, symmetric to the zoo's uint8 one)."""
    import jax

    from sparkdl_tpu.parallel import mesh as mesh_lib
    from sparkdl_tpu.parallel.engine import effective_device_batch

    n = len(jax.devices())
    layouts = [n]  # pure TP: (1, n)
    if n >= 4 and n % 2 == 0:
        layouts.append(n // 2)  # mixed: (2, n/2)
    specs: List[ProgramSpec] = []
    for model_parallel in layouts:
        if model_parallel < 2 or feature_dim_out % model_parallel:
            continue
        mesh = mesh_lib.get_mesh(model_parallel=model_parallel)
        axes = _mesh_axes(mesh)
        b = effective_device_batch(batch_rows, mesh)
        # the default-rule layout, spelled statically so the declaration
        # cannot drift from what build() resolves
        kernel_spec = mesh_lib.spec_to_json(
            jax.sharding.PartitionSpec(None, mesh_lib.MODEL_AXIS))
        partition = (("dense/bias", []), ("dense/kernel", kernel_spec))

        def build(mesh=mesh, b=b):
            def _build():
                import numpy as np

                from sparkdl_tpu.parallel.engine import build_dispatch_jit

                variables = {"dense": {
                    "kernel": jax.ShapeDtypeStruct(
                        (feature_dim_in, feature_dim_out), np.float32),
                    "bias": jax.ShapeDtypeStruct((feature_dim_out,),
                                                 np.float32),
                }}
                shardings, _ = mesh_lib.resolve_param_shardings(
                    variables, mesh)
                jitted = build_dispatch_jit(wide_dense_fn, mesh,
                                            donate_batch=False,
                                            param_shardings=shardings)
                batch = jax.ShapeDtypeStruct((b, feature_dim_in),
                                             np.float32)
                return jitted, (variables, batch)

            return _build

        name = (f"serving/wide_dense/f32/b{b}/"
                f"dp{axes['data']}tp{axes['model']}")
        specs.append(ProgramSpec(
            name=name, kind="dispatch", build=build(), donate=(),
            donate_reason=WIDE_DENSE_DONATE_REASON,
            batch_rows=b, mesh_axes=axes,
            shardings=("params", "batch"),
            param_partition=partition,
            group=name))
    return specs


def wide_dense_fn(v, x):
    """The wide-dense proof model's fn — module-level so the runtime
    bit-identity test serves the EXACT fn the audited programs lower."""
    import jax.numpy as jnp

    return jnp.tanh(x @ v["dense"]["kernel"] + v["dense"]["bias"])


WIDE_DENSE_DONATE_REASON = (
    "the (b, 128) f32 batch cannot alias the (b, 131072) output — the "
    "whole point of the wide head is an output wider than its input, "
    "so XLA would drop the donation")


def train_step_specs(batch_rows: int = 32, feature_dim: int = 2048,
                     num_classes: int = 10, mesh=None) -> List[ProgramSpec]:
    """The data-parallel train-step programs the estimator layer
    compiles (``parallel.train.make_train_step``): the transfer-learning
    linear head (``estimators.classification``'s fit program) as the
    plain per-step jit and the ``steps_per_execution`` multi-step scan.
    Donation is the whole point here (params/opt_state are donated and
    every leaf must alias), so these are GC001's primary subjects."""
    from sparkdl_tpu.parallel.engine import resolve_engine_mesh

    mesh = resolve_engine_mesh(mesh)
    axes = _mesh_axes(mesh)

    def make(kind_multi: bool):
        def _build():
            import jax
            import numpy as np
            import optax

            from sparkdl_tpu.parallel.train import make_train_step

            def predict_fn(p, xb):
                return xb @ p["w"] + p["b"]  # the linear-head logits

            opt = optax.adam(1e-3)
            step = make_train_step(predict_fn,
                                   "sparse_categorical_crossentropy",
                                   opt, mesh=mesh, cache=False)
            params_av = {
                "w": jax.ShapeDtypeStruct((feature_dim, num_classes),
                                          np.float32),
                "b": jax.ShapeDtypeStruct((num_classes,), np.float32),
            }
            opt_av = jax.eval_shape(opt.init, params_av)
            x = jax.ShapeDtypeStruct((batch_rows, feature_dim), np.float32)
            y = jax.ShapeDtypeStruct((batch_rows,), np.int32)
            if not kind_multi:
                return step.step_fn, (params_av, opt_av, x, y)
            k = 4
            xs = jax.ShapeDtypeStruct((k, batch_rows, feature_dim),
                                      np.float32)
            ys = jax.ShapeDtypeStruct((k, batch_rows), np.int32)
            return step.multi(k), (params_av, opt_av, xs, ys)

        return _build

    return [
        ProgramSpec(name=f"train/linear_head/step/b{batch_rows}",
                    build=make(False), kind="train", donate=(0, 1),
                    batch_rows=batch_rows, mesh_axes=axes,
                    shardings=("replicated", "replicated",
                               "batch", "batch"),
                    group="train/linear_head/step"),
        ProgramSpec(name=f"train/linear_head/multi4/b{batch_rows}",
                    build=make(True), kind="train", donate=(0, 1),
                    batch_rows=batch_rows, mesh_axes=axes,
                    shardings=("replicated", "replicated",
                               "stacked_batch", "stacked_batch"),
                    group="train/linear_head/multi4"),
    ]


def sepconv_kernel_specs() -> List[ProgramSpec]:
    """The fused Pallas kernel jits (``ops/sepconv.py``) at their
    canonical Xception/MobileNetV2 shapes, lowered through the pallas
    INTERPRETER (``interpret=True``) so the fingerprint is chip-free.
    No mesh/sharding (kernels shard through the caller's program) and a
    recorded donation exemption: the flat activations chain."""

    def build_sepconv():
        import jax
        import jax.numpy as jnp

        from sparkdl_tpu.ops.sepconv import _fused_sepconv_tpu, flat_width

        s = _KERNEL_SHAPES["sepconv"]
        lo = (s["h"] + 2) * flat_width(s["w"])
        args = (jax.ShapeDtypeStruct((s["b"], lo, s["c"]), jnp.bfloat16),
                jax.ShapeDtypeStruct((3, 3, s["c"]), jnp.bfloat16),
                jax.ShapeDtypeStruct((s["c"], s["f"]), jnp.bfloat16),
                jax.ShapeDtypeStruct((s["f"],), jnp.float32),
                jax.ShapeDtypeStruct((s["f"],), jnp.float32))
        return _Partial(_fused_sepconv_tpu, h=s["h"], w=s["w"],
                        pre_relu=True, post_relu=False,
                        interpret=True), args

    def build_tiled():
        import jax
        import jax.numpy as jnp

        from sparkdl_tpu.ops.sepconv import (_fused_sepconv_tpu_tiled,
                                             flat_rows, flat_width)

        s = _KERNEL_SHAPES["sepconv_tiled"]
        lo = flat_rows(s["h"], s["th"]) * flat_width(s["w"])
        args = (jax.ShapeDtypeStruct((s["b"], lo, s["c"]), jnp.bfloat16),
                jax.ShapeDtypeStruct((3, 3, s["c"]), jnp.bfloat16),
                jax.ShapeDtypeStruct((s["c"], s["f"]), jnp.bfloat16),
                jax.ShapeDtypeStruct((s["f"],), jnp.float32),
                jax.ShapeDtypeStruct((s["f"],), jnp.float32))
        return _Partial(_fused_sepconv_tpu_tiled, h=s["h"], w=s["w"],
                        th=s["th"], pre_relu=True, post_relu=False,
                        interpret=True), args

    def build_mbconv():
        import jax
        import jax.numpy as jnp

        from sparkdl_tpu.ops.sepconv import _fused_mbconv_tpu, flat_width

        s = _KERNEL_SHAPES["mbconv"]
        lo = (s["h"] + 2) * flat_width(s["w"])
        args = (jax.ShapeDtypeStruct((s["b"], lo, s["c"]), jnp.bfloat16),
                jax.ShapeDtypeStruct((3, 3, s["c"]), jnp.bfloat16),
                jax.ShapeDtypeStruct((s["c"], s["f"]), jnp.bfloat16),
                jax.ShapeDtypeStruct((s["c"],), jnp.float32),
                jax.ShapeDtypeStruct((s["f"],), jnp.float32))
        return _Partial(_fused_mbconv_tpu, h=s["h"], w=s["w"],
                        interpret=True), args

    base = dict(kind="kernel", donate=(),
                donate_reason=SEPCONV_DONATE_REASON,
                compute_dtype="bfloat16")
    s1 = _KERNEL_SHAPES["sepconv"]
    s2 = _KERNEL_SHAPES["sepconv_tiled"]
    s3 = _KERNEL_SHAPES["mbconv"]
    return [
        ProgramSpec(name=f"kernel/sepconv/{s1['h']}x{s1['w']}x{s1['c']}",
                    build=build_sepconv, batch_rows=s1["b"],
                    group="kernel/sepconv", **base),
        ProgramSpec(
            name=f"kernel/sepconv_tiled/{s2['h']}x{s2['w']}x{s2['c']}",
            build=build_tiled, batch_rows=s2["b"],
            group="kernel/sepconv_tiled", **base),
        ProgramSpec(name=f"kernel/mbconv/{s3['h']}x{s3['w']}x{s3['c']}",
                    build=build_mbconv, batch_rows=s3["b"],
                    group="kernel/mbconv", **base),
    ]


class _Partial:
    """A static-kwarg binder exposing the jit object's ``lower``: the
    sepconv jits take their shape parameters as ``static_argnames``, so
    the audit lowers them with those bound."""

    def __init__(self, jitted, **static_kwargs):
        self._jitted = jitted
        self._kw = static_kwargs

    def lower(self, *args):
        return self._jitted.lower(*args, **self._kw)


def stack_programs(max_batch_size: int = 32,
                   models: Optional[Sequence[str]] = None,
                   compute_dtype: str = "bfloat16",
                   include_train: bool = True,
                   include_kernels: bool = True,
                   mesh=None) -> List[ProgramSpec]:
    """The full auditable inventory: zoo x bucket plan (+ the train-step
    and sepconv-kernel programs unless excluded).  ``models`` narrows
    the zoo sweep (the tier-1 acceptance gate audits a small subset;
    ``tools/graftcheck.py`` sweeps everything)."""
    specs = zoo_dispatch_specs(max_batch_size=max_batch_size,
                               models=models, compute_dtype=compute_dtype,
                               mesh=mesh)
    # the donated generic serving program rides every audit (subset
    # ones included): it is model-independent and cheap to lower, and
    # GC001's consumed-donation check is the whole point of it
    specs.extend(generic_dispatch_specs(mesh=mesh))
    # the tensor-parallel wide-dense programs (ISSUE 14) ride every
    # audit the same way: cheap to lower, model-independent, and GC005's
    # sharded-HBM proof (no replicated leaf above budget once the
    # kernel splits) is the whole point of them
    specs.extend(sharded_dispatch_specs())
    # the head fan-out tier's program pair (ISSUE 17): the backbone cut
    # (whose fingerprint keys the feature-cut cache namespace) and the
    # one vmapped gather program every tenant's head shares
    specs.extend(headfanout_dispatch_specs(mesh=mesh))
    if include_train:
        # the train batch is the estimator's default fit batch, NOT a
        # serving bucket — keep it fixed so subset audits (--models /
        # --max-batch) still line up with the committed baseline
        specs.extend(train_step_specs(mesh=mesh))
    if include_kernels:
        specs.extend(sepconv_kernel_specs())
    return specs
