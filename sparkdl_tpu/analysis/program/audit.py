"""graftcheck core: abstract lowering + the GC001–GC005 program rules.

Everything here runs CHIP-FREE: a :class:`ProgramSpec` builds its jit
object and abstract argument avals (``jax.ShapeDtypeStruct`` leaves —
no weights materialized, no device memory touched), ``.lower()``
produces StableHLO on the CPU backend, and the rules read three cheap
artifacts of the lowering:

* the StableHLO text (op dtype mix, ``tf.aliasing_output`` donation
  attrs, ``mhlo.sharding`` annotations),
* ``lowered.cost_analysis()`` (FLOPs / bytes accessed on the
  UNOPTIMIZED module — no XLA compile, milliseconds even for the zoo),
* the flat input avals (shape/dtype/weak-type — the executable cache
  key jax would use at runtime).

The audited-configuration contract: rules fire on what the spec
DECLARES (kind, compute dtype, donation expectation, shardings), so the
same engine code audits clean in its f32 parity configuration and is
held to the bf16 contract when the inventory declares it.
"""

from __future__ import annotations

import hashlib
import re
import warnings
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from sparkdl_tpu.analysis.core import Finding

GC_RULE_HELP = {
    "GC000": "committed program fingerprint matches the audited program",
    "GC001": "dispatch/train jits donate; declared donations are consumed",
    "GC002": "no f32 dot/conv under a declared bf16 compute dtype",
    "GC003": "no weak-type/duplicate/churned executable cache keys",
    "GC004": "pad-to-bucket FLOP waste stays inside budget",
    "GC005": "shardings consistent with the mesh; no large param "
             "replicated past a usable model axis",
}

#: GC004 budgets: worst-case pad fraction between adjacent buckets
#: (request of b_{i-1}+1 rows served by bucket b_i), and the inherent
#: floor of the smallest bucket (a 1-row request padded to b_0).
PAD_INTERIOR_BUDGET = 0.55
PAD_FLOOR_BUDGET = 0.95

#: GC005: a single replicated param leaf larger than this, on a mesh
#: whose model axis could shard it, is flagged.
REPLICATED_PARAM_BUDGET_BYTES = 32 * 1024 * 1024

_F32_RESULT = re.compile(r"->\s*tensor<[^>]*xf32>")
#: the op's OPERAND dtype (first input tensor of the call signature):
#: a bf16 x bf16 -> f32 dot is deliberate f32 ACCUMULATION
#: (preferred_element_type, the sepconv kernels' contract), while an
#: f32-operand dot/conv under bf16 compute is a real upcast leak
_OPERAND_DTYPE = re.compile(
    r":\s*\(tensor<[^>]*?x?(bf16|f16|f32|f64)>")


@dataclass
class ProgramSpec:
    """One auditable program: a zero-argument ``build`` returning
    ``(jitted, args)`` where ``args`` are abstract avals, plus the
    declared contract the rules check the lowering against."""

    name: str                      # e.g. "zoo/InceptionV3/featurize/b32"
    kind: str                      # "dispatch" | "train" | "kernel"
    build: Callable[[], Tuple[Any, tuple]]
    # declared contract ----------------------------------------------------
    compute_dtype: Optional[str] = None   # "bfloat16" activates GC002
    donate: Tuple[int, ...] = ()          # jit-level donated arg indices
    donate_reason: Optional[str] = None   # recorded exemption for GC001
    batch_rows: Optional[int] = None      # padded rows per dispatch
    # per-arg sharding declaration: "replicated" | "batch" (data axis on
    # dim 0) | "stacked_batch" (the grouped/multi-step layout — data
    # axis on dim 1) | "params" (per-leaf partition specs — ISSUE 14
    # tensor-parallel weights; see ``param_partition``) | None
    shardings: Optional[Tuple[Optional[str], ...]] = None
    mesh_axes: Optional[Dict[str, int]] = None   # {"data": 8, "model": 1}
    # the "params" arg's declared layout: ((path, spec_json), ...) where
    # spec_json is the per-dim axis list (mesh.spec_to_json) — leaves
    # with an empty spec count as replicated in the GC005 byte budget,
    # sharded leaves contribute bytes/shards per chip and must divide
    param_partition: Optional[Tuple] = None
    # retrace-audit group: one compiled fn identity (GC003 groups shapes
    # under it the way jax's executable cache would)
    group: Optional[str] = None
    model: Optional[str] = None    # zoo model name (GC004 bucket grouping)
    bucket: Optional[int] = None


def _tree_leaves(x) -> list:
    import jax

    return jax.tree_util.tree_leaves(x)


def _aval_signature(aval) -> List[Any]:
    return [list(aval.shape), str(aval.dtype),
            bool(getattr(aval, "weak_type", False))]


def _scan_op_dtypes(text: str) -> Dict[str, int]:
    """Operand-dtype mix of the compute-carrying ops, plus upcast count:
    ``{"conv_f32": N, "dot_bf16": N, ..., "convert_to_f32": N}``.
    Keyed on the OPERAND dtype: a bf16-operand dot that accumulates to
    f32 is the kernels' deliberate precision contract, not a leak."""
    counts: Dict[str, int] = {}

    def bump(key):
        counts[key] = counts.get(key, 0) + 1

    for line in text.splitlines():
        if "stablehlo.convolution" in line:
            op = "conv"
        elif "stablehlo.dot_general" in line:
            op = "dot"
        elif "stablehlo.convert" in line:
            if _F32_RESULT.search(line):
                bump("convert_to_f32")
            continue
        else:
            continue
        m = _OPERAND_DTYPE.search(line)
        bump(f"{op}_{m.group(1) if m else 'other'}")
    return counts


def _lower(spec: ProgramSpec):
    """Build + abstractly lower one spec, capturing jax's
    donation-dropped warning (the runtime signal GC001 turns into a
    deterministic finding)."""
    jitted, args = spec.build()
    with warnings.catch_warnings(record=True) as wlist:
        warnings.simplefilter("always")
        lowered = jitted.lower(*args)
    dropped = sum(str(w.message).count("ShapedArray") for w in wlist
                  if "donated buffers were not usable" in str(w.message))
    return lowered, args, dropped


def audit_program(spec: ProgramSpec) -> Dict[str, Any]:
    """Lower one program and produce its lockfile record: fingerprint,
    cost, donation map, dtype mix, cache-key signature, sharding summary,
    and the per-program findings (GC001/GC002/GC005) as rendered dicts."""
    try:
        lowered, args, dropped = _lower(spec)
    except ValueError as e:
        # jax refuses sharding-incompatible programs at lowering (e.g. a
        # batch not divisible by the data axis) — that IS the GC005
        # regression, reported as a finding instead of a crashed audit
        if "shard" not in str(e).lower() and "divisible" not in str(e):
            raise
        finding = Finding(
            "GC005", spec.name, 0,
            f"program failed to lower under its declared shardings: {e}")
        return {"record": {"name": spec.name, "kind": spec.kind,
                           "fingerprint": None, "flops": 0.0,
                           "in_avals": {"n": 0, "weak": 0, "key": "",
                                        "shape_key": ""},
                           "findings": ["GC005"]},
                "findings": [finding]}
    text = lowered.as_text()
    try:
        cost = dict(lowered.cost_analysis() or {})
    except NotImplementedError:
        # some backends ship no HLO cost analysis; the record then keeps
        # fingerprint/donation/dtype checking and GC004 is skipped
        cost = {}
    flops = float(cost.get("flops", 0.0))
    nbytes = float(cost.get("bytes accessed", 0.0))
    aliased = text.count("tf.aliasing_output")
    donated_leaves = sum(len(_tree_leaves(args[i])) for i in spec.donate)
    dtype_counts = _scan_op_dtypes(text)
    sigs = [_aval_signature(a) for a in _tree_leaves(lowered.in_avals)]
    # compact cache-key digest: the executable key is the full
    # (shape, dtype, weak) tuple list; equality is all GC003 and the
    # lockfile diff need, so only hashes are recorded (a zoo model has
    # hundreds of param leaves — the full list would bloat the lockfile
    # ~20x)
    import json as json_lib

    in_avals = {
        "n": len(sigs),
        "weak": sum(1 for s in sigs if s[2]),
        "key": hashlib.sha256(
            json_lib.dumps(sigs).encode()).hexdigest(),
        "shape_key": hashlib.sha256(
            json_lib.dumps([s[0] for s in sigs]).encode()).hexdigest(),
    }

    record: Dict[str, Any] = {
        "name": spec.name,
        "kind": spec.kind,
        "fingerprint": hashlib.sha256(text.encode()).hexdigest(),
        "flops": flops,
        "bytes_accessed": nbytes,
        "rows": spec.batch_rows,
        "flops_per_row": (flops / spec.batch_rows
                          if spec.batch_rows else None),
        "compute_dtype": spec.compute_dtype,
        "donation": {
            "declared": sorted(spec.donate),
            "donated_leaves": donated_leaves,
            "aliased": aliased,
            "dropped": dropped,
            "reason": spec.donate_reason,
        },
        "dtype_counts": dtype_counts,
        "in_avals": in_avals,
        "group": spec.group,
        "model": spec.model,
        "bucket": spec.bucket,
        "mesh_axes": spec.mesh_axes,
        "sharding_summary": _sharding_summary(spec, args, text),
    }
    findings = (_rule_gc001(spec, record)
                + _rule_gc002(spec, record)
                + _rule_gc005(spec, record, args, text))
    record["findings"] = [f.code for f in findings]
    return {"record": record, "findings": findings}


def _leaf_bytes(leaf) -> int:
    import numpy as np

    return int(np.prod(leaf.shape, dtype=np.int64)
               * np.dtype(leaf.dtype).itemsize)


def _spec_shard_count(spec_json, mesh_axes: Dict[str, int]) -> int:
    """How many ways a spec_json dim list splits its leaf (product of
    the named mesh axis sizes; 1 = replicated)."""
    shards = 1
    for entry in spec_json or ():
        if entry is None:
            continue
        for axis in (entry if isinstance(entry, (list, tuple)) else (entry,)):
            shards *= int(mesh_axes.get(str(axis), 1))
    return shards


def _sharding_summary(spec: ProgramSpec, args: tuple,
                      text: str) -> Optional[Dict[str, Any]]:
    if spec.shardings is None:
        return None

    replicated_bytes = 0
    largest_leaf = 0
    batch_args = []
    param_shards: Optional[Dict[str, Any]] = None
    for i, kind in enumerate(spec.shardings):
        if kind in ("batch", "stacked_batch"):
            batch_args.append((i, 0 if kind == "batch" else 1))
        elif kind == "replicated":
            for leaf in _tree_leaves(args[i]):
                size = _leaf_bytes(leaf)
                replicated_bytes += size
                largest_leaf = max(largest_leaf, size)
        elif kind == "params":
            # tensor-parallel weights (ISSUE 14): replicated leaves
            # (empty spec) join the byte budget above; sharded leaves
            # cost bytes/shards per chip and their split dims must
            # divide (audited by GC005 via "indivisible" below)
            import jax

            from sparkdl_tpu.parallel.mesh import param_path_str

            spec_map = dict(spec.param_partition or ())
            axes = spec.mesh_axes or {}
            sharded_bytes = 0
            sharded_leaves = 0
            indivisible = []
            flat, _ = jax.tree_util.tree_flatten_with_path(args[i])
            for path, leaf in flat:
                name = param_path_str(path)
                sj = spec_map.get(name) or ()
                size = _leaf_bytes(leaf)
                # an axis name absent from the declared mesh is a
                # declaration that matches NO real layout — flagged,
                # and the leaf is EXCLUDED from both byte budgets (its
                # intended layout is unknowable, and folding it into
                # the replicated budget would stack a misleading
                # "shard it with a PartitionSpec" finding on top of
                # the typo finding that already names the fix)
                unknown = False
                for dim, entry in enumerate(sj):
                    if entry is None:
                        continue
                    names = (entry if isinstance(entry, (list, tuple))
                             else (entry,))
                    for axis in names:
                        if str(axis) not in axes:
                            unknown = True
                            indivisible.append(
                                {"param": name, "dim": dim,
                                 "shape": list(leaf.shape), "shards": 0,
                                 "unknown_axis": str(axis)})
                if unknown:
                    continue
                shards = _spec_shard_count(sj, axes)
                if shards <= 1:
                    replicated_bytes += size
                    largest_leaf = max(largest_leaf, size)
                    continue
                sharded_leaves += 1
                sharded_bytes += size // shards
                for dim, entry in enumerate(sj):
                    n = _spec_shard_count([entry], axes)
                    if n > 1 and (dim >= len(leaf.shape)
                                  or leaf.shape[dim] % n):
                        indivisible.append(
                            {"param": name, "dim": dim,
                             "shape": list(leaf.shape), "shards": n})
            # accumulate across MULTIPLE "params" args (e.g. separate
            # frozen/trainable collections): a second arg must add to
            # — never replace — the first's accounting and findings
            if param_shards is None:
                param_shards = {"specs": [], "sharded_leaves": 0,
                                "sharded_bytes_per_chip": 0,
                                "indivisible": []}
            param_shards["specs"] = sorted(
                param_shards["specs"]
                + [(n, list(sj)) for n, sj in spec_map.items()])
            param_shards["sharded_leaves"] += sharded_leaves
            param_shards["sharded_bytes_per_chip"] += sharded_bytes
            param_shards["indivisible"].extend(indivisible)
    summary: Dict[str, Any] = {
        "batch_args": batch_args,
        "replicated_bytes": replicated_bytes,
        "largest_replicated_leaf_bytes": largest_leaf,
        "annotated": text.count("mhlo.sharding"),
    }
    if param_shards is not None:
        summary["param_shards"] = param_shards
    return summary


def _rule_gc001(spec: ProgramSpec, record: Dict[str, Any]) -> List[Finding]:
    if spec.kind == "kernel":
        # kernels declare no jit-level donation; their exemption reason
        # rides in the record (inputs are chained/reused activations)
        return []
    d = record["donation"]
    if not d["declared"]:
        if spec.donate_reason is None:
            return [Finding(
                "GC001", spec.name, 0,
                "dispatch-path jit donates nothing and records no "
                "reason; pass donate_argnums (or record why donation "
                "is unsafe/pointless for this program)")]
        return []
    if d["aliased"] < d["donated_leaves"] and spec.donate_reason is None:
        return [Finding(
            "GC001", spec.name, 0,
            f"donation silently dropped: {d['donated_leaves']} donated "
            f"aval(s) but only {d['aliased']} established an "
            f"input/output alias ({d['dropped']} reported unusable by "
            f"jax) — a dtype/layout mismatch is eating the donation")]
    return []


def _rule_gc002(spec: ProgramSpec, record: Dict[str, Any]) -> List[Finding]:
    if spec.compute_dtype != "bfloat16":
        return []
    c = record["dtype_counts"]
    leaks = c.get("conv_f32", 0) + c.get("dot_f32", 0)
    if leaks:
        return [Finding(
            "GC002", spec.name, 0,
            f"{leaks} f32 compute op(s) under the declared bf16 compute "
            f"dtype (conv_f32={c.get('conv_f32', 0)}, "
            f"dot_f32={c.get('dot_f32', 0)}) — an upcast is leaking "
            f"into the hot path (see PR 6's avg_pool/rescale fixes)")]
    return []


def _rule_gc005(spec: ProgramSpec, record: Dict[str, Any], args: tuple,
                text: str) -> List[Finding]:
    if spec.shardings is None or spec.mesh_axes is None:
        return []
    findings: List[Finding] = []
    data = int(spec.mesh_axes.get("data", 1))
    model = int(spec.mesh_axes.get("model", 1))
    summary = record["sharding_summary"]
    if summary["annotated"] == 0:
        findings.append(Finding(
            "GC005", spec.name, 0,
            "no mhlo.sharding annotation reached the lowered program — "
            "the declared NamedShardings were lost before XLA"))
    for i, dim in summary["batch_args"]:
        for leaf in _tree_leaves(args[i]):
            if len(leaf.shape) > dim and leaf.shape[dim] % data:
                findings.append(Finding(
                    "GC005", spec.name, 0,
                    f"batch aval {tuple(leaf.shape)} dim {dim} not "
                    f"divisible by the {data}-way data axis — uneven "
                    f"shards recompile or fail at dispatch"))
    if (model > 1 and summary["largest_replicated_leaf_bytes"]
            > REPLICATED_PARAM_BUDGET_BYTES):
        mb = summary["largest_replicated_leaf_bytes"] / 1e6
        findings.append(Finding(
            "GC005", spec.name, 0,
            f"param leaf of {mb:.0f} MB fully replicated although the "
            f"mesh has a {model}-way model axis — shard it with a "
            f"PartitionSpec (mesh.match_partition_rules / parallel.train "
            f"param_specs) instead of paying {model}x HBM"))
    shards = summary.get("param_shards")
    if shards:
        for bad in shards["indivisible"]:
            if bad.get("unknown_axis"):
                findings.append(Finding(
                    "GC005", spec.name, 0,
                    f"sharded param {bad['param']!r} dim {bad['dim']} "
                    f"names unknown mesh axis {bad['unknown_axis']!r} "
                    f"(declared axes: {sorted(spec.mesh_axes or {})}) "
                    f"— the declaration matches no real layout"))
                continue
            findings.append(Finding(
                "GC005", spec.name, 0,
                f"sharded param {bad['param']!r} dim {bad['dim']} "
                f"(shape {tuple(bad['shape'])}) not divisible by its "
                f"{bad['shards']}-way split — the layout recompiles or "
                f"fails at device_put (mesh.resolve_param_shardings "
                f"would have replicated this leaf)"))
    return findings


def retrace_audit(records: Sequence[Dict[str, Any]]) -> List[Finding]:
    """GC003 over the WHOLE inventory: the executable cache key jax
    uses is (compiled fn identity, flat aval signatures).  Weak types,
    duplicate keys, and same-shape dtype/weak-type churn inside one
    group each force a recompilation of the "same" program at runtime —
    all three are statically visible here."""
    findings: List[Finding] = []
    seen: Dict[tuple, str] = {}
    by_group: Dict[str, list] = {}
    for rec in records:
        avals = rec["in_avals"]
        if avals["weak"]:
            findings.append(Finding(
                "GC003", rec["name"], 0,
                f"{avals['weak']} weak-typed input aval(s): a python "
                f"scalar is reaching the traced signature and will "
                f"re-specialize on the first strongly-typed call"))
        group = rec.get("group") or rec["name"]
        key = (group, avals["key"])
        if key in seen:
            findings.append(Finding(
                "GC003", rec["name"], 0,
                f"duplicate executable cache key: identical avals "
                f"already enumerated by {seen[key]} — the same program "
                f"would be built/compiled twice"))
        else:
            seen[key] = rec["name"]
        by_group.setdefault(group, []).append(rec)
    for group, recs in by_group.items():
        by_shape: Dict[str, set] = {}
        for rec in recs:
            by_shape.setdefault(rec["in_avals"]["shape_key"], set()).add(
                (rec["in_avals"]["key"], rec["name"]))
        for shape_key, keys in by_shape.items():
            if len({k for k, _ in keys}) > 1:
                names = sorted(n for _, n in keys)
                findings.append(Finding(
                    "GC003", names[0], 0,
                    f"dtype/weak-type churn in group {group!r}: "
                    f"{len(keys)} distinct cache keys share identical "
                    f"shapes ({', '.join(names)}) — each is a separate "
                    f"compilation of the same program"))
    return findings


def pad_waste_audit(records: Sequence[Dict[str, Any]],
                    interior_budget: float = PAD_INTERIOR_BUDGET,
                    floor_budget: float = PAD_FLOOR_BUDGET
                    ) -> List[Finding]:
    """GC004 over each model's bucket set: FLOPs are row-linear (the
    per-row figure must agree across buckets — checked), so the padded
    share of a bucket's FLOPs equals its padded row share.  Worst cases:
    a request of ``prev_bucket + 1`` rows served by bucket ``b`` wastes
    ``(b - prev - 1)/b`` of the program; a 1-row request pays the
    smallest bucket's floor."""
    findings: List[Finding] = []
    by_model: Dict[str, list] = {}
    for rec in records:
        if rec.get("model") and rec.get("bucket") and rec.get("flops"):
            by_model.setdefault(rec["model"], []).append(rec)
    for model, recs in sorted(by_model.items()):
        recs = sorted(recs, key=lambda r: r["bucket"])
        per_row = [r["flops"] / r["bucket"] for r in recs]
        lo, hi = min(per_row), max(per_row)
        if lo > 0 and (hi - lo) / lo > 0.02:
            findings.append(Finding(
                "GC004", f"zoo/{model}", 0,
                f"per-row FLOPs disagree across buckets "
                f"({lo / 1e9:.3f}–{hi / 1e9:.3f} GF/row): the program is "
                f"not row-linear, so pad-to-bucket accounting (and the "
                f"bench's FLOP-scaled baselines) are invalid"))
        buckets = [r["bucket"] for r in recs]
        # the formulas live in lockfile.pad_gap_fracs/pad_worst_fracs,
        # shared with bench's pad_overhead rider (ISSUE 11)
        from sparkdl_tpu.analysis.program.lockfile import (pad_gap_fracs,
                                                           pad_worst_fracs)

        floor = pad_worst_fracs(buckets)[1]
        if floor > floor_budget:
            findings.append(Finding(
                "GC004", f"zoo/{model}", 0,
                f"smallest bucket {buckets[0]} pads a 1-row request to "
                f"{floor:.0%} waste (budget {floor_budget:.0%}); add a "
                f"smaller bucket"))
        for prev, b, waste in pad_gap_fracs(buckets):
            if waste > interior_budget:
                findings.append(Finding(
                    "GC004", f"zoo/{model}", 0,
                    f"bucket gap {prev}->{b}: a {prev + 1}-row request "
                    f"wastes {waste:.0%} of bucket {b}'s FLOPs (budget "
                    f"{interior_budget:.0%}); tighten the bucket "
                    f"spacing"))
    return findings


def audit_inventory(specs: Sequence[ProgramSpec],
                    progress: Optional[Callable[[str], None]] = None
                    ) -> Tuple[List[Dict[str, Any]], List[Finding]]:
    """Audit every spec and run the cross-program rules; returns
    ``(records, findings)`` with findings sorted most-actionable first
    (per-program order, then GC003/GC004)."""
    records: List[Dict[str, Any]] = []
    findings: List[Finding] = []
    for spec in specs:
        out = audit_program(spec)
        records.append(out["record"])
        findings.extend(out["findings"])
        if progress is not None:
            r = out["record"]
            progress(f"{spec.name}: {r['flops'] / 1e9:.2f} GF, "
                     f"{len(out['findings'])} finding(s)")
    findings.extend(retrace_audit(records))
    findings.extend(pad_waste_audit(records))
    return records, findings
