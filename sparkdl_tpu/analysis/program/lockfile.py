"""``PROGRAMS.lock.json`` — the committed program-fingerprint lockfile.

One JSON document records every audited program (StableHLO sha256,
FLOPs, bytes accessed, donation map, dtype-mix counters, executable
cache-key avals, sharding summary).  ``diff_records`` classifies any
divergence between the committed baseline and a fresh audit into the
GC rule whose invariant moved — so run-tests.sh's graftcheck stage
fails NAMING the regression class (a dropped donation is GC001, an f32
upcast is GC002, a new retrace key is GC003, pad growth is GC004, a
sharding change is GC005, anything else is GC000 fingerprint drift).

This module is import-light on purpose (stdlib json only — no jax):
``bench.py`` reads its per-model FLOP denominators from the lockfile at
import time via :func:`zoo_gflop_per_img`, and pulling jax in there
would re-initialize the backend inside every bench subprocess.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

from sparkdl_tpu.analysis.core import Finding

DEFAULT_LOCKFILE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))),
    "PROGRAMS.lock.json")

SCHEMA_VERSION = 1

#: drift classification: first differing field group wins, most
#: actionable first (donation before dtype before keys before cost)
_FIELD_RULES = (
    ("GC001", ("donation",)),
    ("GC002", ("dtype_counts", "compute_dtype")),
    ("GC003", ("in_avals", "group")),
    ("GC004", ("flops", "rows", "flops_per_row", "bucket")),
    ("GC005", ("sharding_summary", "mesh_axes")),
)


def write_lockfile(records: Sequence[Dict[str, Any]], path: str,
                   meta: Optional[Dict[str, Any]] = None) -> None:
    doc = {
        "schema_version": SCHEMA_VERSION,
        "meta": dict(meta or {}),
        "programs": {rec["name"]: {k: v for k, v in sorted(rec.items())
                                   if k != "name"}
                     for rec in records},
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")


def read_lockfile(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("schema_version") != SCHEMA_VERSION:
        raise ValueError(
            f"{path}: unsupported lockfile schema "
            f"{doc.get('schema_version')!r} (expected {SCHEMA_VERSION}); "
            f"regenerate with tools/graftcheck.py --write-baseline")
    return doc


def _norm(value: Any) -> Any:
    """JSON round-trip normalization so fresh records compare equal to
    committed ones (tuples become lists, dict key order is irrelevant)."""
    return json.loads(json.dumps(value, sort_keys=True))


def diff_records(committed: Dict[str, Any],
                 current: Sequence[Dict[str, Any]],
                 subset: bool = False) -> List[Finding]:
    """Classified drift between the committed lockfile document and a
    fresh audit's records.  ``subset=True`` (the tier-1 acceptance gate
    audits a handful of programs) skips the missing-program check for
    programs the fresh audit did not enumerate."""
    findings: List[Finding] = []
    baseline = committed.get("programs", {})
    fresh = {rec["name"]: rec for rec in current}
    for name, rec in sorted(fresh.items()):
        base = baseline.get(name)
        if base is None:
            findings.append(Finding(
                "GC003", name, 0,
                "program not in the committed lockfile — a new compiled "
                "program entered the stack; review it and regenerate "
                "the baseline (tools/graftcheck.py --write-baseline)"))
            continue
        rule = None
        moved = []
        for code, fields in _FIELD_RULES:
            for f in fields:
                if _norm(rec.get(f)) != _norm(base.get(f)):
                    moved.append(f)
                    rule = rule or code
        if moved:
            findings.append(Finding(
                rule, name, 0,
                f"program drifted from the committed lockfile in "
                f"{', '.join(moved)} — "
                f"{GC_DRIFT_HINTS.get(rule, 'review the change')}"))
        elif _norm(rec.get("fingerprint")) != _norm(base.get("fingerprint")):
            findings.append(Finding(
                "GC000", name, 0,
                "StableHLO fingerprint drifted with no tracked field "
                "moving (op-level program change); review and regenerate "
                "the baseline if deliberate"))
    if not subset:
        for name in sorted(set(baseline) - set(fresh)):
            findings.append(Finding(
                "GC003", name, 0,
                "program in the committed lockfile was not enumerated "
                "by this audit — a compiled program silently left the "
                "stack (or the inventory shrank); regenerate the "
                "baseline if deliberate"))
    return findings


GC_DRIFT_HINTS = {
    "GC001": "a donation was added/dropped or stopped aliasing",
    "GC002": "the op dtype mix changed (bf16/f32 regression?)",
    "GC003": "the executable cache key changed (retrace/recompile)",
    "GC004": "FLOPs / pad accounting moved",
    "GC005": "sharding layout changed",
}


def pad_gap_fracs(buckets: Sequence[int]) -> List[tuple]:
    """``[(prev, b, waste_frac)]`` per adjacent bucket pair — THE one
    spelling of GC004's interior pad-waste formula (a request of
    ``prev + 1`` rows served by bucket ``b`` wastes ``(b - prev - 1)/b``
    of the program), shared by ``audit.pad_waste_audit`` (budgets it)
    and bench.py's ``pad_overhead`` rider (stamps it) so the two can
    never drift apart."""
    bs = sorted(int(b) for b in buckets)
    return [(prev, b, (b - prev - 1) / b) for prev, b in zip(bs, bs[1:])]


def pad_worst_fracs(buckets: Sequence[int]) -> tuple:
    """``(interior_worst, floor)`` for a bucket set: the worst adjacent
    gap from :func:`pad_gap_fracs`, and GC004's floor formula (a 1-row
    request padded to the smallest bucket pays ``(b0 - 1)/b0``)."""
    bs = sorted(int(b) for b in buckets)
    interior = max((w for _, _, w in pad_gap_fracs(bs)), default=0.0)
    return interior, (bs[0] - 1) / bs[0]


def zoo_gflop_per_img(path: Optional[str] = None) -> Dict[str, float]:
    """Per-model GFLOPs/image derived from the committed lockfile (the
    largest audited bucket of each zoo featurize program) — bench.py's
    FLOP-scaling denominators.  Returns ``{}`` when no lockfile exists
    (fresh checkouts fall back to bench.py's pinned constants)."""
    path = path or DEFAULT_LOCKFILE
    if not os.path.isfile(path):
        return {}
    try:
        doc = read_lockfile(path)
    except (ValueError, OSError, json.JSONDecodeError):
        return {}
    best: Dict[str, tuple] = {}
    for name, rec in doc.get("programs", {}).items():
        model = rec.get("model")
        rows = rec.get("rows") or 0
        flops = rec.get("flops") or 0.0
        if not (name.startswith("zoo/") and model and rows and flops):
            continue
        if rows > best.get(model, (0, 0.0))[0]:
            best[model] = (rows, flops)
    return {model: flops / rows / 1e9
            for model, (rows, flops) in best.items()}
