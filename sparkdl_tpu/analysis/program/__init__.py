"""graftcheck — jaxpr/StableHLO program auditing (ISSUE 6).

graftlint (the sibling rule set, ``sparkdl_tpu.analysis``) checks the
PYTHON source; this package checks the COMPILED PROGRAMS the stack
actually ships.  Every program the scoring/training stack constructs —
the full zoo × the serving bucket plan, the data-parallel train step
(plain and ``steps_per_execution`` scan), the sepconv Pallas-path jits —
is lowered ABSTRACTLY on CPU (``jax.eval_shape``/``jit(...).lower()``
over ``ShapeDtypeStruct`` avals: no device, no weights, no compile) and
audited against program-level rules:

====== ==================================================================
code   invariant
====== ==================================================================
GC000  committed program fingerprint (StableHLO hash) matches the audit
GC001  dispatch/train-path jits donate their consumable inputs, and a
       DECLARED donation actually establishes its input/output aliases
       (a dtype/layout mismatch silently drops donation) — or the
       program carries a recorded reason
GC002  under a declared bf16 compute dtype no ``dot_general``/
       ``convolution`` runs in f32 (the whole-network upcasts PR 6
       found and fixed in InceptionV3/EfficientNetB0)
GC003  the statically enumerated (fn, mesh, donation, shape, dtype)
       executable cache keys contain no weak types, no duplicates, and
       no same-shape dtype churn that would recompile the "same"
       program
GC004  pad-to-bucket waste stays inside budget: per-bucket
       ``cost_analysis`` FLOPs split into useful vs pad rows, adjacent
       buckets within the interior-waste budget
GC005  every program's params/batch shardings are consistent with the
       mesh axes (batch divisible by the data axis, shardings present
       in the lowered text), and no large param is fully replicated
       while a usable model axis exists
====== ==================================================================

Findings are serialized into a committed ``PROGRAMS.lock.json``
(per-program StableHLO hash, FLOPs, bytes accessed, donation map,
dtype-mix counters, sharding summary), so ANY drift — a dropped
donation, a dtype regression, a new retrace key, pad growth — fails
``run-tests.sh``'s graftcheck stage deterministically without a chip.
``tools/graftcheck.py`` is the CLI; ``--write-baseline`` regenerates
the lockfile after a reviewed, deliberate program change.
"""

from __future__ import annotations

from sparkdl_tpu.analysis.program.audit import (GC_RULE_HELP, ProgramSpec,
                                                audit_inventory,
                                                audit_program,
                                                pad_waste_audit,
                                                retrace_audit)
from sparkdl_tpu.analysis.program.inventory import (
    fleet_dispatch_specs, headfanout_dispatch_specs, stack_programs)
from sparkdl_tpu.analysis.program.lockfile import (DEFAULT_LOCKFILE,
                                                   diff_records,
                                                   read_lockfile,
                                                   write_lockfile,
                                                   zoo_gflop_per_img)

__all__ = [
    "GC_RULE_HELP",
    "ProgramSpec",
    "audit_program",
    "audit_inventory",
    "retrace_audit",
    "pad_waste_audit",
    "stack_programs",
    "fleet_dispatch_specs",
    "headfanout_dispatch_specs",
    "DEFAULT_LOCKFILE",
    "read_lockfile",
    "write_lockfile",
    "diff_records",
    "zoo_gflop_per_img",
]
