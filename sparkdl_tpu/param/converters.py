"""Domain-specific type converters.

Replaces ``SparkDLTypeConverters`` (``python/sparkdl/param/converters.py``):
validated conversion of user-supplied values — zoo-model names, optimizer /
loss identifiers, callables, column-name tuples — into canonical internal
form, raising ``TypeError`` on anything malformed (same failure contract the
reference's estimator param-validation tests assert on).
"""

from __future__ import annotations

from typing import Any, Callable

from sparkdl_tpu.param.params import TypeConverters


def supported_name_converter(supported):
    """Build a converter accepting only names in ``supported`` (case-insensitive
    resolution to the canonical casing)."""
    canonical = {name.lower(): name for name in supported}

    def _convert(value):
        if not isinstance(value, str):
            raise TypeError(f"Expected a model-name string, got {value!r}")
        key = value.lower()
        if key not in canonical:
            raise TypeError(
                f"{value!r} is not in the supported list {sorted(supported)}")
        return canonical[key]

    return _convert


class SparkDLTypeConverters:
    """Converters for framework-specific param types."""

    supportedNameConverter = staticmethod(supported_name_converter)

    @staticmethod
    def toOptimizer(value) -> Any:
        """Accept an optax GradientTransformation, a factory callable, or a
        canonical optimizer-name string (adam/sgd/rmsprop/adamw/...).

        Replaces ``SparkDLTypeConverters.toKerasOptimizer`` — here the string
        resolves to an optax constructor instead of a keras identifier.
        """
        import optax
        if isinstance(value, optax.GradientTransformation):
            return value
        if callable(value):
            return value
        if isinstance(value, str):
            name = value.lower()
            table = {
                "adam": optax.adam,
                "adamw": optax.adamw,
                "sgd": optax.sgd,
                "rmsprop": optax.rmsprop,
                "adagrad": optax.adagrad,
                "lamb": optax.lamb,
                "lion": optax.lion,
            }
            if name in table:
                return table[name]
            raise TypeError(f"Unknown optimizer name {value!r}")
        raise TypeError(f"Could not convert {value!r} to an optimizer")

    @staticmethod
    def toLoss(value) -> Any:
        """Accept a loss callable ``(logits, labels) -> scalar`` or a canonical
        loss-name string.  Replaces ``toKerasLoss``."""
        if callable(value):
            return value
        if isinstance(value, str):
            name = value.lower()
            table = {
                "categorical_crossentropy": "categorical_crossentropy",
                "sparse_categorical_crossentropy": "sparse_categorical_crossentropy",
                "binary_crossentropy": "binary_crossentropy",
                "mse": "mse",
                "mean_squared_error": "mse",
                "mae": "mae",
                "mean_absolute_error": "mae",
            }
            if name in table:
                return table[name]
            raise TypeError(f"Unknown loss name {value!r}")
        raise TypeError(f"Could not convert {value!r} to a loss")

    @staticmethod
    def toColumnToTensorMap(value):
        """Validate a {column_name: tensor_name} dict (both strings)."""
        if not isinstance(value, dict):
            raise TypeError(f"Expected dict, got {value!r}")
        out = {}
        for k, v in value.items():
            if not isinstance(k, str) or not isinstance(v, str):
                raise TypeError(
                    f"Column/tensor mapping must be str->str, got {k!r}: {v!r}")
            out[k] = v
        return out

    @staticmethod
    def toModelFunction(value):
        """Accept a ModelFunction (sparkdl_tpu.graph) or raise."""
        from sparkdl_tpu.graph.function import ModelFunction
        if isinstance(value, ModelFunction):
            return value
        raise TypeError(f"Expected a ModelFunction, got {type(value).__name__}")

    toCallable = staticmethod(TypeConverters.toCallable)
