"""Domain-specific type converters.

Replaces ``SparkDLTypeConverters`` (``python/sparkdl/param/converters.py``):
validated conversion of user-supplied values — zoo-model names, optimizer /
loss identifiers, callables, column-name tuples — into canonical internal
form, raising ``TypeError`` on anything malformed (same failure contract the
reference's estimator param-validation tests assert on).
"""

from __future__ import annotations

from typing import Any, Callable

from sparkdl_tpu.param.params import TypeConverters


def supported_name_converter(supported):
    """Build a converter accepting only names in ``supported`` (case-insensitive
    resolution to the canonical casing)."""
    canonical = {name.lower(): name for name in supported}

    def _convert(value):
        if not isinstance(value, str):
            raise TypeError(f"Expected a model-name string, got {value!r}")
        key = value.lower()
        if key not in canonical:
            raise TypeError(
                f"{value!r} is not in the supported list {sorted(supported)}")
        return canonical[key]

    return _convert


class SparkDLTypeConverters:
    """Converters for framework-specific param types."""

    supportedNameConverter = staticmethod(supported_name_converter)

    @staticmethod
    def toOptimizer(value) -> Any:
        """Accept an optax GradientTransformation, a factory callable, or a
        canonical optimizer-name string (adam/sgd/rmsprop/adamw/...).

        Replaces ``SparkDLTypeConverters.toKerasOptimizer`` — here the string
        resolves to an optax constructor instead of a keras identifier.
        """
        import optax
        if isinstance(value, optax.GradientTransformation):
            return value
        if callable(value):
            # Must be a ZERO-ARG factory (called at fit time).  Reject
            # constructors like optax.adam here so the mistake surfaces at
            # set time, not mid-fit.
            import inspect

            try:
                sig = inspect.signature(value)
                required = [
                    p for p in sig.parameters.values()
                    if p.default is inspect.Parameter.empty
                    and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
            except (TypeError, ValueError):
                required = []
            if required:
                raise TypeError(
                    f"Optimizer factory {value!r} requires arguments "
                    f"{[p.name for p in required]}; pass a constructed "
                    f"optimizer (e.g. optax.adam(1e-3)) or a zero-arg factory")
            return value
        if isinstance(value, str):
            name = value.lower()
            # Name strings construct with keras-style default learning rates
            # (the reference's string->keras-optimizer contract); pass an
            # optax object for custom settings.
            table = {
                "adam": lambda: optax.adam(1e-3),
                "adamw": lambda: optax.adamw(1e-3),
                "sgd": lambda: optax.sgd(1e-2),
                "rmsprop": lambda: optax.rmsprop(1e-3),
                "adagrad": lambda: optax.adagrad(1e-2),
                "lamb": lambda: optax.lamb(1e-3),
                "lion": lambda: optax.lion(1e-4),
            }
            if name in table:
                return table[name]()
            raise TypeError(f"Unknown optimizer name {value!r}")
        raise TypeError(f"Could not convert {value!r} to an optimizer")

    @staticmethod
    def toLoss(value) -> Any:
        """Accept a loss callable ``(logits, labels) -> scalar`` or a canonical
        loss-name string.  Replaces ``toKerasLoss``."""
        if callable(value):
            return value
        if isinstance(value, str):
            name = value.lower()
            table = {
                "categorical_crossentropy": "categorical_crossentropy",
                "sparse_categorical_crossentropy": "sparse_categorical_crossentropy",
                "binary_crossentropy": "binary_crossentropy",
                "mse": "mse",
                "mean_squared_error": "mse",
                "mae": "mae",
                "mean_absolute_error": "mae",
            }
            if name in table:
                return table[name]
            raise TypeError(f"Unknown loss name {value!r}")
        raise TypeError(f"Could not convert {value!r} to a loss")

    @staticmethod
    def toColumnToTensorMap(value):
        """Validate a {column_name: tensor_name} dict (both strings)."""
        if not isinstance(value, dict):
            raise TypeError(f"Expected dict, got {value!r}")
        out = {}
        for k, v in value.items():
            if not isinstance(k, str) or not isinstance(v, str):
                raise TypeError(
                    f"Column/tensor mapping must be str->str, got {k!r}: {v!r}")
            out[k] = v
        return out

    @staticmethod
    def toModelFunction(value):
        """Accept a ModelFunction (sparkdl_tpu.graph) or raise."""
        from sparkdl_tpu.graph.function import ModelFunction
        if isinstance(value, ModelFunction):
            return value
        raise TypeError(f"Expected a ModelFunction, got {type(value).__name__}")

    toCallable = staticmethod(TypeConverters.toCallable)
