"""Typed, string-addressable parameter system.

This is the framework's config system, replacing the reference's Spark ML
Params layer (``python/sparkdl/param/__init__.py`` — ``keyword_only``, shared
``Param`` definitions, ``SparkDLTypeConverters``).  Every pipeline stage
(transformer / estimator) carries typed, validated, *string-addressable*
params; string addressability is load-bearing — it is what makes
``ParamGridBuilder`` / ``CrossValidator`` hyperparameter search work.

Spark-independent: no pyspark import anywhere.
"""

from sparkdl_tpu.param.params import (
    Param,
    Params,
    TypeConverters,
    keyword_only,
)
from sparkdl_tpu.param.shared import (
    HasInputCol,
    HasOutputCol,
    HasBatchSize,
    HasModelName,
    HasTopK,
    HasLabelCol,
    HasOutputMode,
    CanLoadImage,
)
from sparkdl_tpu.param.converters import SparkDLTypeConverters

__all__ = [
    "Param",
    "Params",
    "TypeConverters",
    "keyword_only",
    "SparkDLTypeConverters",
    "HasInputCol",
    "HasOutputCol",
    "HasBatchSize",
    "HasModelName",
    "HasTopK",
    "HasLabelCol",
    "HasOutputMode",
    "CanLoadImage",
]
