"""Shared param mixins used across pipeline stages.

Replaces the reference's shared ``Param`` definitions in
``python/sparkdl/param/shared_params.py`` (``HasInputCol``, ``HasOutputCol``,
``HasLabelCol``, ``CanLoadImage``, ...) — the common vocabulary every
transformer/estimator speaks.
"""

from __future__ import annotations

from sparkdl_tpu.param.params import Param, Params, TypeConverters


class HasInputCol(Params):
    inputCol = Param(
        "undefined", "inputCol", "name of the input column",
        typeConverter=TypeConverters.toString)

    def setInputCol(self, value):
        return self._set(inputCol=value)

    def getInputCol(self):
        return self.getOrDefault(self.inputCol)


class HasOutputCol(Params):
    outputCol = Param(
        "undefined", "outputCol", "name of the output column",
        typeConverter=TypeConverters.toString)

    def setOutputCol(self, value):
        return self._set(outputCol=value)

    def getOutputCol(self):
        return self.getOrDefault(self.outputCol)


class HasLabelCol(Params):
    labelCol = Param(
        "undefined", "labelCol", "name of the label column",
        typeConverter=TypeConverters.toString)

    def setLabelCol(self, value):
        return self._set(labelCol=value)

    def getLabelCol(self):
        return self.getOrDefault(self.labelCol)


class HasBatchSize(Params):
    batchSize = Param(
        "undefined", "batchSize",
        "device batch size; batches are padded up to this shape so the "
        "compiled XLA program is reused across calls",
        typeConverter=TypeConverters.toInt)

    def setBatchSize(self, value):
        return self._set(batchSize=value)

    def getBatchSize(self):
        return self.getOrDefault(self.batchSize)


class HasModelName(Params):
    modelName = Param(
        "undefined", "modelName",
        "name of a model in the pretrained zoo (see sparkdl_tpu.models.SUPPORTED_MODELS)",
        typeConverter=TypeConverters.toString)

    def setModelName(self, value):
        return self._set(modelName=value)

    def getModelName(self):
        return self.getOrDefault(self.modelName)


class HasTopK(Params):
    topK = Param(
        "undefined", "topK",
        "how many class predictions to return per image",
        typeConverter=TypeConverters.toInt)

    def setTopK(self, value):
        return self._set(topK=value)

    def getTopK(self):
        return self.getOrDefault(self.topK)


def _output_mode_converter(value):
    if value not in HasOutputMode.OUTPUT_MODES:
        raise TypeError(
            f"outputMode must be one of {HasOutputMode.OUTPUT_MODES}, got {value!r}")
    return value


class HasOutputMode(Params):
    OUTPUT_MODES = ("vector", "image")

    outputMode = Param(
        "undefined", "outputMode",
        'output column payload: "vector" (flat float vector) or "image" '
        "(image struct)  — mirrors TFImageTransformer.OUTPUT_MODES",
        typeConverter=_output_mode_converter)

    def setOutputMode(self, value):
        return self._set(outputMode=value)

    def getOutputMode(self):
        return self.getOrDefault(self.outputMode)


class CanLoadImage(Params):
    """Mixin for stages that read image files through a user preprocessor.

    Mirrors the reference's ``CanLoadImage`` (``sparkdl/param/image_params.py``):
    ``imageLoader`` is a user function ``uri -> np.ndarray[H,W,C] float`` doing
    decode + model-specific preprocessing; the stage maps it over a URI column.
    """

    imageLoader = Param(
        "undefined", "imageLoader",
        "function uri -> numpy array [H,W,C]; decodes and preprocesses one "
        "image for the model",
        typeConverter=TypeConverters.toCallable)

    def setImageLoader(self, value):
        return self._set(imageLoader=value)

    def getImageLoader(self):
        return self.getOrDefault(self.imageLoader)

    def loadImagesInternal(self, uris):
        """Load a sequence of URIs into a stacked numpy batch."""
        import numpy as np
        loader = self.getImageLoader()
        arrs = [np.asarray(loader(u)) for u in uris]
        return np.stack(arrs, axis=0)
