"""Core Param / Params machinery.

Re-designs the contract of ``pyspark.ml.param`` that the reference's config
system (``python/sparkdl/param/`` — C16 in SURVEY.md) is built on, without any
Spark dependency: typed ``Param`` descriptors attached to stage classes,
per-instance value maps, defaults, copy-with-overrides, and string addressing
via ``getParam(name)`` so parameter grids can be built programmatically.
"""

from __future__ import annotations

import copy
import functools
import inspect
from typing import Any, Callable, Dict, Iterable, List, Optional


class Param:
    """A typed parameter descriptor with self-contained documentation.

    Mirrors the role of ``pyspark.ml.param.Param`` used throughout the
    reference (e.g. ``sparkdl/param/shared_params.py``): identified by
    ``(parent, name)``, with an optional ``typeConverter`` that validates and
    normalizes values at ``set`` time.
    """

    def __init__(self, parent: "Params", name: str, doc: str,
                 typeConverter: Optional[Callable[[Any], Any]] = None):
        self.parent = parent.uid if isinstance(parent, Params) else str(parent)
        self.name = name
        self.doc = doc
        self.typeConverter = typeConverter or TypeConverters.identity

    def _copy_new_parent(self, parent: "Params") -> "Param":
        p = copy.copy(self)
        p.parent = parent.uid
        return p

    def __str__(self):
        return f"{self.parent}__{self.name}"

    def __repr__(self):
        return f"Param(parent={self.parent!r}, name={self.name!r}, doc={self.doc!r})"

    def __hash__(self):
        return hash(str(self))

    def __eq__(self, other):
        return isinstance(other, Param) and str(self) == str(other)


class TypeConverters:
    """Built-in value converters/validators for ``Param.typeConverter``."""

    @staticmethod
    def identity(value):
        return value

    @staticmethod
    def toInt(value):
        if isinstance(value, bool):
            raise TypeError(f"Could not convert {value!r} to int")
        try:
            iv = int(value)
        except (TypeError, ValueError):
            raise TypeError(f"Could not convert {value!r} to int")
        if float(iv) != float(value):
            raise TypeError(f"Could not losslessly convert {value!r} to int")
        return iv

    @staticmethod
    def toFloat(value):
        if isinstance(value, bool):
            raise TypeError(f"Could not convert {value!r} to float")
        try:
            return float(value)
        except (TypeError, ValueError):
            raise TypeError(f"Could not convert {value!r} to float")

    @staticmethod
    def toString(value):
        if isinstance(value, str):
            return value
        raise TypeError(f"Could not convert {value!r} to string")

    @staticmethod
    def toBoolean(value):
        if isinstance(value, bool):
            return value
        raise TypeError(f"Could not convert {value!r} to boolean")

    @staticmethod
    def toList(value):
        if isinstance(value, (list, tuple)):
            return list(value)
        raise TypeError(f"Could not convert {value!r} to list")

    @staticmethod
    def toListString(value):
        lst = TypeConverters.toList(value)
        return [TypeConverters.toString(v) for v in lst]

    @staticmethod
    def toListFloat(value):
        lst = TypeConverters.toList(value)
        return [TypeConverters.toFloat(v) for v in lst]

    @staticmethod
    def toDict(value):
        if isinstance(value, dict):
            return dict(value)
        raise TypeError(f"Could not convert {value!r} to dict")

    @staticmethod
    def toCallable(value):
        if callable(value):
            return value
        raise TypeError(f"{value!r} is not callable")


def keyword_only(func):
    """Decorator forcing keyword-only invocation, stashing kwargs.

    Same contract as the reference's ``keyword_only`` (re-exported from
    ``sparkdl/param/__init__.py``): the wrapped ``__init__``/``setParams``
    records its keyword arguments in ``self._input_kwargs`` so the stage can
    forward them to ``_set``.
    """

    @functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        if args:
            raise TypeError(
                f"Method {func.__name__} only takes keyword arguments.")
        self._input_kwargs = kwargs
        return func(self, **kwargs)

    return wrapper


_uid_counters: Dict[str, int] = {}


def _gen_uid(cls_name: str) -> str:
    n = _uid_counters.get(cls_name, 0)
    _uid_counters[cls_name] = n + 1
    return f"{cls_name}_{n:04x}"


class Params:
    """Mixin giving a stage typed params, defaults, and string addressing.

    Class attributes of type :class:`Param` are discovered automatically and
    re-parented per instance (matching pyspark.ml semantics the reference
    relies on).  Values live in ``_paramMap``; defaults in ``_defaultParamMap``.
    """

    def __init__(self):
        self.uid = _gen_uid(type(self).__name__)
        self._paramMap: Dict[Param, Any] = {}
        self._defaultParamMap: Dict[Param, Any] = {}
        # Re-parent class-level Param descriptors onto this instance so that
        # two instances of the same stage never alias each other's params.
        for name in dir(type(self)):
            attr = getattr(type(self), name, None)
            if isinstance(attr, Param):
                setattr(self, name, attr._copy_new_parent(self))

    # -- discovery ---------------------------------------------------------
    @property
    def params(self) -> List[Param]:
        return sorted(
            (getattr(self, name) for name in dir(self)
             if name != "params" and isinstance(getattr(self, name, None), Param)),
            key=lambda p: p.name)

    def getParam(self, name: str) -> Param:
        """String-addressable lookup — the grid-search contract."""
        p = getattr(self, name, None)
        if isinstance(p, Param):
            return p
        raise ValueError(f"{type(self).__name__} has no param {name!r}")

    def hasParam(self, name: str) -> bool:
        return isinstance(getattr(self, name, None), Param)

    # -- get/set -----------------------------------------------------------
    def _resolveParam(self, param) -> Param:
        if isinstance(param, Param):
            if param.parent != self.uid:
                # Accept a sibling instance's descriptor by name (pyspark
                # tolerates this inside paramMaps built from another copy).
                return self.getParam(param.name)
            return param
        if isinstance(param, str):
            return self.getParam(param)
        raise TypeError(f"Cannot resolve param from {param!r}")

    def _set(self, **kwargs) -> "Params":
        for name, value in kwargs.items():
            if value is None:
                continue
            p = self.getParam(name)
            self._paramMap[p] = p.typeConverter(value)
        return self

    def set(self, param, value) -> "Params":
        p = self._resolveParam(param)
        self._paramMap[p] = p.typeConverter(value)
        return self

    def _setDefault(self, **kwargs) -> "Params":
        for name, value in kwargs.items():
            p = self.getParam(name)
            if value is not None:
                value = p.typeConverter(value)
            self._defaultParamMap[p] = value
        return self

    def isSet(self, param) -> bool:
        return self._resolveParam(param) in self._paramMap

    def hasDefault(self, param) -> bool:
        return self._resolveParam(param) in self._defaultParamMap

    def isDefined(self, param) -> bool:
        return self.isSet(param) or self.hasDefault(param)

    def getOrDefault(self, param):
        p = self._resolveParam(param)
        if p in self._paramMap:
            return self._paramMap[p]
        if p in self._defaultParamMap:
            return self._defaultParamMap[p]
        raise KeyError(
            f"Param {p.name!r} is not set and has no default on {self.uid}")

    def extractParamMap(self, extra: Optional[Dict[Param, Any]] = None) -> Dict[Param, Any]:
        m = dict(self._defaultParamMap)
        m.update(self._paramMap)
        if extra:
            m.update({self._resolveParam(k): v for k, v in extra.items()})
        return m

    # -- copy --------------------------------------------------------------
    def copy(self, extra: Optional[Dict[Param, Any]] = None) -> "Params":
        that = copy.copy(self)
        that._paramMap = dict(self._paramMap)
        that._defaultParamMap = dict(self._defaultParamMap)
        # Params keep pointing at self.uid intentionally (pyspark keeps the
        # uid on copy too), so descriptors still resolve.
        if extra:
            for k, v in extra.items():
                p = that._resolveParam(k)
                that._paramMap[p] = p.typeConverter(v)
        return that

    # -- persistence (Spark ML writable/readable contract) ------------------
    def save(self, path: str, overwrite: bool = False) -> str:
        """Write this stage to ``path``; see sparkdl_tpu.persistence."""
        from sparkdl_tpu import persistence

        return persistence.save_stage(self, path, overwrite=overwrite)

    @classmethod
    def load(cls, path: str) -> "Params":
        from sparkdl_tpu import persistence

        stage = persistence.load_stage(path)
        if not isinstance(stage, cls):
            raise TypeError(
                f"{path} holds a {type(stage).__name__}, not a {cls.__name__}")
        return stage

    def _persist(self, path: str):
        """Hook: (extra metadata dict, variables pytree or None, pickles
        dict).  The default persists nothing beyond JSON-able params."""
        return {}, None, {}

    @classmethod
    def _restore(cls, extra: Dict, pytree, pickles: Dict, path: str):
        """Hook: rebuild an instance from the persisted pieces (params are
        re-applied by the caller afterwards)."""
        return cls()

    def explainParam(self, param) -> str:
        p = self._resolveParam(param)
        value = "undefined"
        if self.hasDefault(p):
            value = f"default: {self._defaultParamMap[p]!r}"
        if self.isSet(p):
            value = f"current: {self._paramMap[p]!r}"
        return f"{p.name}: {p.doc} ({value})"

    def explainParams(self) -> str:
        return "\n".join(self.explainParam(p) for p in self.params)
