"""EfficientNetB0 as a flax module — a zoo extension BEYOND the reference.

Like MobileNetV2 (``models/mobilenet.py``), this extends the reference's
five-architecture registry (``python/sparkdl/transformers/named_image.py``)
with a modern efficiency-class backbone.  Featurizer cut = global average
pool after ``top_conv`` (1280-d).

Layer names mirror ``keras.applications.EfficientNetB0`` exactly
("stem_conv", "block1a_dwconv", "block2a_se_reduce", ..., "top_conv",
"predictions"), so weights import BY NAME — except the input
``Normalization`` layer, which keras auto-suffixes per session build and
therefore also has a creation-order fallback in the registry.  Keras folds
the input pipeline INTO the model: ``x/255``, the ``Normalization`` layer
(mean/variance ship as weights -> the batch_stats-carrying ``InputNorm``
submodule, importer kind "norm"), and — ONLY when built with pretrained
imagenet weights — an extra weightless ``Rescaling(1/sqrt(std))``
correction (upstream tf#49930 workaround), captured here as InputNorm's
``post_scale`` stat via :func:`efficientnet_import_fixup`.  The registry's
preprocess mode is "none" (no host-side scaling).  Stride-2 stages
zero-pad with Keras's ``correct_pad`` then convolve VALID; activations are
SiLU (swish); BN epsilon is the Keras default 1e-3.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from sparkdl_tpu.models.layers import DepthwiseConv2D, global_avg_pool

# Per-stage (kernel, repeats, out_channels, expand_ratio, first_stride) —
# EfficientNet-B0 (width/depth multiplier 1.0).
_STAGES = ((3, 1, 16, 1, 1), (3, 2, 24, 6, 2), (5, 2, 40, 6, 2),
           (3, 3, 80, 6, 2), (5, 3, 112, 6, 1), (5, 4, 192, 6, 2),
           (3, 1, 320, 6, 1))
_SE_RATIO = 0.25


def _correct_pad(x, kernel: int):
    """Keras ``imagenet_utils.correct_pad`` for stride-2 VALID convs."""
    adjust = (1 - x.shape[1] % 2, 1 - x.shape[2] % 2)
    correct = (kernel // 2, kernel // 2)
    pad = ((correct[0] - adjust[0], correct[0]),
           (correct[1] - adjust[1], correct[1]))
    return jnp.pad(x, ((0, 0), pad[0], pad[1], (0, 0)))


class InputNorm(nn.Module):
    """Keras ``Normalization`` twin: ((x - mean) / sqrt(var)) * post_scale,
    with the dataset statistics shipped as (non-trainable) batch_stats so
    the weight importer can fill them (kind "norm").  ``post_scale``
    captures the weightless Rescaling correction keras inserts only in
    imagenet-weight builds (see module docstring); it defaults to 1."""

    channels: int = 3

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        mean = self.variable("batch_stats", "mean",
                             lambda: jnp.zeros((self.channels,), jnp.float32))
        var = self.variable("batch_stats", "var",
                            lambda: jnp.ones((self.channels,), jnp.float32))
        post = self.variable("batch_stats", "post_scale",
                             lambda: jnp.ones((self.channels,), jnp.float32))
        return (x - mean.value) / jnp.sqrt(var.value) * post.value


def efficientnet_import_fixup(keras_model, variables: dict) -> dict:
    """Capture keras's weightless post-Normalization ``Rescaling``.

    ``EfficientNetB0(weights="imagenet")`` inserts a second Rescaling
    layer (per-channel ``1/sqrt(IMAGENET_STDDEV_RGB)``) AFTER the
    Normalization layer; it carries no weights, so the weight importer
    cannot see it.  This post-import hook reads its scale into
    InputNorm's ``post_scale`` stat; weights=None builds have no such
    layer and keep the default 1."""
    import numpy as np

    rescalings = [l for l in keras_model.layers
                  if type(l).__name__ == "Rescaling"]
    if len(rescalings) < 2:
        return variables
    scale = np.asarray(rescalings[1].scale, dtype=np.float32).reshape(-1)
    if scale.size == 1:
        scale = np.repeat(scale, 3)
    variables["batch_stats"]["normalization"]["post_scale"] = scale
    return variables


class EfficientNetB0(nn.Module):
    """``drop_connect_rate`` enables keras-parity stochastic depth on the
    residual blocks during ``train=True`` (per-block rate ramps linearly
    ``rate * block_index / num_blocks``, per-sample noise shape, like
    ``keras.applications`` Dropout(noise_shape=(None,1,1,1))).  Default
    0.0 = off: inference/featurization parity is unaffected either way,
    and fine-tuning without an rng stays valid; pass a "dropout" rng to
    ``apply`` when enabling it (keras trains B0 with 0.2)."""

    num_classes: int = 1000
    drop_connect_rate: float = 0.0

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False,
                 features: bool = False, logits: bool = False) -> jnp.ndarray:

        def bn(name):
            return nn.BatchNorm(use_running_average=not train,
                                momentum=0.99, epsilon=1e-3, name=name)

        # Input pipeline lives IN the model (keras parity): rescale then
        # the weights-carrying normalization.  The rescale divides in the
        # input's own float dtype — a concrete f32 divisor would upcast a
        # bf16 program (and every conv after it) to f32 (graftcheck
        # GC002); integer inputs (the uint8 default path) still promote
        # to f32 exactly as before.
        rescale_dtype = (x.dtype if jnp.issubdtype(x.dtype, jnp.floating)
                         else jnp.float32)
        x = x / jnp.asarray(255.0, rescale_dtype)
        x = InputNorm(name="normalization")(x)

        x = _correct_pad(x, 3)
        x = nn.Conv(32, (3, 3), strides=(2, 2), padding="VALID",
                    use_bias=False, name="stem_conv")(x)
        x = nn.silu(bn("stem_bn")(x))

        num_blocks = sum(st[1] for st in _STAGES)
        block_idx = 0
        for stage_idx, (k, repeats, c_out, t, s) in enumerate(_STAGES, 1):
            for rep in range(repeats):
                stride = s if rep == 0 else 1
                prefix = f"block{stage_idx}{chr(ord('a') + rep)}"
                cin = x.shape[-1]
                inp = x
                filters = cin * t
                if t != 1:
                    x = nn.Conv(filters, (1, 1), use_bias=False,
                                name=f"{prefix}_expand_conv")(x)
                    x = nn.silu(bn(f"{prefix}_expand_bn")(x))
                if stride == 2:
                    x = _correct_pad(x, k)
                x = DepthwiseConv2D(
                    (k, k), strides=(stride, stride),
                    padding="SAME" if stride == 1 else "VALID",
                    use_bias=False, name=f"{prefix}_dwconv")(x)
                x = nn.silu(bn(f"{prefix}_bn")(x))
                # Squeeze-and-excitation over the EXPANDED channels; the
                # bottleneck width derives from the block INPUT channels.
                se_filters = max(1, int(cin * _SE_RATIO))
                se = jnp.mean(x, axis=(1, 2), keepdims=True)
                se = nn.Conv(se_filters, (1, 1),
                             name=f"{prefix}_se_reduce")(se)
                se = nn.silu(se)
                se = nn.Conv(filters, (1, 1),
                             name=f"{prefix}_se_expand")(se)
                x = x * nn.sigmoid(se)
                x = nn.Conv(c_out, (1, 1), use_bias=False,
                            name=f"{prefix}_project_conv")(x)
                x = bn(f"{prefix}_project_bn")(x)
                if stride == 1 and cin == c_out:
                    drop = self.drop_connect_rate * block_idx / num_blocks
                    if train and drop > 0:
                        # per-sample stochastic depth (keras Dropout with
                        # noise_shape=(None,1,1,1)): survivors rescale.
                        import jax

                        keep = 1.0 - drop
                        mask = jax.random.bernoulli(
                            self.make_rng("dropout"), keep,
                            (x.shape[0], 1, 1, 1))
                        x = jnp.where(mask, x / jnp.float32(keep),
                                      jnp.float32(0.0))
                    x = x + inp
                block_idx += 1

        x = nn.Conv(1280, (1, 1), use_bias=False, name="top_conv")(x)
        x = nn.silu(bn("top_bn")(x))
        x = global_avg_pool(x)  # 1280-d featurizer cut
        if features:
            return x
        x = nn.Dense(self.num_classes, name="predictions")(x)
        if logits:
            return x
        return nn.softmax(x)
