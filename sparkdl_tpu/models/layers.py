"""Shared flax.linen building blocks for the pretrained-CNN zoo.

The zoo replaces the reference's model registry (``python/sparkdl/transformers/
named_image.py — SUPPORTED_MODELS`` and the Scala ``Models.scala`` packaged
GraphDefs) with hand-written flax modules.  Design rules:

  * NHWC layout, ``padding="SAME"`` via lax's TF-compatible asymmetric padding
    — both match what the MXU/XLA:TPU pipeline expects and what the Keras
    weights were trained under, so weight import is layout-transpose-free.
  * Submodule names equal the corresponding Keras layer names wherever
    keras.applications assigns explicit names (VGG/ResNet/Xception), so the
    weight importer can match by name; InceptionV3 (auto-named layers
    upstream) is matched by deterministic build order instead.
  * BatchNorm carries real ``batch_stats`` so the same module trains (for
    fine-tuning in the estimator) and infers (featurizer/predictor).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

# Keras BatchNormalization defaults; individual models override epsilon.
BN_EPS_DEFAULT = 1e-3
BN_MOMENTUM_DEFAULT = 0.99


def _depthwise_conv(x: jnp.ndarray, dw: jnp.ndarray, strides, padding,
                    dtype) -> jnp.ndarray:
    """Apply a Keras-layout depthwise kernel [H,W,Cin,mult] via lax.

    The Keras depthwise output channel (c, m) -> c*mult + m equals a
    C-major reshape to [H,W,1,Cin*mult], which is exactly lax's
    grouped-conv kernel layout (feature_group_count=Cin) — the one subtle
    layout fact both depthwise modules depend on, kept in one place."""
    import jax.lax as lax

    kh, kw, cin, mult = dw.shape
    dw_lax = dw.reshape(kh, kw, 1, cin * mult)
    return lax.conv_general_dilated(
        jnp.asarray(x, dtype), jnp.asarray(dw_lax, dtype),
        window_strides=strides,
        padding=padding,
        feature_group_count=cin,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


class SeparableConv2D(nn.Module):
    """Depthwise-separable conv matching ``keras.layers.SeparableConv2D``.

    Param layout mirrors Keras: ``depthwise_kernel`` [H,W,Cin,mult] and
    ``pointwise_kernel`` [1,1,Cin*mult,Cout] (plus optional bias), so the
    importer can copy Keras weights verbatim.  Lowered as a grouped conv
    (feature_group_count=Cin) followed by a 1x1 conv — XLA fuses both onto
    the MXU.

    ``fused_flat`` switches to the pallas fused inference path
    (``ops/sepconv.py``): the input/output are PADDED-FLAT
    [N, (H+2)*Wp, C] and the BatchNorm affine + activations fuse into the
    kernel.  Param creation is identical either way, so a module's
    variables are interchangeable between paths (and with the keras
    importer).
    """

    features: int
    kernel_size: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    depth_multiplier: int = 1
    use_bias: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 fused_flat: Optional[dict] = None) -> jnp.ndarray:
        cin = x.shape[-1]
        kh, kw = self.kernel_size
        dw = self.param(
            "depthwise_kernel",
            nn.initializers.lecun_normal(),
            (kh, kw, cin, self.depth_multiplier))
        pw = self.param(
            "pointwise_kernel",
            nn.initializers.lecun_normal(),
            (1, 1, cin * self.depth_multiplier, self.features))
        if fused_flat is not None:
            assert (self.kernel_size == (3, 3)
                    and self.strides == (1, 1)
                    and self.padding == "SAME"
                    and self.depth_multiplier == 1
                    and not self.use_bias), \
                "fused path: 3x3/s1/SAME/mult1/nobias"
            from sparkdl_tpu.ops.sepconv import fused_sepconv_flat

            return fused_sepconv_flat(
                x, dw, pw, fused_flat["scale"], fused_flat["shift"],
                h=fused_flat["h"], w=fused_flat["w"],
                pre_relu=fused_flat.get("pre_relu", False),
                post_relu=fused_flat.get("post_relu", False),
                force=fused_flat.get("force"),
                row_tile=fused_flat.get("row_tile"))
        dtype = self.dtype or x.dtype
        import jax.lax as lax

        y = _depthwise_conv(x, dw, self.strides, self.padding, dtype)
        y = lax.conv_general_dilated(
            y, jnp.asarray(pw, dtype),
            window_strides=(1, 1),
            padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros, (self.features,))
            y = y + jnp.asarray(b, dtype)
        return y


class BNAffine(nn.Module):
    """Inference-mode twin of ``nn.BatchNorm``: declares the IDENTICAL
    variable tree (params scale/bias, batch_stats mean/var — same names,
    shapes, inits) but returns the folded affine ``(scale', shift')`` with
    scale' = gamma / sqrt(var + eps), shift' = beta - mean * scale',
    for fusion into a preceding conv's epilogue (ops/sepconv.py).  A model
    can therefore apply the same variables through either module."""

    epsilon: float = BN_EPS_DEFAULT
    use_scale: bool = True

    @nn.compact
    def __call__(self, features: int):
        mean = self.variable("batch_stats", "mean",
                             lambda: jnp.zeros((features,), jnp.float32))
        var = self.variable("batch_stats", "var",
                            lambda: jnp.ones((features,), jnp.float32))
        beta = self.param("bias", nn.initializers.zeros, (features,))
        if self.use_scale:
            gamma = self.param("scale", nn.initializers.ones, (features,))
        else:
            gamma = jnp.float32(1.0)
        s = (jnp.asarray(gamma, jnp.float32)
             / jnp.sqrt(jnp.asarray(var.value, jnp.float32) + self.epsilon))
        t = jnp.asarray(beta, jnp.float32) - \
            jnp.asarray(mean.value, jnp.float32) * s
        return s, t


class KernelParam(nn.Module):
    """Variable-tree twin of ``nn.Conv``: declares the identical
    ``kernel`` (and, with ``use_bias``, ``bias``) params — same names,
    shapes, inits — and returns them instead of convolving.  Lets a
    parent fuse several branch convs into one wider conv
    (models/inception.py fused heads, models/resnet.py fused shortcut)
    while keeping the per-branch variable tree interchangeable with the
    plain path."""

    shape: Tuple[int, ...]
    use_bias: bool = False
    # "depthwise_kernel" twins DepthwiseConv2D instead of nn.Conv
    param_name: str = "kernel"

    @nn.compact
    def __call__(self):
        kernel = self.param(self.param_name, nn.initializers.lecun_normal(),
                            self.shape)
        if not self.use_bias:
            return kernel
        bias = self.param("bias", nn.initializers.zeros,
                          (self.shape[-1],))
        return kernel, bias


def fold_bn_into_conv(kernel, scale, shift, bias=None):
    """Fold an inference-mode BN affine into conv constants:
    ``(conv(x, k) + b) * s + t == conv(x, k*s) + (b*s + t)`` (conv is
    linear).  Returns ``(K, B)`` — K cast back to the kernel's dtype so a
    bf16 program stays bf16 (fold math in f32), B in f32 for the caller
    to cast at the add.  Shared by every fused-conv path
    (models/inception.py fused heads, models/resnet.py fused shortcut)
    so precision/dtype fixes cannot diverge between them."""
    K = (kernel.astype(jnp.float32) * scale).astype(kernel.dtype)
    b = bias.astype(jnp.float32) if bias is not None else jnp.float32(0)
    return K, b * scale + shift


class DepthwiseConv2D(nn.Module):
    """Depthwise conv matching ``keras.layers.DepthwiseConv2D``.

    Param layout mirrors Keras (``depthwise_kernel`` [H,W,Cin,mult], the
    importer's ``depthconv`` kind); lowered as a grouped conv
    (feature_group_count=Cin)."""

    kernel_size: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    depth_multiplier: int = 1
    use_bias: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cin = x.shape[-1]
        kh, kw = self.kernel_size
        dw = self.param(
            "depthwise_kernel",
            nn.initializers.lecun_normal(),
            (kh, kw, cin, self.depth_multiplier))
        dtype = self.dtype or x.dtype
        y = _depthwise_conv(x, dw, self.strides, self.padding, dtype)
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros,
                           (cin * self.depth_multiplier,))
            y = y + jnp.asarray(b, dtype)
        return y


class SpaceToDepthConv(nn.Module):
    """Stride-``s`` VALID conv computed as space-to-depth + stride-1 conv.

    A stem conv reads a 3-channel input, occupying 3/128 MXU lanes; folding
    each s x s spatial block into channels multiplies lane occupancy by s^2
    while computing the *same* function: the kernel is zero-padded to a
    multiple of the stride and re-blocked so every original tap lands on
    the matching input pixel (the MLPerf-era TPU stem transform).  Declares
    the IDENTICAL ``kernel`` param as ``nn.Conv(use_bias=False)`` — same
    name, shape, and init — so a model can route the same variables through
    either path and weight import is unaffected.

    Only ``padding="VALID"`` with block == stride is supported (what
    InceptionV3's ``stem_conv1`` needs); odd input extents are zero-padded,
    which is exact because the padded taps multiply zero kernel rows.
    """

    features: int
    kernel_size: Tuple[int, int]
    strides: Tuple[int, int]

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        import jax.lax as lax

        kh, kw = self.kernel_size
        bh, bw = self.strides
        n, h, w, cin = x.shape
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (kh, kw, cin, self.features))
        dtype = x.dtype
        hp = -(-h // bh) * bh
        wp = -(-w // bw) * bw
        khp = -(-kh // bh) * bh
        kwp = -(-kw // bw) * bw
        xpad = jnp.pad(x, ((0, 0), (0, hp - h), (0, wp - w), (0, 0)))
        # [n, hp/bh, wp/bw, bh*bw*cin]: channel index (dy*bw + dx)*cin + c
        xs = xpad.reshape(n, hp // bh, bh, wp // bw, bw, cin).transpose(
            0, 1, 3, 2, 4, 5).reshape(n, hp // bh, wp // bw, bh * bw * cin)
        k4 = jnp.pad(jnp.asarray(kernel, dtype),
                     ((0, khp - kh), (0, kwp - kw), (0, 0), (0, 0)))
        # k2[by,bx,(dy*bw+dx)*cin+c,o] = k4[by*bh+dy, bx*bw+dx, c, o]
        k2 = k4.reshape(khp // bh, bh, kwp // bw, bw, cin, self.features
                        ).transpose(0, 2, 1, 3, 4, 5).reshape(
            khp // bh, kwp // bw, bh * bw * cin, self.features)
        out = lax.conv_general_dilated(
            xs, k2, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        # blocked VALID yields ceil(h/bh)-ceil(kh/bh)+1 rows; the reference
        # conv yields (h-kh)//bh + 1.  They differ (by one trailing row of
        # padded-tap output) when kh % bh == 0 and h % bh != 0 — slice to
        # the reference extent so parity holds for every supported config.
        oh = (h - kh) // bh + 1
        ow = (w - kw) // bw + 1
        return out[:, :oh, :ow, :]


class ConvBN(nn.Module):
    """``conv2d_bn`` from keras.applications.inception_v3: Conv(no bias) +
    BatchNorm(scale=False) + ReLU.

    ``s2d=True`` routes the conv through :class:`SpaceToDepthConv`
    (identical variables, identical math, better MXU occupancy for
    few-channel stems); requires VALID padding."""

    features: int
    kernel_size: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    bn_eps: float = BN_EPS_DEFAULT
    bn_scale: bool = False
    s2d: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False,
                 fold: bool = False):
        if fold:
            # declare the identical variable tree but return the folded
            # (kernel, bn_scale, bn_shift) for a parent-level fused conv
            # (inference only — models/inception.py fused heads)
            kh, kw = self.kernel_size
            kernel = KernelParam((kh, kw, x.shape[-1], self.features),
                                 name="conv")()
            s, t = BNAffine(epsilon=self.bn_eps, use_scale=self.bn_scale,
                            name="bn")(self.features)
            return kernel, s, t
        if self.s2d:
            assert self.padding == "VALID", "s2d requires VALID padding"
            x = SpaceToDepthConv(self.features, self.kernel_size,
                                 self.strides, name="conv")(x)
        else:
            x = nn.Conv(self.features, self.kernel_size,
                        strides=self.strides, padding=self.padding,
                        use_bias=False, name="conv")(x)
        x = nn.BatchNorm(use_running_average=not train,
                         momentum=BN_MOMENTUM_DEFAULT, epsilon=self.bn_eps,
                         use_scale=self.bn_scale, name="bn")(x)
        return nn.relu(x)


def max_pool_valid(x: jnp.ndarray, window: int, stride: int) -> jnp.ndarray:
    return nn.max_pool(x, (window, window), strides=(stride, stride),
                       padding="VALID")


def global_avg_pool(x: jnp.ndarray) -> jnp.ndarray:
    """GlobalAveragePooling2D — the featurizer cut of every non-VGG zoo
    model (DeepImageFeaturizer's penultimate-layer semantics)."""
    return jnp.mean(x, axis=(1, 2))
