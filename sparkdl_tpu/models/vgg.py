"""VGG16 / VGG19 as flax modules.

Zoo entries from the reference's ``SUPPORTED_MODELS`` registry
(``python/sparkdl/transformers/named_image.py``; Scala twin in
``src/main/scala/com/databricks/sparkdl/Models.scala``).  The reference's
``DeepImageFeaturizer`` cuts VGG at the penultimate fully-connected layer
(``fc2``, 4096-d) — exposed here via ``features=True``.

Submodule names match keras.applications.vgg16/vgg19 layer names exactly
("block1_conv1", ..., "fc1", "fc2", "predictions"), so the importer matches
weights by name.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

from sparkdl_tpu.models.layers import max_pool_valid

# convs per block: VGG16 = [2,2,3,3,3], VGG19 = [2,2,4,4,4]
_VGG16_BLOCKS: Tuple[int, ...] = (2, 2, 3, 3, 3)
_VGG19_BLOCKS: Tuple[int, ...] = (2, 2, 4, 4, 4)
_BLOCK_FILTERS: Tuple[int, ...] = (64, 128, 256, 512, 512)


class VGG(nn.Module):
    """Shared VGG backbone + classifier head."""

    blocks: Tuple[int, ...]
    num_classes: int = 1000

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False,
                 features: bool = False, logits: bool = False) -> jnp.ndarray:
        del train  # no BatchNorm / dropout-at-inference in classic VGG
        for b, (n_convs, filters) in enumerate(zip(self.blocks, _BLOCK_FILTERS), 1):
            for c in range(1, n_convs + 1):
                x = nn.Conv(filters, (3, 3), padding="SAME",
                            name=f"block{b}_conv{c}")(x)
                x = nn.relu(x)
            x = max_pool_valid(x, 2, 2)
        # Flatten in Keras' channel-last row-major order.
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, name="fc1")(x))
        x = nn.relu(nn.Dense(4096, name="fc2")(x))
        if features:
            return x  # 4096-d penultimate activations (featurizer cut)
        x = nn.Dense(self.num_classes, name="predictions")(x)
        if logits:
            return x
        return nn.softmax(x)


def VGG16(num_classes: int = 1000) -> VGG:
    return VGG(blocks=_VGG16_BLOCKS, num_classes=num_classes)


def VGG19(num_classes: int = 1000) -> VGG:
    return VGG(blocks=_VGG19_BLOCKS, num_classes=num_classes)
