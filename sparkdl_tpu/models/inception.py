"""InceptionV3 as a flax module — the north-star featurizer model
(BASELINE.json config #1; reference zoo entry in
``python/sparkdl/transformers/named_image.py — SUPPORTED_MODELS`` and
``src/main/scala/com/databricks/sparkdl/Models.scala``).

The architecture (94 conv+BN units, mixed0..mixed10) is declared ONCE as a
spec table; both the forward pass and the Keras weight-import order are
generated from it, so they cannot drift.  Import is order-matched because
upstream keras.applications leaves InceptionV3's conv/BN layers auto-named
(``conv2d_41``) — see ``models/keras_import.py``.

Keras semantics preserved: conv(no bias) + BN(scale=False, eps=1e-3) + relu;
avg-pool branches exclude padding from the denominator (TF AvgPool SAME
behavior); featurizer cut = global average pool (2048-d).
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from flax import linen as nn

from sparkdl_tpu.models.layers import ConvBN, global_avg_pool


class C(NamedTuple):
    """One conv2d_bn unit."""
    name: str
    filters: int
    kh: int
    kw: int
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"


class P(NamedTuple):
    """One pooling op."""
    kind: str  # "max" | "avg"
    window: int
    stride: int
    padding: str


Split = Tuple[str, list, list]               # ("split", ops_a, ops_b)
Op = Union[C, P, Split]
Block = Tuple[str, List[List[Op]]]           # ("mixed0", [branch_ops, ...])


def _c(name, f, kh, kw, s=1, p="SAME"):
    return C(name, f, kh, kw, (s, s), p)


def _mixed35(i: int, pool_filters: int) -> Block:
    n = f"mixed{i}"
    return (n, [
        [_c(f"{n}_b1x1", 64, 1, 1)],
        [_c(f"{n}_b5x5_1", 48, 1, 1), _c(f"{n}_b5x5_2", 64, 5, 5)],
        [_c(f"{n}_b3x3dbl_1", 64, 1, 1), _c(f"{n}_b3x3dbl_2", 96, 3, 3),
         _c(f"{n}_b3x3dbl_3", 96, 3, 3)],
        [P("avg", 3, 1, "SAME"), _c(f"{n}_bpool", pool_filters, 1, 1)],
    ])


def _mixed17(i: int, f: int) -> Block:
    n = f"mixed{i}"
    return (n, [
        [_c(f"{n}_b1x1", 192, 1, 1)],
        [_c(f"{n}_b7x7_1", f, 1, 1), _c(f"{n}_b7x7_2", f, 1, 7),
         _c(f"{n}_b7x7_3", 192, 7, 1)],
        [_c(f"{n}_b7x7dbl_1", f, 1, 1), _c(f"{n}_b7x7dbl_2", f, 7, 1),
         _c(f"{n}_b7x7dbl_3", f, 1, 7), _c(f"{n}_b7x7dbl_4", f, 7, 1),
         _c(f"{n}_b7x7dbl_5", 192, 1, 7)],
        [P("avg", 3, 1, "SAME"), _c(f"{n}_bpool", 192, 1, 1)],
    ])


def _mixed8x8(i: int) -> Block:
    n = f"mixed{i}"
    return (n, [
        [_c(f"{n}_b1x1", 320, 1, 1)],
        [_c(f"{n}_b3x3", 384, 1, 1),
         ("split",
          [_c(f"{n}_b3x3_1", 384, 1, 3)],
          [_c(f"{n}_b3x3_2", 384, 3, 1)])],
        [_c(f"{n}_b3x3dbl_1", 448, 1, 1), _c(f"{n}_b3x3dbl_2", 384, 3, 3),
         ("split",
          [_c(f"{n}_b3x3dbl_3", 384, 1, 3)],
          [_c(f"{n}_b3x3dbl_4", 384, 3, 1)])],
        [P("avg", 3, 1, "SAME"), _c(f"{n}_bpool", 192, 1, 1)],
    ])


# Full network in upstream source build order (keras inception_v3.py).
STEM: List[Op] = [
    _c("stem_conv1", 32, 3, 3, s=2, p="VALID"),
    _c("stem_conv2", 32, 3, 3, p="VALID"),
    _c("stem_conv3", 64, 3, 3),
    P("max", 3, 2, "VALID"),
    _c("stem_conv4", 80, 1, 1, p="VALID"),
    _c("stem_conv5", 192, 3, 3, p="VALID"),
    P("max", 3, 2, "VALID"),
]

BLOCKS: List[Block] = [
    _mixed35(0, 32),
    _mixed35(1, 64),
    _mixed35(2, 64),
    ("mixed3", [
        [_c("mixed3_b3x3", 384, 3, 3, s=2, p="VALID")],
        [_c("mixed3_b3x3dbl_1", 64, 1, 1), _c("mixed3_b3x3dbl_2", 96, 3, 3),
         _c("mixed3_b3x3dbl_3", 96, 3, 3, s=2, p="VALID")],
        [P("max", 3, 2, "VALID")],
    ]),
    _mixed17(4, 128),
    _mixed17(5, 160),
    _mixed17(6, 160),
    _mixed17(7, 192),
    ("mixed8", [
        [_c("mixed8_b3x3_1", 192, 1, 1),
         _c("mixed8_b3x3_2", 320, 3, 3, s=2, p="VALID")],
        [_c("mixed8_b7x7x3_1", 192, 1, 1), _c("mixed8_b7x7x3_2", 192, 1, 7),
         _c("mixed8_b7x7x3_3", 192, 7, 1),
         _c("mixed8_b7x7x3_4", 192, 3, 3, s=2, p="VALID")],
        [P("max", 3, 2, "VALID")],
    ]),
    _mixed8x8(9),
    _mixed8x8(10),
]


def _iter_convs(ops: Sequence[Op]):
    for op in ops:
        if isinstance(op, C):
            yield op
        elif isinstance(op, tuple) and op and op[0] == "split":
            yield from _iter_convs(op[1])
            yield from _iter_convs(op[2])


def inception_import_order():
    """(kind, flax_path) sequence in upstream creation order for the
    auto-named conv/BN layers.  Each conv2d_bn creates its Conv2D then its
    BatchNormalization, so per-kind creation order both equal spec order.
    (The final "predictions" Dense is explicitly named upstream and matches
    by name instead.)"""
    order = []
    convs = list(_iter_convs(STEM))
    for _, branches in BLOCKS:
        for branch in branches:
            convs.extend(_iter_convs(branch))
    for c in convs:
        order.append(("conv", (c.name, "conv")))
        order.append(("bn", (c.name, "bn")))
    return order


class InceptionV3(nn.Module):
    """``s2d_stem``: compute ``stem_conv1`` (3x3/s2/VALID on the 3-channel
    input — 3/128 MXU lane occupancy) via the space-to-depth transform
    (``layers.SpaceToDepthConv``): same variables, same math (allclose
    parity pinned in tests/test_models.py), different XLA program.  Off by
    default; the registry builder enables it when ``SPARKDL_S2D_STEM=1``.
    Measured delta on the bench is recorded in PERF.md.

    ``fused_heads``: at inference, the 2-3 LEADING 1x1 convs of each mixed
    block's branches (which all read the same block input) run as ONE
    wider conv — kernels concatenated along output channels, BN folded
    into the kernel/shift, one ReLU, then split.  Identical math and
    variables (``ConvBN(fold=True)`` declares the same tree); attacks the
    "many small matmuls" MFU story the round-4 profile documented (no
    single fusion >4% of device time).  None = on at inference; disable
    with ``SPARKDL_FUSED_HEADS=0`` (registry builder) for A/B runs."""

    num_classes: int = 1000
    s2d_stem: bool = False
    fused_heads: Optional[bool] = None

    def _use_fused_heads(self, train: bool) -> bool:
        if train:
            return False
        return True if self.fused_heads is None else self.fused_heads

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False,
                 features: bool = False, logits: bool = False) -> jnp.ndarray:
        fuse_heads = self._use_fused_heads(train)

        def pool(x, p: P):
            if p.kind == "max":
                return nn.max_pool(x, (p.window, p.window),
                                   strides=(p.stride, p.stride),
                                   padding=p.padding)
            # flax divides by f32 window counts under count_include_pad=
            # False, which would upcast a bf16 program — and every conv
            # downstream of the branch concat — to f32 (graftcheck GC002);
            # the cast is a no-op in the default f32 path
            return nn.avg_pool(x, (p.window, p.window),
                               strides=(p.stride, p.stride),
                               padding=p.padding,
                               count_include_pad=False).astype(x.dtype)

        def run(x, ops: Sequence[Op]):
            for op in ops:
                if isinstance(op, C):
                    x = ConvBN(op.filters, (op.kh, op.kw), strides=op.strides,
                               padding=op.padding, bn_eps=1e-3,
                               bn_scale=False,
                               s2d=(self.s2d_stem
                                    and op.name == "stem_conv1"),
                               name=op.name)(x, train=train)
                elif isinstance(op, P):
                    x = pool(x, op)
                else:  # split: apply both arms to x, concat results
                    a = run(x, op[1])
                    b = run(x, op[2])
                    x = jnp.concatenate([a, b], axis=-1)
            return x

        def run_block(x, branches):
            """One mixed block.  With fused heads, every branch whose
            first op is a stride-1 1x1 ConvBN is started by one combined
            conv over the shared block input; remaining ops run per
            branch from their split slice."""
            head_idx = [bi for bi, br in enumerate(branches)
                        if (isinstance(br[0], C) and br[0].kh == 1
                            and br[0].kw == 1 and br[0].strides == (1, 1))]
            starts = {}
            if fuse_heads and len(head_idx) >= 2:
                import jax.lax as lax

                parts = []
                for bi in head_idx:
                    c0 = branches[bi][0]
                    k, s, t = ConvBN(c0.filters, (1, 1), bn_eps=1e-3,
                                     bn_scale=False, name=c0.name)(
                        x, fold=True)
                    parts.append((c0.filters, k, s, t))
                # fold the BN scale into the kernel (conv is linear), keep
                # the conv in the variables' dtype (bf16 under the engine)
                from sparkdl_tpu.models.layers import fold_bn_into_conv

                folded = [fold_bn_into_conv(k, s, t)
                          for _, k, s, t in parts]
                kdt = folded[0][0].dtype
                K = jnp.concatenate([f[0] for f in folded], axis=-1)
                T = jnp.concatenate([f[1] for f in folded])
                y = lax.conv_general_dilated(
                    x.astype(kdt), K, (1, 1), "SAME",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))
                y = nn.relu(y + T.astype(y.dtype)).astype(x.dtype)
                off = 0
                for bi, (f, _, _, _) in zip(head_idx, parts):
                    starts[bi] = y[..., off:off + f]
                    off += f
            outs = []
            for bi, br in enumerate(branches):
                if bi in starts:
                    outs.append(run(starts[bi], br[1:]))
                else:
                    outs.append(run(x, br))
            return jnp.concatenate(outs, axis=-1)

        x = run(x, STEM)
        for _, branches in BLOCKS:
            x = run_block(x, branches)
        x = global_avg_pool(x)  # 2048-d featurizer cut
        if features:
            return x
        x = nn.Dense(self.num_classes, name="predictions")(x)
        if logits:
            return x
        return nn.softmax(x)
