"""ImageNet preprocessing as pure JAX functions, fused into the jit.

Replaces the preprocessing the reference splices in front of zoo models as TF
subgraphs (``python/sparkdl/graph/pieces.py — buildSpImageConverter`` plus the
per-model ``keras.applications.*.preprocess_input`` nodes composed in
``python/sparkdl/transformers/named_image.py — _buildTFGraphForName``).

TPU-first design: the host pipeline ships **uint8 RGB** batches (4x less
host->device traffic than float32); scaling / mean subtraction / channel
reordering happen on-device inside the same XLA program as the conv stack, so
they fuse with the first convolution's input handling and cost ~nothing.

Semantics match ``keras.applications.imagenet_utils.preprocess_input`` modes:
  * ``tf``     : x/127.5 - 1, RGB order          (InceptionV3, Xception, MobileNetV2)
  * ``caffe``  : RGB->BGR, subtract BGR ImageNet means, no scaling
                 (VGG16, VGG19, ResNet50)
  * ``torch``  : x/255 then per-channel ImageNet mean/std normalize, RGB
"""

from __future__ import annotations

import jax.numpy as jnp

# ImageNet channel statistics (identical constants to keras.applications).
_CAFFE_MEAN_BGR = (103.939, 116.779, 123.68)
_TORCH_MEAN_RGB = (0.485, 0.456, 0.406)
_TORCH_STD_RGB = (0.229, 0.224, 0.225)

PREPROCESS_MODES = ("tf", "caffe", "torch", "none")


def preprocess_tf(x: jnp.ndarray) -> jnp.ndarray:
    """[0,255] RGB -> [-1, 1]."""
    x = x.astype(jnp.float32)
    return x / 127.5 - 1.0


def preprocess_caffe(x: jnp.ndarray) -> jnp.ndarray:
    """[0,255] RGB -> zero-centered BGR (no scaling)."""
    x = x.astype(jnp.float32)
    x = x[..., ::-1]  # RGB -> BGR
    return x - jnp.asarray(_CAFFE_MEAN_BGR, dtype=jnp.float32)


def preprocess_torch(x: jnp.ndarray) -> jnp.ndarray:
    """[0,255] RGB -> normalized by ImageNet mean/std."""
    x = x.astype(jnp.float32) / 255.0
    mean = jnp.asarray(_TORCH_MEAN_RGB, dtype=jnp.float32)
    std = jnp.asarray(_TORCH_STD_RGB, dtype=jnp.float32)
    return (x - mean) / std


def preprocess_none(x: jnp.ndarray) -> jnp.ndarray:
    return x.astype(jnp.float32)


_MODES = {
    "tf": preprocess_tf,
    "caffe": preprocess_caffe,
    "torch": preprocess_torch,
    "none": preprocess_none,
}


def get_preprocess_fn(mode: str):
    try:
        return _MODES[mode]
    except KeyError:
        raise ValueError(
            f"Unknown preprocess mode {mode!r}; supported: {PREPROCESS_MODES}")
