"""Xception as a flax module.

Zoo entry from the reference's ``SUPPORTED_MODELS`` registry
(``python/sparkdl/transformers/named_image.py``).  Featurizer cut = global
average pool (2048-d).

Layer names mirror keras.applications.xception ("block1_conv1",
"block2_sepconv1", ..., "predictions"); the four residual-shortcut convs/BNs
are auto-named upstream, so they import by creation order — see
``xception_auto_order`` and ``models/keras_import.py``.  Separable convs are
bias-free depthwise+pointwise pairs lowered as grouped convs (MXU-friendly);
BN epsilon is the Keras default 1e-3.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from sparkdl_tpu.models.layers import SeparableConv2D, global_avg_pool

# (block index, filters) of the three entry-flow residual blocks.
_ENTRY_BLOCKS = ((2, 128), (3, 256), (4, 728))


def xception_auto_order():
    """Creation-order import targets for the auto-named shortcut layers."""
    order = []
    for i, _ in _ENTRY_BLOCKS:
        order.append(("conv", (f"shortcut{i}_conv",)))
        order.append(("bn", (f"shortcut{i}_bn",)))
    order.append(("conv", ("shortcut13_conv",)))
    order.append(("bn", ("shortcut13_bn",)))
    return order


class Xception(nn.Module):
    num_classes: int = 1000

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False,
                 features: bool = False, logits: bool = False) -> jnp.ndarray:

        def bn(name):
            return nn.BatchNorm(use_running_average=not train, momentum=0.99,
                                epsilon=1e-3, name=name)

        def sep(x, filters, name):
            x = SeparableConv2D(filters, (3, 3), use_bias=False, name=name)(x)
            return bn(f"{name}_bn")(x)

        # Entry flow: two plain convs (VALID, stride-2 first)
        x = nn.Conv(32, (3, 3), strides=(2, 2), padding="VALID",
                    use_bias=False, name="block1_conv1")(x)
        x = nn.relu(bn("block1_conv1_bn")(x))
        x = nn.Conv(64, (3, 3), padding="VALID", use_bias=False,
                    name="block1_conv2")(x)
        x = nn.relu(bn("block1_conv2_bn")(x))

        # Entry-flow residual blocks (block2 has no leading relu — upstream
        # quirk preserved)
        for i, f in _ENTRY_BLOCKS:
            residual = nn.Conv(f, (1, 1), strides=(2, 2), padding="SAME",
                               use_bias=False, name=f"shortcut{i}_conv")(x)
            residual = bn(f"shortcut{i}_bn")(residual)
            if i > 2:
                x = nn.relu(x)
            x = sep(x, f, f"block{i}_sepconv1")
            x = nn.relu(x)
            x = sep(x, f, f"block{i}_sepconv2")
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            x = x + residual

        # Middle flow: 8 identity blocks of three sepconvs
        for i in range(5, 13):
            residual = x
            for j in (1, 2, 3):
                x = nn.relu(x)
                x = sep(x, 728, f"block{i}_sepconv{j}")
            x = x + residual

        # Exit flow
        residual = nn.Conv(1024, (1, 1), strides=(2, 2), padding="SAME",
                           use_bias=False, name="shortcut13_conv")(x)
        residual = bn("shortcut13_bn")(residual)
        x = nn.relu(x)
        x = sep(x, 728, "block13_sepconv1")
        x = nn.relu(x)
        x = sep(x, 1024, "block13_sepconv2")
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = x + residual

        x = nn.relu(sep(x, 1536, "block14_sepconv1"))
        x = nn.relu(sep(x, 2048, "block14_sepconv2"))
        x = global_avg_pool(x)  # 2048-d featurizer cut
        if features:
            return x
        x = nn.Dense(self.num_classes, name="predictions")(x)
        if logits:
            return x
        return nn.softmax(x)
