"""Xception as a flax module.

Zoo entry from the reference's ``SUPPORTED_MODELS`` registry
(``python/sparkdl/transformers/named_image.py``).  Featurizer cut = global
average pool (2048-d).

Layer names mirror keras.applications.xception ("block1_conv1",
"block2_sepconv1", ..., "predictions"); the four residual-shortcut convs/BNs
are auto-named upstream, so they import by creation order — see
``xception_auto_order`` and ``models/keras_import.py``.  Separable convs are
bias-free depthwise+pointwise pairs lowered as grouped convs (MXU-friendly);
BN epsilon is the Keras default 1e-3.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import linen as nn

from sparkdl_tpu.models.layers import (BNAffine, SeparableConv2D,
                                       global_avg_pool)

# (block index, filters) of the three entry-flow residual blocks.
_ENTRY_BLOCKS = ((2, 128), (3, 256), (4, 728))


def xception_auto_order():
    """Creation-order import targets for the auto-named shortcut layers."""
    order = []
    for i, _ in _ENTRY_BLOCKS:
        order.append(("conv", (f"shortcut{i}_conv",)))
        order.append(("bn", (f"shortcut{i}_bn",)))
    order.append(("conv", ("shortcut13_conv",)))
    order.append(("bn", ("shortcut13_bn",)))
    return order


def _pick_row_tile(h: int, w: int, channels: int):
    """Row tile when the whole-image padded-flat working set would exceed
    VMEM; None = whole-image kernel.  Budget calibrated on hardware: 37^2
    x 728ch (1.14M position-channels, block4 at the native 299^2 input)
    compiles and runs; 74^2 x 256ch (1.56M) does not fit — so the
    threshold sits just above the known-good point and the decision
    scales with the actual block shape, not a block index (works for
    non-299 input sizes too)."""
    from sparkdl_tpu.ops.sepconv import flat_width

    if (h + 2) * flat_width(w) * channels <= 1_200_000:
        return None
    return 16


class Xception(nn.Module):
    """``fused_inference`` routes every separable conv through the pallas
    fused kernel (``ops/sepconv.py``) when not training: None = auto (on
    for single-device TPU backends), True = always (CPU falls back to the
    jax reference path — used by parity tests), False = never.  Both
    paths declare identical variables, so weights import/persist the same
    way regardless."""

    num_classes: int = 1000
    fused_inference: Optional[bool] = None
    # entry blocks 2-3 (147^2/74^2) through the ROW-TILED kernel
    # (ops/sepconv.py).  Measured round 5 and retired: whole-model -24%
    # (2341 vs 3086 img/s) — the pad/unflatten repacking around 2-layer
    # blocks dominates, and XLA's own sepconv lowering at 147^2 is within
    # 3% of the kernel per-layer (PERF.md "Row-tiled sepconv").  Kept
    # off-by-default behind SPARKDL_XC_TILED=1 with parity tests.
    tiled_entry: bool = False

    def _use_fused(self, train: bool) -> bool:
        if train:
            return False
        if self.fused_inference is not None:
            return self.fused_inference
        import jax

        from sparkdl_tpu.ops.sepconv import _on_tpu

        return _on_tpu() and jax.device_count() == 1

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False,
                 features: bool = False, logits: bool = False) -> jnp.ndarray:
        fused = self._use_fused(train)

        def bn(name):
            return nn.BatchNorm(use_running_average=not train, momentum=0.99,
                                epsilon=1e-3, name=name)

        def bn_act(x, name, relu=False):
            """Inference BN in fused mode folds to a precomputed affine
            (scale/shift derived in f32 from the running stats — BNAffine)
            applied in x's dtype.  vs nn.BatchNorm this keeps the folded
            constants at full precision even when the engine has cast all
            variables (incl. running var) to bf16, and keeps the epilogue
            a two-op elementwise chain in the activation dtype.  Identical
            variable tree either way."""
            if fused:
                s, t = BNAffine(epsilon=1e-3, name=name)(x.shape[-1])
                y = x * s.astype(x.dtype) + t.astype(x.dtype)
                if relu:
                    y = nn.relu(y)
                return y
            y = bn(name)(x)
            return nn.relu(y) if relu else y

        def sep(x, filters, name, pre_relu=False, post_relu=False,
                flat_hw=None, row_tile=None):
            """sepconv + BN (+ neighboring ReLUs).  When ``fused`` and a
            ``flat_hw`` is given, x is PADDED-FLAT [N,rows*Wp,C] and the
            whole stack runs as one pallas kernel (``row_tile`` selects
            the row-tiled variant for VMEM-oversized spatial shapes);
            otherwise the plain NHWC conv/BN modules run (XLA path)."""
            if fused and flat_hw is not None:
                s, t = BNAffine(epsilon=1e-3, name=f"{name}_bn")(filters)
                h, w = flat_hw
                return SeparableConv2D(filters, (3, 3), use_bias=False,
                                       name=name)(
                    x, fused_flat=dict(scale=s, shift=t, h=h, w=w,
                                       pre_relu=pre_relu,
                                       post_relu=post_relu,
                                       row_tile=row_tile))
            if pre_relu:
                x = nn.relu(x)
            x = SeparableConv2D(filters, (3, 3), use_bias=False, name=name)(x)
            x = bn_act(x, f"{name}_bn", relu=post_relu)
            return x

        if fused:
            from sparkdl_tpu.ops.sepconv import pad_to_flat, unflatten

        # Entry flow: two plain convs (VALID, stride-2 first)
        x = nn.Conv(32, (3, 3), strides=(2, 2), padding="VALID",
                    use_bias=False, name="block1_conv1")(x)
        x = bn_act(x, "block1_conv1_bn", relu=True)
        x = nn.Conv(64, (3, 3), padding="VALID", use_bias=False,
                    name="block1_conv2")(x)
        x = bn_act(x, "block1_conv2_bn", relu=True)

        # Entry-flow residual blocks (block2 has no leading relu — upstream
        # quirk preserved).  Fused mode routes ALL entry blocks through
        # the kernel: block4 (37x37) fits VMEM whole; blocks 2-3 (147/74
        # spatial — whose padded-flat working set exceeds VMEM) use the
        # ROW-TILED kernel generation (ops/sepconv.py — VERDICT r4 #1).
        for i, f in _ENTRY_BLOCKS:
            residual = nn.Conv(f, (1, 1), strides=(2, 2), padding="SAME",
                               use_bias=False, name=f"shortcut{i}_conv")(x)
            residual = bn_act(residual, f"shortcut{i}_bn")
            h, w = x.shape[1], x.shape[2]
            needs_tile = _pick_row_tile(h, w, max(x.shape[-1], f))
            use_flat = fused and (needs_tile is None or self.tiled_entry)
            if use_flat:
                xf = pad_to_flat(x, h, w, row_tile=needs_tile)
                xf = sep(xf, f, f"block{i}_sepconv1", pre_relu=i > 2,
                         flat_hw=(h, w), row_tile=needs_tile)
                xf = sep(xf, f, f"block{i}_sepconv2", pre_relu=True,
                         flat_hw=(h, w), row_tile=needs_tile)
                x = unflatten(xf, h, w)
            else:
                x = sep(x, f, f"block{i}_sepconv1", pre_relu=i > 2)
                x = sep(x, f, f"block{i}_sepconv2", pre_relu=True)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            x = x + residual

        # Middle flow: 8 identity blocks of three sepconvs.  In fused mode
        # the whole flow CHAINS in padded-flat layout — the kernel's output
        # halo contract means zero repacking passes between the 24 layers.
        mid_fits = _pick_row_tile(x.shape[1], x.shape[2], 728) is None
        if fused and mid_fits:
            h, w = x.shape[1], x.shape[2]
            xf = pad_to_flat(x, h, w)
            for i in range(5, 13):
                res_f = xf
                for j in (1, 2, 3):
                    xf = sep(xf, 728, f"block{i}_sepconv{j}", pre_relu=True,
                             flat_hw=(h, w))
                xf = xf + res_f
            x19 = unflatten(xf, h, w)
        else:
            for i in range(5, 13):
                residual = x
                for j in (1, 2, 3):
                    x = sep(x, 728, f"block{i}_sepconv{j}", pre_relu=True)
                x = x + residual
            x19 = x

        # Exit flow
        residual = nn.Conv(1024, (1, 1), strides=(2, 2), padding="SAME",
                           use_bias=False, name="shortcut13_conv")(x19)
        residual = bn_act(residual, "shortcut13_bn")
        h, w = x19.shape[1], x19.shape[2]
        if fused and mid_fits and _pick_row_tile(h, w, 1024) is None:
            xf = sep(xf, 728, "block13_sepconv1", pre_relu=True,
                     flat_hw=(h, w))
            xf = sep(xf, 1024, "block13_sepconv2", pre_relu=True,
                     flat_hw=(h, w))
            x = unflatten(xf, h, w)
        else:
            x = sep(x19, 728, "block13_sepconv1", pre_relu=True)
            x = sep(x, 1024, "block13_sepconv2", pre_relu=True)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = x + residual

        if fused and _pick_row_tile(x.shape[1], x.shape[2], 2048) is None:
            h = x.shape[1]
            xf = pad_to_flat(x, h, x.shape[2])
            xf = sep(xf, 1536, "block14_sepconv1", post_relu=True,
                     flat_hw=(h, x.shape[2]))
            xf = sep(xf, 2048, "block14_sepconv2", post_relu=True,
                     flat_hw=(h, x.shape[2]))
            x = unflatten(xf, h, x.shape[2])
        else:
            x = sep(x, 1536, "block14_sepconv1", post_relu=True)
            x = sep(x, 2048, "block14_sepconv2", post_relu=True)
        x = global_avg_pool(x)  # 2048-d featurizer cut
        if features:
            return x
        x = nn.Dense(self.num_classes, name="predictions")(x)
        if logits:
            return x
        return nn.softmax(x)
