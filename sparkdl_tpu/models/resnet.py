"""ResNet50 as a flax module.

Zoo entry from the reference's ``SUPPORTED_MODELS`` registry
(``python/sparkdl/transformers/named_image.py``).  Featurizer cut = global
average pool (2048-d), matching ``DeepImageFeaturizer``'s penultimate-layer
semantics.

Architecture and layer names mirror keras.applications ResNet50 (v1
bottleneck blocks, stride on the first 1x1 conv, BN epsilon 1.001e-5,
explicit 3-pad before the 7x7 stem conv) so the weight importer matches by
name: "conv1_conv", "conv2_block1_1_conv", ..., "predictions".
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from flax import linen as nn

from sparkdl_tpu.models.layers import global_avg_pool

BN_EPS = 1.001e-5
BN_MOMENTUM = 0.99


def _bn(name: str, train: bool) -> nn.BatchNorm:
    return nn.BatchNorm(use_running_average=not train, momentum=BN_MOMENTUM,
                        epsilon=BN_EPS, name=name)


class BottleneckBlock(nn.Module):
    """Keras ``residual_block_v1``: 1x1 -> 3x3 -> 1x1 with a (possibly
    projected) shortcut; stride lives on the first 1x1 conv (classic v1)."""

    filters: int
    stride: int = 1
    conv_shortcut: bool = True
    prefix: str = ""

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        p = self.prefix
        if self.conv_shortcut:
            shortcut = nn.Conv(4 * self.filters, (1, 1),
                               strides=(self.stride, self.stride),
                               name=f"{p}_0_conv")(x)
            shortcut = _bn(f"{p}_0_bn", train)(shortcut)
        else:
            shortcut = x
        y = nn.Conv(self.filters, (1, 1), strides=(self.stride, self.stride),
                    name=f"{p}_1_conv")(x)
        y = nn.relu(_bn(f"{p}_1_bn", train)(y))
        y = nn.Conv(self.filters, (3, 3), padding="SAME",
                    name=f"{p}_2_conv")(y)
        y = nn.relu(_bn(f"{p}_2_bn", train)(y))
        y = nn.Conv(4 * self.filters, (1, 1), name=f"{p}_3_conv")(y)
        y = _bn(f"{p}_3_bn", train)(y)
        return nn.relu(shortcut + y)


class ResNet50(nn.Module):
    num_classes: int = 1000
    # (filters, num_blocks, first_stride) per stage, keras stack order
    stages: Tuple[Tuple[int, int, int], ...] = (
        (64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2))

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False,
                 features: bool = False, logits: bool = False) -> jnp.ndarray:
        # Stem: explicit 3-pad + 7x7/2 VALID conv (keras "conv1_pad"+"conv1_conv")
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=((3, 3), (3, 3)),
                    name="conv1_conv")(x)
        x = nn.relu(_bn("conv1_bn", train)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage_idx, (filters, blocks, stride) in enumerate(self.stages, 2):
            for b in range(1, blocks + 1):
                x = BottleneckBlock(
                    filters=filters,
                    stride=stride if b == 1 else 1,
                    conv_shortcut=(b == 1),
                    prefix=f"conv{stage_idx}_block{b}",
                    name=f"conv{stage_idx}_block{b}")(x, train=train)
        x = global_avg_pool(x)  # 2048-d featurizer cut
        if features:
            return x
        x = nn.Dense(self.num_classes, name="predictions")(x)
        if logits:
            return x
        return nn.softmax(x)
