"""ResNet50 as a flax module.

Zoo entry from the reference's ``SUPPORTED_MODELS`` registry
(``python/sparkdl/transformers/named_image.py``).  Featurizer cut = global
average pool (2048-d), matching ``DeepImageFeaturizer``'s penultimate-layer
semantics.

Architecture and layer names mirror keras.applications ResNet50 (v1
bottleneck blocks, stride on the first 1x1 conv, BN epsilon 1.001e-5,
explicit 3-pad before the 7x7 stem conv) so the weight importer matches by
name: "conv1_conv", "conv2_block1_1_conv", ..., "predictions".
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from flax import linen as nn

from sparkdl_tpu.models.layers import global_avg_pool

BN_EPS = 1.001e-5
BN_MOMENTUM = 0.99


def _bn(name: str, train: bool) -> nn.BatchNorm:
    return nn.BatchNorm(use_running_average=not train, momentum=BN_MOMENTUM,
                        epsilon=BN_EPS, name=name)


class BottleneckBlock(nn.Module):
    """Keras ``residual_block_v1``: 1x1 -> 3x3 -> 1x1 with a (possibly
    projected) shortcut; stride lives on the first 1x1 conv (classic v1).

    ``fused_shortcut``: at inference, downsample blocks run the 1x1
    projection shortcut and the 1x1 reduce conv — which read the SAME
    input at the SAME stride — as ONE wider conv (kernels/biases
    concatenated along output channels, inference BN folded in), then
    split.  Identical math and variable tree (``KernelParam``/
    ``BNAffine`` twins — the pattern that bought +8.6% on InceptionV3's
    branch heads)."""

    filters: int
    stride: int = 1
    conv_shortcut: bool = True
    prefix: str = ""
    fused_shortcut: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        from sparkdl_tpu.models.layers import (BNAffine, KernelParam,
                                               fold_bn_into_conv)

        p = self.prefix
        f4 = 4 * self.filters
        if self.conv_shortcut and self.fused_shortcut and not train:
            cin = x.shape[-1]
            k0, b0 = KernelParam((1, 1, cin, f4), use_bias=True,
                                 name=f"{p}_0_conv")()
            s0, t0 = BNAffine(epsilon=BN_EPS, name=f"{p}_0_bn")(f4)
            k1, b1 = KernelParam((1, 1, cin, self.filters), use_bias=True,
                                 name=f"{p}_1_conv")()
            s1, t1 = BNAffine(epsilon=BN_EPS, name=f"{p}_1_bn")(
                self.filters)
            K0, B0 = fold_bn_into_conv(k0, s0, t0, bias=b0)
            K1, B1 = fold_bn_into_conv(k1, s1, t1, bias=b1)
            kdt = K0.dtype
            K = jnp.concatenate([K0, K1], axis=-1)
            B = jnp.concatenate([B0, B1])
            import jax.lax as lax

            z = lax.conv_general_dilated(
                x.astype(kdt), K, (self.stride, self.stride), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            z = (z + B.astype(z.dtype)).astype(x.dtype)
            shortcut = z[..., :f4]
            y = nn.relu(z[..., f4:])
        else:
            if self.conv_shortcut:
                shortcut = nn.Conv(f4, (1, 1),
                                   strides=(self.stride, self.stride),
                                   name=f"{p}_0_conv")(x)
                shortcut = _bn(f"{p}_0_bn", train)(shortcut)
            else:
                shortcut = x
            y = nn.Conv(self.filters, (1, 1),
                        strides=(self.stride, self.stride),
                        name=f"{p}_1_conv")(x)
            y = nn.relu(_bn(f"{p}_1_bn", train)(y))
        y = nn.Conv(self.filters, (3, 3), padding="SAME",
                    name=f"{p}_2_conv")(y)
        y = nn.relu(_bn(f"{p}_2_bn", train)(y))
        y = nn.Conv(4 * self.filters, (1, 1), name=f"{p}_3_conv")(y)
        y = _bn(f"{p}_3_bn", train)(y)
        return nn.relu(shortcut + y)


RESNET_STAGES = {
    # (filters, num_blocks, first_stride) per stage, keras stack order
    50: ((64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)),
    101: ((64, 3, 1), (128, 4, 2), (256, 23, 2), (512, 3, 2)),
    152: ((64, 3, 1), (128, 8, 2), (256, 36, 2), (512, 3, 2)),
}


class ResNet50(nn.Module):
    """Also parameterizes ResNet101/152 via ``stages`` (keras layer names
    are depth-independent — ``conv{stage}_block{b}_*`` — so the by-name
    weight importer covers the whole family)."""

    num_classes: int = 1000
    stages: Tuple[Tuple[int, int, int], ...] = RESNET_STAGES[50]
    # fuse each downsample block's shortcut+reduce 1x1s at inference
    # (BottleneckBlock docstring); OFF until measured on hardware —
    # enable with SPARKDL_RN_FUSED_SHORTCUT=1 (registry builder)
    fused_shortcut: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False,
                 features: bool = False, logits: bool = False) -> jnp.ndarray:
        # Stem: explicit 3-pad + 7x7/2 VALID conv (keras "conv1_pad"+"conv1_conv")
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=((3, 3), (3, 3)),
                    name="conv1_conv")(x)
        x = nn.relu(_bn("conv1_bn", train)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage_idx, (filters, blocks, stride) in enumerate(self.stages, 2):
            for b in range(1, blocks + 1):
                x = BottleneckBlock(
                    filters=filters,
                    stride=stride if b == 1 else 1,
                    conv_shortcut=(b == 1),
                    prefix=f"conv{stage_idx}_block{b}",
                    fused_shortcut=self.fused_shortcut,
                    name=f"conv{stage_idx}_block{b}")(x, train=train)
        x = global_avg_pool(x)  # 2048-d featurizer cut
        if features:
            return x
        x = nn.Dense(self.num_classes, name="predictions")(x)
        if logits:
            return x
        return nn.softmax(x)


def ResNet101(**kwargs) -> ResNet50:
    return ResNet50(stages=RESNET_STAGES[101], **kwargs)


def ResNet152(**kwargs) -> ResNet50:
    return ResNet50(stages=RESNET_STAGES[152], **kwargs)
