"""ImageNet top-K prediction decoding.

Counterpart of the reference's ``_decodeOutputAsPredictions``
(``python/sparkdl/transformers/named_image.py``), which delegated to
``keras.decode_predictions``.  We do the same when the ImageNet class-index
file is available (cached or downloadable), and degrade to stable synthetic
ids (``class_123``) in air-gapped environments instead of failing the job.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_CLASS_INDEX = None          # idx -> (synset_id, description)
_CLASS_INDEX_TRIED = False


def _load_class_index():
    global _CLASS_INDEX, _CLASS_INDEX_TRIED
    if _CLASS_INDEX_TRIED:
        return _CLASS_INDEX
    _CLASS_INDEX_TRIED = True
    try:
        import json

        from keras.utils import get_file

        path = get_file(
            "imagenet_class_index.json",
            "https://storage.googleapis.com/download.tensorflow.org/"
            "data/imagenet_class_index.json",
            cache_subdir="models")
        with open(path) as f:
            raw = json.load(f)
        _CLASS_INDEX = {int(k): (v[0], v[1]) for k, v in raw.items()}
    except Exception as e:
        logger.warning(
            "ImageNet class index unavailable (%s); topK decode will use "
            "synthetic class ids", e)
        _CLASS_INDEX = None
    return _CLASS_INDEX


def decode_predictions(probs: np.ndarray, top: int = 5
                       ) -> List[List[Tuple[str, str, float]]]:
    """[(class_id, description, probability) x top] per row, sorted
    descending — same row shape as keras ``decode_predictions``."""
    probs = np.asarray(probs)
    if probs.ndim != 2:
        raise ValueError(f"Expected [batch, classes] probabilities, got "
                         f"shape {probs.shape}")
    index = _load_class_index()
    out = []
    for row in probs:
        top_idx = np.argsort(row)[::-1][:top]
        decoded = []
        for i in top_idx:
            if index is not None and int(i) in index:
                cid, desc = index[int(i)]
            else:
                cid = desc = f"class_{int(i)}"
            decoded.append((cid, desc, float(row[i])))
        out.append(decoded)
    return out
