"""ImageNet top-K prediction decoding.

Counterpart of the reference's ``_decodeOutputAsPredictions``
(``python/sparkdl/transformers/named_image.py``), which delegated to
``keras.decode_predictions``.  We do the same when the ImageNet class-index
file is available (cached or downloadable), and degrade to stable synthetic
ids (``class_123``) in air-gapped environments instead of failing the job.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_CLASS_INDEX = None          # idx -> (synset_id, description)
_CLASS_INDEX_TRIED = False


def reset_class_index_cache():
    global _CLASS_INDEX, _CLASS_INDEX_TRIED
    _CLASS_INDEX = None
    _CLASS_INDEX_TRIED = False


def _class_index_candidates():
    """Air-gap-friendly resolution order for the class-index JSON:

    1. ``SPARKDL_CLASS_INDEX`` — explicit file path
    2. ``<package>/models/data/imagenet_class_index.json`` — vendored copy
       (drop the public 35 KB file here for fully offline deployments)
    3. ``$SPARKDL_WEIGHTS_DIR/imagenet_class_index.json`` — alongside the
       offline weight bundle
    4. the keras cache (``~/.keras/models/``) if a previous download exists
    """
    import os

    explicit = os.environ.get("SPARKDL_CLASS_INDEX")
    if explicit:
        yield explicit
    yield os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "data", "imagenet_class_index.json")
    wdir = os.environ.get("SPARKDL_WEIGHTS_DIR")
    if wdir:
        yield os.path.join(wdir, "imagenet_class_index.json")
    yield os.path.expanduser("~/.keras/models/imagenet_class_index.json")


def _parse_class_index(path):
    import json

    with open(path) as f:
        raw = json.load(f)
    return {int(k): (v[0], v[1]) for k, v in raw.items()}


def _load_class_index():
    global _CLASS_INDEX, _CLASS_INDEX_TRIED
    if _CLASS_INDEX_TRIED:
        return _CLASS_INDEX
    _CLASS_INDEX_TRIED = True
    import os

    for path in _class_index_candidates():
        if not os.path.isfile(path):
            continue
        try:
            _CLASS_INDEX = _parse_class_index(path)
            return _CLASS_INDEX
        except Exception as e:
            logger.warning("Bad class-index file %s (%s); trying next", path, e)
    try:  # last resort: download through the keras cache
        from keras.utils import get_file

        path = get_file(
            "imagenet_class_index.json",
            "https://storage.googleapis.com/download.tensorflow.org/"
            "data/imagenet_class_index.json",
            cache_subdir="models")
        _CLASS_INDEX = _parse_class_index(path)
    except Exception as e:
        logger.warning(
            "ImageNet class index unavailable (%s); topK decode will use "
            "synthetic class ids. Provide it offline via SPARKDL_CLASS_INDEX "
            "or the package data dir (see _class_index_candidates)", e)
        _CLASS_INDEX = None
    return _CLASS_INDEX


def decode_predictions(probs: np.ndarray, top: int = 5
                       ) -> List[List[Tuple[str, str, float]]]:
    """[(class_id, description, probability) x top] per row, sorted
    descending — same row shape as keras ``decode_predictions``."""
    probs = np.asarray(probs)
    if probs.ndim != 2:
        raise ValueError(f"Expected [batch, classes] probabilities, got "
                         f"shape {probs.shape}")
    index = _load_class_index()
    out = []
    for row in probs:
        top_idx = np.argsort(row)[::-1][:top]
        decoded = []
        for i in top_idx:
            if index is not None and int(i) in index:
                cid, desc = index[int(i)]
            else:
                cid = desc = f"class_{int(i)}"
            decoded.append((cid, desc, float(row[i])))
        out.append(decoded)
    return out
