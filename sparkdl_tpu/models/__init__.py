"""Pretrained-CNN zoo registry.

Replaces the reference's ``SUPPORTED_MODELS`` registry
(``python/sparkdl/transformers/named_image.py — SUPPORTED_MODELS``,
``_buildTFGraphForName``) and the Scala packaged-GraphDef registry
(``src/main/scala/com/databricks/sparkdl/Models.scala``): the same five
named models, but as flax modules compiled by XLA:TPU instead of frozen TF
GraphDefs run in per-executor sessions.

Each ``ModelSpec`` carries what the transformer layer needs: input size,
featurizer cut dimensionality, ImageNet preprocess mode, and a loader that
builds the keras.applications twin for weight import (pretrained weights when
the environment provides them, otherwise architecture-faithful random init).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from sparkdl_tpu.models.preprocess import get_preprocess_fn
from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass(frozen=True)
class ModelSpec:
    """One zoo entry: everything needed to featurize/predict with the model."""

    name: str
    module_builder: Callable[[], Any]          # () -> flax module
    input_size: Tuple[int, int]                # (height, width)
    feature_size: int                          # featurizer-cut dimensionality
    preprocess_mode: str                       # see models.preprocess
    keras_app: str                             # keras.applications attr name
    # () -> str tag when module_builder reads process env (e.g. the
    # InceptionV3 s2d-stem knob); caches keyed on the model name must fold
    # this tag in (model_variant_key) or they serve stale variants.
    variant_key_fn: Optional[Callable[[], str]] = None

    @property
    def preprocess(self):
        return get_preprocess_fn(self.preprocess_mode)

    def build(self):
        return self.module_builder()

    def init_variables(self, rng=None, dtype=np.float32) -> dict:
        """Architecture-shaped random variables (for tests / shape checks).

        jit-compiled: eager per-op dispatch of a 94-conv init is ~10x slower
        than one fused XLA program.
        """
        import jax

        module = self.build()
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        h, w = self.input_size
        dummy = np.zeros((1, h, w, 3), dtype=dtype)
        # graftlint: allow=SDL007 reason=one-shot init program; inputs are a PRNG key and a 1-row dummy, nothing worth donating
        init = jax.jit(lambda r, x: module.init(r, x, train=False))
        return jax.tree_util.tree_map(np.asarray, init(rng, dummy))

    def abstract_variables(self, dtype=np.float32) -> dict:
        """Shape/dtype-only variable pytree (``jax.ShapeDtypeStruct`` leaves)
        — free to build, enough for weight import to fill in."""
        import jax

        module = self.build()
        h, w = self.input_size
        dummy = jax.ShapeDtypeStruct((1, h, w, 3), dtype)
        return jax.eval_shape(
            lambda r, x: module.init(r, x, train=False),
            jax.random.PRNGKey(0), dummy)

    def resolve_weights(self, weights: Optional[str] = "imagenet"
                        ) -> Optional[str]:
        """Resolve the ``weights`` argument against the offline bundle.

        ``weights="imagenet"`` checks ``$SPARKDL_WEIGHTS_DIR`` for a local
        file first (air-gapped deployments — the analog of the reference's
        packaged, build-time-fetched GraphDefs in ``Models.scala``); an
        explicit path is returned as-is (and must exist)."""
        import os

        if weights is None:
            return None
        if weights != "imagenet":
            if not os.path.isfile(weights):
                raise FileNotFoundError(
                    f"weights file {weights!r} does not exist")
            return weights
        wdir = os.environ.get("SPARKDL_WEIGHTS_DIR")
        if wdir:
            stems = {self.name, self.name.lower(), self.keras_app,
                     self.keras_app.lower()}
            for stem in sorted(stems):
                for ext in (".weights.h5", ".h5", ".keras"):
                    cand = os.path.join(wdir, stem + ext)
                    if os.path.isfile(cand):
                        logger.info("Using offline weights %s", cand)
                        return cand
        return "imagenet"

    def keras_model(self, weights: Optional[str] = "imagenet"):
        """Build the keras.applications twin (CPU; used for weight import and
        as the parity oracle, mirroring the reference's test strategy).

        ``weights`` may be "imagenet" (keras download cache, with
        ``$SPARKDL_WEIGHTS_DIR`` consulted first), a ``.weights.h5`` file
        (loaded into the twin architecture), a full ``.h5``/``.keras`` model
        file, or None (random init)."""
        import keras

        builder = getattr(keras.applications, self.keras_app)
        resolved = self.resolve_weights(weights)
        if resolved is not None and resolved != "imagenet":
            if resolved.endswith(".weights.h5"):
                model = builder(weights=None)
                model.load_weights(resolved)
                return model
            return keras.saving.load_model(resolved)
        try:
            return builder(weights=resolved)
        except Exception as e:
            # Only the default imagenet download may degrade gracefully (no
            # network / no cache); an explicit user weight path must fail.
            if weights != "imagenet":
                raise
            logger.warning(
                "Could not load %s imagenet weights (%s); falling back to "
                "random initialization. For air-gapped use, point "
                "SPARKDL_WEIGHTS_DIR at a directory holding "
                "<model>.weights.h5 / .h5 / .keras files", self.name, e)
            return builder(weights=None)

class _Registry:
    def __init__(self):
        self._specs: Dict[str, ModelSpec] = {}
        self._auto_orders: Dict[str, Callable] = {}
        self._fixups: Dict[str, Callable] = {}

    def register(self, spec: ModelSpec, auto_order_fn=None,
                 import_fixup=None):
        self._specs[spec.name.lower()] = spec
        if auto_order_fn is not None:
            self._auto_orders[spec.name.lower()] = auto_order_fn
        if import_fixup is not None:
            self._fixups[spec.name.lower()] = import_fixup

    def get(self, name: str) -> ModelSpec:
        spec = self._specs.get(name.lower())
        if spec is None:
            raise ValueError(
                f"Unknown model {name!r}; supported: {self.names()}")
        return spec

    def auto_order_fn(self, name: str):
        return self._auto_orders.get(name.lower())

    def import_fixup(self, name: str):
        return self._fixups.get(name.lower())

    def names(self):
        return sorted(s.name for s in self._specs.values())


_registry = _Registry()


def _populate():
    from sparkdl_tpu.models.efficientnet import EfficientNetB0
    from sparkdl_tpu.models.inception import (InceptionV3,
                                              inception_import_order)
    from sparkdl_tpu.models.mobilenet import MobileNetV2
    from sparkdl_tpu.models.resnet import ResNet50, ResNet101, ResNet152
    from sparkdl_tpu.models.vgg import VGG16, VGG19
    from sparkdl_tpu.models.xception import Xception, xception_auto_order

    _registry.register(ModelSpec(
        name="VGG16", module_builder=VGG16, input_size=(224, 224),
        feature_size=4096, preprocess_mode="caffe", keras_app="VGG16"))
    _registry.register(ModelSpec(
        name="VGG19", module_builder=VGG19, input_size=(224, 224),
        feature_size=4096, preprocess_mode="caffe", keras_app="VGG19"))
    def _resnet_variant():
        # one helper for the whole family: a second ResNet knob must
        # change the tag for ResNet50/101/152 together (the InceptionV3
        # combined-tag lesson)
        return "fsc" if _rn_fused_shortcut_enabled() else ""

    # ResNet50 (reference) + deeper keras siblings (beyond the
    # reference's five): same module, deeper stage tables, same by-name
    # importer and knobs.  SPARKDL_RN_FUSED_SHORTCUT=1 fuses each
    # downsample block's shortcut+reduce 1x1 convs at inference
    # (resnet.py); off until measured on hardware.
    for _depth, _builder in ((50, ResNet50), (101, ResNet101),
                             (152, ResNet152)):
        _registry.register(ModelSpec(
            name=f"ResNet{_depth}",
            module_builder=(lambda b=_builder:
                            b(fused_shortcut=_rn_fused_shortcut_enabled())),
            input_size=(224, 224), feature_size=2048,
            preprocess_mode="caffe", keras_app=f"ResNet{_depth}",
            variant_key_fn=_resnet_variant))
    def _xception_builder():
        # SPARKDL_XC_TILED=1 routes entry blocks 2-3 through the
        # row-tiled pallas kernel — measured -24% whole-model, so the
        # default keeps them on XLA (xception.py tiled_entry / PERF.md)
        return Xception(tiled_entry=_xc_tiled_enabled())

    _registry.register(ModelSpec(
        name="Xception", module_builder=_xception_builder,
        input_size=(299, 299),
        feature_size=2048, preprocess_mode="tf", keras_app="Xception",
        variant_key_fn=lambda: "tiled" if _xc_tiled_enabled() else ""),
        xception_auto_order)
    def _inception_builder():
        # SPARKDL_S2D_STEM=1 computes stem_conv1 via space-to-depth
        # (identical variables/math, better MXU occupancy — inception.py);
        # SPARKDL_FUSED_HEADS=0 disables the branch-head conv fusion
        # (default: on at inference — inception.py fused_heads)
        return InceptionV3(s2d_stem=_s2d_stem_enabled(),
                           fused_heads=None if _fused_heads_enabled()
                           else False)

    def _inception_variant():
        tags = []
        if _s2d_stem_enabled():
            tags.append("s2d")
        if not _fused_heads_enabled():
            tags.append("nofh")
        return "+".join(tags)

    _registry.register(ModelSpec(
        name="InceptionV3", module_builder=_inception_builder,
        input_size=(299, 299),
        feature_size=2048, preprocess_mode="tf", keras_app="InceptionV3",
        variant_key_fn=_inception_variant),
        inception_import_order)
    # Beyond the reference's five: edge/efficiency-class backbones (see
    # mobilenet.py / efficientnet.py).
    def _mobilenet_builder():
        # SPARKDL_MNV2_FUSED=1 routes stride-1 inverted-residual tails
        # through the fused pallas kernel (mobilenet.py); off until
        # measured on hardware
        return MobileNetV2(fused_inference=_mnv2_fused_enabled())

    _registry.register(ModelSpec(
        name="MobileNetV2", module_builder=_mobilenet_builder,
        input_size=(224, 224), feature_size=1280, preprocess_mode="tf",
        keras_app="MobileNetV2",
        variant_key_fn=lambda: "fused" if _mnv2_fused_enabled() else ""))
    # The input Normalization layer is auto-named by keras ("normalization",
    # "normalization_1", ... per session build count), so it imports by
    # creation order as a fallback when the by-name match misses.
    from sparkdl_tpu.models.efficientnet import efficientnet_import_fixup

    _registry.register(ModelSpec(
        name="EfficientNetB0", module_builder=EfficientNetB0,
        input_size=(224, 224), feature_size=1280, preprocess_mode="none",
        keras_app="EfficientNetB0"),
        lambda: [("norm", ("normalization",))],
        import_fixup=efficientnet_import_fixup)


_populate()

SUPPORTED_MODELS = _registry.names()


def get_model_spec(name: str) -> ModelSpec:
    return _registry.get(name)


def _env_flag(name: str, default: bool) -> bool:
    """Truthy env knob: unset or empty -> ``default``; "0"/"false" (any
    case) -> False; anything else -> True."""
    import os

    raw = os.environ.get(name, "").lower()
    if raw == "":
        return default
    return raw not in ("0", "false")


def _s2d_stem_enabled() -> bool:
    return _env_flag("SPARKDL_S2D_STEM", False)


def _fused_heads_enabled() -> bool:
    return _env_flag("SPARKDL_FUSED_HEADS", True)


def _xc_tiled_enabled() -> bool:
    return _env_flag("SPARKDL_XC_TILED", False)


def _rn_fused_shortcut_enabled() -> bool:
    return _env_flag("SPARKDL_RN_FUSED_SHORTCUT", False)


def _mnv2_fused_enabled() -> bool:
    return _env_flag("SPARKDL_MNV2_FUSED", False)


def model_variant_key(name: str) -> str:
    """Environment-dependent build-variant tag for ``name``.

    When a spec's ``module_builder`` reads process env (today:
    ``SPARKDL_S2D_STEM`` for InceptionV3, via its ``variant_key_fn``), a
    cache keyed on the model name alone would keep serving the
    previously-built variant after the env var is toggled.  Cache owners
    must include this tag in their keys.
    """
    spec = _registry.get(name)
    return spec.variant_key_fn() if spec.variant_key_fn is not None else ""


def import_keras_weights(name: str, keras_model, variables: dict) -> dict:
    """Import a keras.applications model's weights into flax variables
    (by-name where upstream names are stable, by-creation-order for
    upstream's auto-named layers)."""
    from sparkdl_tpu.models import keras_import

    _registry.get(name)  # validate
    auto_order_fn = _registry.auto_order_fn(name)
    variables = keras_import.import_weights(
        keras_model, variables,
        auto_order=auto_order_fn() if auto_order_fn else None)
    fixup = _registry.import_fixup(name)
    if fixup is not None:
        # model-specific post-import hook for weightless keras layers the
        # importer cannot see (e.g. EfficientNet's imagenet-only Rescaling)
        variables = fixup(keras_model, variables)
    return variables


def load_model(name: str, weights: Optional[str] = "imagenet"):
    """Build (module, variables) for a zoo model, importing Keras weights.

    The TPU-native analog of the reference's ``_buildTFGraphForName``.
    """
    import jax

    spec = _registry.get(name)
    module = spec.build()
    # Shape-only template: every leaf must be filled by the import (a full
    # random init would be overwritten anyway and costs an XLA compile).
    variables = spec.abstract_variables()
    keras_model = spec.keras_model(weights=weights)
    variables = import_keras_weights(name, keras_model, variables)
    abstract = [
        "/".join(str(k) for k in path)
        for path, leaf in jax.tree_util.tree_flatten_with_path(variables)[0]
        if isinstance(leaf, jax.ShapeDtypeStruct)]
    if abstract:
        raise ValueError(
            f"Import left {len(abstract)} uninitialized leaves: {abstract[:5]}")
    return module, variables
