"""MobileNetV2 as a flax module — a zoo extension BEYOND the reference.

The reference's ``SUPPORTED_MODELS`` stops at five architectures
(``python/sparkdl/transformers/named_image.py``); MobileNetV2 (alpha=1.0,
224x224) is added because edge-class backbones are the common "cheap
featurizer" ask the reference never served.  Featurizer cut = global
average pool (1280-d).

Layer names mirror ``keras.applications.MobileNetV2`` exactly ("Conv1",
"bn_Conv1", "expanded_conv_depthwise", "block_1_expand", ..., "Conv_1",
"predictions"), so weight import matches entirely BY NAME (no
creation-order table needed).  Keras's stride-2 stages zero-pad
((0,1),(0,1)) then convolve VALID; reproduced verbatim so spatial parity
is exact.  BN epsilon 1e-3 (the keras app overrides the layer default).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from sparkdl_tpu.models.layers import DepthwiseConv2D, global_avg_pool

# (expansion t, out channels c, repeats n, first stride s) — table 2 of the
# MobileNetV2 paper, alpha=1.0.
_BLOCKS = ((1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
           (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1))


def _relu6(x):
    return jnp.minimum(nn.relu(x), 6.0)


def _pad_correct(x):
    """Keras ``ZeroPadding2D(((0,1),(0,1)))`` before stride-2 VALID convs."""
    return jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))


class MobileNetV2(nn.Module):
    """``fused_inference``: stride-1 inverted-residual blocks run their
    depthwise+BN+relu6+project+BN tail as ONE pallas kernel
    (``ops/sepconv.py fused_mbconv_flat``) with the expand conv as a
    masked matmul in the same PADDED-FLAT layout, so whole stride-1
    stages chain with zero repacking (the Xception middle-flow pattern,
    which measured +12%).  Identical math and variable tree
    (KernelParam/BNAffine twins).  OFF by default until measured —
    enable with ``SPARKDL_MNV2_FUSED=1`` (registry builder)."""

    num_classes: int = 1000
    fused_inference: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False,
                 features: bool = False, logits: bool = False) -> jnp.ndarray:
        fused = self.fused_inference and not train

        def bn(name):
            return nn.BatchNorm(use_running_average=not train,
                                momentum=0.999, epsilon=1e-3, name=name)

        # Stem: pad-correct + 3x3 s2 VALID
        x = _pad_correct(x)
        x = nn.Conv(32, (3, 3), strides=(2, 2), padding="VALID",
                    use_bias=False, name="Conv1")(x)
        x = _relu6(bn("bn_Conv1")(x))

        if fused:
            from sparkdl_tpu.models.layers import (BNAffine, KernelParam,
                                                   fold_bn_into_conv)
            from sparkdl_tpu.ops.sepconv import (fused_mbconv_flat,
                                                 halo_mask, pad_to_flat,
                                                 unflatten)

        xf = None  # padded-flat state for a run of stride-1 blocks
        block_id = 0
        for t, c, n, s in _BLOCKS:
            for i in range(n):
                stride = s if i == 0 else 1
                prefix = ("expanded_conv" if block_id == 0
                          else f"block_{block_id}")
                if fused and stride == 1:
                    if xf is None:
                        h, w = x.shape[1], x.shape[2]
                        work_dt = x.dtype
                        xf = pad_to_flat(x, h, w)
                        mask = halo_mask(h, w)
                    cin = xf.shape[-1]
                    inp_f = xf
                    if t != 1:
                        ke = KernelParam((1, 1, cin, cin * t),
                                         name=f"{prefix}_expand")()
                        se, te = BNAffine(epsilon=1e-3,
                                          name=f"{prefix}_expand_BN")(
                            cin * t)
                        Ke, Be = fold_bn_into_conv(ke, se, te)
                        y = xf.astype(Ke.dtype) @ Ke.reshape(cin, cin * t)
                        y = (jnp.clip(y + Be.astype(y.dtype), 0.0, 6.0)
                             * mask.astype(y.dtype))
                    else:
                        y = xf
                    cdw = y.shape[-1]
                    kd = KernelParam((3, 3, cdw, 1),
                                     param_name="depthwise_kernel",
                                     name=f"{prefix}_depthwise")()
                    sd, td = BNAffine(epsilon=1e-3,
                                      name=f"{prefix}_depthwise_BN")(cdw)
                    Kd, Bd = fold_bn_into_conv(kd.reshape(3, 3, cdw),
                                               sd, td)
                    kp = KernelParam((1, 1, cdw, c),
                                     name=f"{prefix}_project")()
                    sp, tp = BNAffine(epsilon=1e-3,
                                      name=f"{prefix}_project_BN")(c)
                    Kp, Bp = fold_bn_into_conv(kp, sp, tp)
                    yf = fused_mbconv_flat(y, Kd, Kp.reshape(cdw, c),
                                           Bd, Bp, h, w).astype(work_dt)
                    xf = yf + inp_f if cin == c else yf
                    block_id += 1
                    continue
                if xf is not None:  # leaving a flat run (stride-2 block)
                    x = unflatten(xf, h, w)
                    xf = None
                cin = x.shape[-1]
                inp = x
                if t != 1:
                    x = nn.Conv(cin * t, (1, 1), use_bias=False,
                                name=f"{prefix}_expand")(x)
                    x = _relu6(bn(f"{prefix}_expand_BN")(x))
                if stride == 2:
                    x = _pad_correct(x)
                x = DepthwiseConv2D(
                    (3, 3), strides=(stride, stride),
                    padding="SAME" if stride == 1 else "VALID",
                    use_bias=False, name=f"{prefix}_depthwise")(x)
                x = _relu6(bn(f"{prefix}_depthwise_BN")(x))
                x = nn.Conv(c, (1, 1), use_bias=False,
                            name=f"{prefix}_project")(x)
                x = bn(f"{prefix}_project_BN")(x)  # linear bottleneck
                if stride == 1 and cin == c:
                    x = x + inp
                block_id += 1
        if xf is not None:
            x = unflatten(xf, h, w)
            xf = None

        x = nn.Conv(1280, (1, 1), use_bias=False, name="Conv_1")(x)
        x = _relu6(bn("Conv_1_bn")(x))
        x = global_avg_pool(x)  # 1280-d featurizer cut
        if features:
            return x
        x = nn.Dense(self.num_classes, name="predictions")(x)
        if logits:
            return x
        return nn.softmax(x)
