"""Keras -> flax weight import.

Replaces the reference's weight-delivery machinery: Keras HDF5 loading in
``python/sparkdl/transformers/keras_utils.py`` / ``keras_image.py`` and the
packaged frozen GraphDefs of ``src/main/scala/com/databricks/sparkdl/
Models.scala``.  Here pretrained/user Keras weights become flax variable
pytrees that feed the jit-compiled TPU path.

Matching strategies:
  * **by name** (VGG/ResNet/Xception — keras.applications assigns stable
    explicit layer names): each weighted Keras layer maps to the subtree of
    the flax variables whose module name equals the layer name.
  * **by build order** (InceptionV3 — upstream layers are auto-named
    ``conv2d_42`` with session-global counters): weighted layers are sorted
    by their creation counter (recoverable from the auto-name suffix) and
    paired with an explicitly declared flax-path order.

Conversion is layout-transpose-free: Keras and flax both use HWIO conv
kernels and (in, out) dense kernels in NHWC.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

Path = Tuple[str, ...]

# Keras layer classes that carry importable weights, -> handler key.
_WEIGHTED = {
    "Conv2D": "conv",
    "Dense": "dense",
    "BatchNormalization": "bn",
    "SeparableConv2D": "sepconv",
    "DepthwiseConv2D": "depthconv",
    # keras.layers.Normalization (EfficientNet's in-model input pipeline):
    # weights are [mean, variance, count]; count is bookkeeping, dropped.
    "Normalization": "norm",
}


def _tree_paths(tree: Any, prefix: Path = ()) -> Dict[Path, Any]:
    out: Dict[Path, Any] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_tree_paths(v, prefix + (k,)))
    else:
        out[prefix] = tree
    return out


def _module_paths(tree: Any, prefix: Path = ()) -> Dict[str, Path]:
    """Map each module name (dict key) to its full path; innermost wins on
    duplicates only if names collide, which keras.applications avoids."""
    out: Dict[str, Path] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            p = prefix + (k,)
            if isinstance(v, dict):
                out.setdefault(k, p)
                out.update(_module_paths(v, p))
    return out


def _set_in(tree: dict, path: Path, leaf_name: str, value: np.ndarray) -> None:
    node = tree
    for k in path:
        node = node[k]
    if leaf_name not in node:
        raise KeyError(f"No leaf {leaf_name!r} under {'/'.join(path)}")
    old = node[leaf_name]  # concrete array or jax.ShapeDtypeStruct
    if tuple(old.shape) != tuple(value.shape):
        raise ValueError(
            f"Shape mismatch importing {'/'.join(path)}/{leaf_name}: "
            f"flax {tuple(old.shape)} vs keras {tuple(value.shape)}")
    node[leaf_name] = value.astype(old.dtype)


def _split_bn_weights(layer, weights: List[np.ndarray]):
    """Keras BN weight order: [gamma if scale][beta if center][mean, var]."""
    scale = bool(getattr(layer, "scale", True))
    center = bool(getattr(layer, "center", True))
    idx = 0
    gamma = beta = None
    if scale:
        gamma = weights[idx]; idx += 1
    if center:
        beta = weights[idx]; idx += 1
    mean, var = weights[idx], weights[idx + 1]
    return gamma, beta, mean, var


def _assign(variables: dict, path: Path, kind: str, layer, weights) -> None:
    params, stats = variables["params"], variables.get("batch_stats", {})
    if kind == "bn":
        gamma, beta, mean, var = _split_bn_weights(layer, weights)
        if gamma is not None:
            _set_in(params, path, "scale", gamma)
        if beta is not None:
            _set_in(params, path, "bias", beta)
        _set_in(stats, path, "mean", mean)
        _set_in(stats, path, "var", var)
    elif kind in ("conv", "dense"):
        _set_in(params, path, "kernel", weights[0])
        if len(weights) > 1:
            _set_in(params, path, "bias", weights[1])
    elif kind == "sepconv":
        _set_in(params, path, "depthwise_kernel", weights[0])
        _set_in(params, path, "pointwise_kernel", weights[1])
        if len(weights) > 2:
            _set_in(params, path, "bias", weights[2])
    elif kind == "depthconv":
        _set_in(params, path, "depthwise_kernel", weights[0])
        if len(weights) > 1:
            _set_in(params, path, "bias", weights[1])
    elif kind == "norm":
        mean = np.asarray(weights[0]).reshape(-1)
        _set_in(stats, path, "mean", mean)
        _set_in(stats, path, "var", np.asarray(weights[1]).reshape(-1))
        node = stats
        for k in path:
            node = node[k]
        if "post_scale" in node:
            # default the weightless post-Rescaling correction to identity;
            # a model-specific import fixup overwrites it when the keras
            # build carries the extra layer (EfficientNet imagenet builds)
            _set_in(stats, path, "post_scale",
                    np.ones_like(mean, dtype=np.float32))
    else:  # pragma: no cover
        raise ValueError(f"Unknown weight kind {kind!r}")


def weighted_layers(keras_model) -> List[Tuple[str, str, Any, List[np.ndarray]]]:
    """All (name, kind, layer, weights) entries of the model that carry
    weights, in ``model.layers`` order.  Weights are fetched once here
    (``get_weights`` copies ~100MB for ResNet50; don't do it twice)."""
    out = []
    for layer in keras_model.layers:
        kind = _WEIGHTED.get(type(layer).__name__)
        if kind:
            weights = layer.get_weights()
            if weights:
                out.append((layer.name, kind, layer, weights))
    return out


_AUTO_SUFFIX = re.compile(r"^(.*?)(?:_(\d+))?$")


def _creation_counter(name: str) -> int:
    m = _AUTO_SUFFIX.match(name)
    return int(m.group(2)) if m.group(2) else -1


def import_weights(keras_model, variables: dict,
                   auto_order: Optional[Sequence[Tuple[str, Path]]] = None,
                   rename: Optional[Dict[str, str]] = None) -> dict:
    """Import weights from ``keras_model`` into a copy of ``variables``.

    Layers whose Keras name equals a flax module name match **by name**.
    Remaining (auto-named) layers match **by creation order**: Keras
    auto-names embed a session-global creation counter (``conv2d``,
    ``conv2d_7``, ...), so per-kind creation order is recovered by sorting on
    the counter and pairing with ``auto_order``'s (kind, flax_path) entries —
    valid regardless of how many models the session created before.
    """
    import jax

    def _as_numpy(leaf):
        # Abstract (ShapeDtypeStruct) leaves pass through: they only provide
        # shape/dtype for validation and are overwritten by the import.
        return leaf if isinstance(leaf, jax.ShapeDtypeStruct) else np.asarray(leaf)

    variables = jax.tree_util.tree_map(_as_numpy, dict(variables))
    modules = _module_paths(variables["params"])
    for name, path in _module_paths(variables.get("batch_stats", {})).items():
        modules.setdefault(name, path)
    rename = rename or {}
    unmatched: List[Tuple[str, str, Any, Any]] = []
    for name, kind, layer, weights in weighted_layers(keras_model):
        target = rename.get(name, name)
        path = modules.get(target)
        if path is None:
            unmatched.append((name, kind, layer, weights))
            continue
        _assign(variables, path, kind, layer, weights)
    if not unmatched:
        # auto_order may be a FALLBACK for layers keras sometimes
        # auto-suffixes ("normalization" vs "normalization_1" depending on
        # how many models the session built): when every layer matched by
        # name this round, the fallback simply wasn't needed.
        return variables
    if auto_order is None:
        raise KeyError(
            f"No flax module found for keras layers "
            f"{[n for n, _, _, _ in unmatched]} and no auto_order provided")
    by_kind: Dict[str, List[Tuple[str, Any, Any]]] = {}
    for name, kind, layer, weights in unmatched:
        by_kind.setdefault(kind, []).append((name, layer, weights))
    for kind in by_kind:
        by_kind[kind].sort(key=lambda nlw: _creation_counter(nlw[0]))
    cursors = {k: 0 for k in by_kind}
    for kind, path in auto_order:
        entries = by_kind.get(kind, [])
        i = cursors.get(kind, 0)
        if i >= len(entries):
            raise ValueError(
                f"Keras model has only {len(entries)} unmatched {kind!r} "
                f"layers; auto_order asks for more (at {'/'.join(path)})")
        _, layer, weights = entries[i]
        cursors[kind] = i + 1
        _assign(variables, path, kind, layer, weights)
    leftover = {k: len(v) - cursors.get(k, 0)
                for k, v in by_kind.items() if len(v) != cursors.get(k, 0)}
    if leftover:
        raise ValueError(f"Unconsumed keras weighted layers by kind: {leftover}")
    return variables
