"""sparkdl_tpu — TPU-native Deep Learning Pipelines.

A brand-new JAX/XLA framework with the capabilities of the reference
``kailuowang/spark-deep-learning`` ("Deep Learning Pipelines"): image
DataFrames, transfer learning via pretrained-CNN featurization, batch
inference at scale, model deployment as vectorized UDFs, and distributed
hyperparameter tuning — re-designed TPU-first (jit/shard_map over a device
mesh instead of per-executor TF sessions; XLA collectives instead of
Spark broadcast; Arrow batches instead of Spark partitions).

Public surface mirrors the reference's ``python/sparkdl/__init__.py``.
"""

from __future__ import annotations

__version__ = "0.1.0"

# Public API (lazy where the submodule pulls in heavyweight deps, so that
# `import sparkdl_tpu` stays fast and works before jax initializes a device).
_LAZY = {
    # image / frame layer
    "imageIO": "sparkdl_tpu.image",
    "ImageSchema": "sparkdl_tpu.image",
    "readImages": "sparkdl_tpu.image",
    "DataFrame": "sparkdl_tpu.frame",
    "Row": "sparkdl_tpu.frame",
    # transformers
    "DeepImageFeaturizer": "sparkdl_tpu.transformers.named_image",
    "DeepImagePredictor": "sparkdl_tpu.transformers.named_image",
    "TFImageTransformer": "sparkdl_tpu.transformers.named_image",
    "KerasImageFileTransformer": "sparkdl_tpu.transformers.image_file",
    "ImageFileTransformer": "sparkdl_tpu.transformers.image_file",
    "KerasTransformer": "sparkdl_tpu.transformers.tensor",
    "ModelTransformer": "sparkdl_tpu.transformers.tensor",
    "TFTransformer": "sparkdl_tpu.transformers.tensor",
    # graph layer
    "ModelFunction": "sparkdl_tpu.graph.function",
    "TFInputGraph": "sparkdl_tpu.graph.input",
    "ModelInput": "sparkdl_tpu.graph.input",
    # estimators / tuning
    "KerasImageFileEstimator": "sparkdl_tpu.estimators.image_file_estimator",
    "ImageFileEstimator": "sparkdl_tpu.estimators.image_file_estimator",
    "ParamGridBuilder": "sparkdl_tpu.estimators.tuning",
    "CrossValidator": "sparkdl_tpu.estimators.tuning",
    # udf
    "registerKerasImageUDF": "sparkdl_tpu.udf",
    "register_image_udf": "sparkdl_tpu.udf",
    # serving (online inference layer; "serving" exposes the module itself)
    "serving": "sparkdl_tpu.serving",
    "Server": "sparkdl_tpu.serving",
    "InferenceCache": "sparkdl_tpu.serving",
    # streaming (exactly-once continuous scoring; module itself + the
    # runner, mirroring the serving pair above)
    "streaming": "sparkdl_tpu.streaming",
    "StreamScorer": "sparkdl_tpu.streaming",
}

# Only advertise names whose modules actually exist, so `import *` works at
# every stage of the build-out (layers land incrementally).  Existence is
# checked on the filesystem, NOT via find_spec: find_spec imports parent
# packages, which would defeat the lazy-import design above.
import os as _os

_PKG_DIR = _os.path.dirname(__file__)


def _module_exists(mod: str) -> bool:
    rel = mod.split(".")[1:]  # drop leading "sparkdl_tpu"
    base = _os.path.join(_PKG_DIR, *rel)
    return _os.path.isfile(base + ".py") or _os.path.isfile(
        _os.path.join(base, "__init__.py"))


__all__ = sorted(
    n for n, m in _LAZY.items() if _module_exists(m)
) + ["__version__"]


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module 'sparkdl_tpu' has no attribute {name!r}")
    import importlib

    try:
        mod = importlib.import_module(target)
    except ModuleNotFoundError as e:
        raise AttributeError(
            f"sparkdl_tpu.{name} is declared in the public API but its "
            f"module {target!r} is unavailable: {e}") from e
    # "imageIO"/"serving"/"streaming" expose the module itself (parity
    # with `from sparkdl import imageIO`; `from sparkdl_tpu import
    # serving`)
    obj = mod if name in ("imageIO", "serving", "streaming") else getattr(
        mod, name)
    globals()[name] = obj
    return obj
