"""sparkdl_tpu.obs — span tracing, metrics export, and slow-request
exemplars for the scoring and serving stack.

The observability layer SURVEY.md §5 found missing from the reference
(Spark UI only): every request/batch carries a trace, every stage emits
spans, and every run can export a machine-readable record.

* :mod:`~sparkdl_tpu.obs.trace` — :class:`Tracer` / spans / the
  ``SPARKDL_TRACE=0|1|dir`` gate (disabled path near-zero cost).
* :mod:`~sparkdl_tpu.obs.export` — Chrome trace-event JSON (Perfetto /
  chrome://tracing), Prometheus text exposition, and JSONL snapshots of
  the :class:`~sparkdl_tpu.utils.metrics.Metrics` registry.
* :mod:`~sparkdl_tpu.obs.exemplar` — top-K slowest request span trees,
  surfaced by ``Server.varz()``.

Instrumented surfaces: ``serving.Server``/``DynamicBatcher`` (request +
micro-batch spans), ``parallel.engine.InferenceEngine`` (call/dispatch
spans), ``parallel.pipeline.PipelinedRunner`` (per-stage spans with
``block_until_ready``-bracketed device time),
``streaming.StreamScorer`` (``stream.run``/``stream.chunk`` spans over
the commit path + watermark/lag/redelivery metrics), and ``bench.py``
(one trace artifact + metrics snapshot per config line).
"""

from sparkdl_tpu.obs.exemplar import ExemplarReservoir
from sparkdl_tpu.obs.export import (load_spans, metrics_snapshot,
                                    prometheus_text, to_chrome_trace,
                                    write_chrome_trace,
                                    write_metrics_jsonl, write_spans_jsonl)
from sparkdl_tpu.obs.trace import (NULL_SPAN, Span, Tracer, configure,
                                   configure_from_env, current_trace_id,
                                   get_tracer, tracing_from_env)

__all__ = [
    "Tracer",
    "Span",
    "NULL_SPAN",
    "get_tracer",
    "configure",
    "configure_from_env",
    "current_trace_id",
    "tracing_from_env",
    "to_chrome_trace",
    "write_chrome_trace",
    "write_spans_jsonl",
    "load_spans",
    "metrics_snapshot",
    "write_metrics_jsonl",
    "prometheus_text",
    "ExemplarReservoir",
]
