"""sparkdl_tpu.obs — span tracing, metrics export, and slow-request
exemplars for the scoring and serving stack.

The observability layer SURVEY.md §5 found missing from the reference
(Spark UI only): every request/batch carries a trace, every stage emits
spans, and every run can export a machine-readable record.

* :mod:`~sparkdl_tpu.obs.trace` — :class:`Tracer` / spans / the
  ``SPARKDL_TRACE=0|1|dir`` gate (disabled path near-zero cost).
* :mod:`~sparkdl_tpu.obs.export` — Chrome trace-event JSON (Perfetto /
  chrome://tracing), Prometheus text exposition, and JSONL snapshots of
  the :class:`~sparkdl_tpu.utils.metrics.Metrics` registry.
* :mod:`~sparkdl_tpu.obs.exemplar` — top-K slowest request span trees,
  surfaced by ``Server.varz()``.
* :mod:`~sparkdl_tpu.obs.flight` — the :class:`FlightRecorder` incident
  black box: a bounded ring of structured state-change events
  (``SPARKDL_BLACKBOX=0|1|dir`` gate, near-zero disabled path) durably
  dumped on atexit/SIGTERM/ready->degraded; ``tools/blackbox.py`` folds
  a dump + span JSONL + stream journal + bench artifact into one
  trace-id-correlated incident timeline.
* :mod:`~sparkdl_tpu.obs.slo` — declarative SLOs (availability, p99
  latency, streaming watermark lag) evaluated with multi-window
  burn-rate math over the existing ``Metrics`` series, feeding
  ``HealthTracker`` degradation and surfacing in
  ``Server.varz()``/``Fleet.varz()``/``StreamScorer.health()``.
* :mod:`~sparkdl_tpu.obs.cost` — the :class:`CostLedger` hardware
  showback layer (``SPARKDL_COST`` gate): every settled request
  attributed to a bounded (tenant, model, program, bucket) ledger —
  metered device seconds split by real rows with the pad tax on a
  shared ``__pad__`` line, batcher queue wait, lockfile-analytic
  FLOPs, HBM byte-seconds, near-zero cache/coalesced/feature-hit
  charges — plus the per-program perf-regression sentinel
  (``cost.regression``/``cost.recovered`` flight events, SLO-style
  ``health()`` degradation) and ``tools/costreport.py`` showback.

Instrumented surfaces: ``serving.Server``/``DynamicBatcher`` (request +
micro-batch spans; shed/drain flight events; ``batch.topoff`` events +
``serving.topoff_rows``/``serving.batch_fill_ratio`` metrics for the
ragged top-off path), ``parallel.engine.
InferenceEngine`` (call/dispatch spans; breaker open/half-open/close
flight events; the ``engine.rows``/``engine.pad_rows`` pad ledger),
``parallel.compile_cache`` (``compile.persist``/``compile.invalidate``
flight events + hit/miss counters for the persistent executable
store), ``parallel.pipeline.PipelinedRunner`` (per-stage spans
with ``block_until_ready``-bracketed device time),
``serving.fleet.Fleet`` (rollout start/promote/rollback + tenant-shed
flight events), ``serving.cache.InferenceCache`` (hit/miss/coalesced/
evict/invalidate flight events + ``cache.*`` metrics),
``streaming.StreamScorer`` (``stream.run``/
``stream.chunk`` spans + stall/redelivery/commit flight events),
``utils.health.HealthTracker`` (ready<->degraded transition events),
``faults`` (``fault.fired`` per injected rule firing), ``utils.retry``
(``retry.attempt`` per re-execution), ``obs.cost.CostLedger``
(per-tenant/per-program attribution in ``varz()["cost"]``; its own
labeled ``prometheus_text``; ``cost.regression``/``cost.recovered``
flight events from the sentinel; the ``cost.attr`` degrade-not-fail
fault site), and ``bench.py`` (one trace artifact + metrics snapshot +
``slo`` + ``cost`` snapshot per config line).
"""

from sparkdl_tpu.obs.exemplar import ExemplarReservoir
from sparkdl_tpu.obs.export import (load_spans, metrics_snapshot,
                                    prometheus_text, to_chrome_trace,
                                    write_chrome_trace,
                                    write_metrics_jsonl, write_spans_jsonl)
from sparkdl_tpu.obs.trace import (NULL_SPAN, Span, Tracer, configure,
                                   configure_from_env, current_trace_id,
                                   get_tracer, tracing_from_env)
from sparkdl_tpu.obs import flight
from sparkdl_tpu.obs import slo as slo_module  # noqa: F401 — re-export
from sparkdl_tpu.obs import cost as cost_module  # noqa: F401 — re-export
from sparkdl_tpu.obs.cost import (CostLedger, CostRegression, cost_rider,
                                  resolve_cost)
from sparkdl_tpu.obs.flight import FlightRecorder, blackbox_from_env
from sparkdl_tpu.obs.slo import SLO, SLOEngine, SLOViolation, slo_snapshot

__all__ = [
    "Tracer",
    "Span",
    "NULL_SPAN",
    "get_tracer",
    "configure",
    "configure_from_env",
    "current_trace_id",
    "tracing_from_env",
    "to_chrome_trace",
    "write_chrome_trace",
    "write_spans_jsonl",
    "load_spans",
    "metrics_snapshot",
    "write_metrics_jsonl",
    "prometheus_text",
    "ExemplarReservoir",
    "flight",
    "FlightRecorder",
    "blackbox_from_env",
    "SLO",
    "SLOEngine",
    "SLOViolation",
    "slo_snapshot",
    "CostLedger",
    "CostRegression",
    "cost_rider",
    "resolve_cost",
]
