"""Slow-request exemplars: full span trees for tail-latency outliers.

A p99 number says the tail is slow; an exemplar says WHY — it is the
complete span tree (request → micro-batch → engine dispatch → pipeline
stages) of an actual slow request, captured at settle time.  The
reservoir keeps the K slowest requests seen (a min-heap: a new request
enters only by evicting the current fastest member), which converges on
the p99-and-beyond outliers of any bounded window without per-request
percentile math on the hot path — the common case is one lock-guarded
float compare; the span-tree copy happens only on the rare entry into
the top K.

``Server.varz()`` surfaces the reservoir; it is inert (every ``offer``
returns False) while tracing is disabled, so the serving hot path pays
nothing unless ``SPARKDL_TRACE`` is on.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Any, Dict, List, Optional

from sparkdl_tpu.analysis.lockcheck import named_lock

__all__ = ["ExemplarReservoir"]


class ExemplarReservoir:
    """Top-``k`` slowest traces, each with its captured span tree."""

    def __init__(self, k: int = 4):
        self.k = max(1, int(k))
        self._heap: list = []  # (duration_s, seq, exemplar_dict)
        self._seq = itertools.count()
        self._lock = named_lock("obs.exemplar")

    def offer(self, duration_s: float, trace_id: Optional[str],
              tracer=None) -> bool:
        """Consider one completed request.  Captures its span tree from
        the tracer ring and admits it iff it is among the ``k`` slowest
        seen.  Cheap rejection first: no span copying unless the
        duration beats the current floor."""
        if tracer is None or not getattr(tracer, "enabled", False):
            return False
        if not trace_id:
            return False
        with self._lock:
            if (len(self._heap) >= self.k
                    and duration_s <= self._heap[0][0]):
                return False
        # Capture OUTSIDE the lock (ring scan + dict copies); spans for
        # this trace are all finished by settle time, and the ring is
        # bounded so very old traces may already be evicted — capture
        # whatever survives.
        spans = [s for s in tracer.snapshot()
                 if s.get("trace_id") == trace_id]
        entry = (duration_s, next(self._seq), {
            "trace_id": trace_id,
            "duration_ms": round(duration_s * 1e3, 3),
            "spans": spans,
        })
        with self._lock:
            if len(self._heap) < self.k:
                heapq.heappush(self._heap, entry)
                return True
            if duration_s > self._heap[0][0]:
                heapq.heapreplace(self._heap, entry)
                return True
            return False

    def snapshot(self) -> List[Dict[str, Any]]:
        """Current exemplars, slowest first."""
        with self._lock:
            entries = sorted(self._heap, reverse=True)
        return [dict(e[2]) for e in entries]

    def clear(self) -> None:
        with self._lock:
            self._heap.clear()
