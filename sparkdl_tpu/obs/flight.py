"""Flight recorder: the stack's always-on incident black box (ISSUE 9).

Every resilience layer built since PR 4 can *survive* an incident —
breaker trips, rollout drains, stream stalls — but once the process
dies there is no durable record of *what happened in what order*.  This
module is the black-box recorder: a process-global, bounded, lock-cheap
ring of structured STATE-CHANGE events (health transitions, breaker
open/half-open/close, rollout phase flips, admission sheds, stream
stall/redelivery/commit, fault-injection firings, retry attempts, SLO
breaches), each stamped with wall time, monotonic time, and the active
trace id from :mod:`sparkdl_tpu.obs.trace` — so a post-mortem can
correlate the event stream with the span tree of the request that
tripped it (``tools/blackbox.py`` folds both into one timeline).

Gate: ``SPARKDL_BLACKBOX`` (the ``SPARKDL_TRACE`` grammar)
  * ``""``/``0``/``false``/``off``/``no`` — DISABLED (default).  The
    disabled path is near-zero cost: :func:`emit` is one module-global
    read plus an identity check (same budget as ``faults.inject`` with
    no plan — guarded by the run-tests.sh overhead stage).
  * ``1``/``true``/``on``/``yes`` — enabled, in-memory ring only (read
    it with :func:`get_recorder` ``.snapshot()``).
  * anything else — treated as a DIRECTORY: enabled, and the ring is
    DURABLY dumped to ``flight_<pid>.jsonl`` there (fsync'd JSONL via
    :class:`~sparkdl_tpu.utils.jsonl.CrashSafeJsonlWriter`, torn-tail
    tolerant on read) on ``atexit``, on ``SIGTERM``, on explicit
    :meth:`FlightRecorder.dump`, and on EVERY ready->degraded health
    transition — so a SIGKILL mid-incident still leaves every event up
    to the degradation on disk for the restarted process to explain.

Event names come from ONE catalog (:data:`EVENT_HELP`, the
``faults.sites.SITE_HELP`` pattern): :meth:`FlightRecorder.record`
rejects unregistered names at emit time, and graftlint rule SDL008
checks ``flight.emit("...")`` literals statically against this file —
a typo'd event can neither be recorded nor silently compiled into an
instrumentation site where it would never be found by ``blackbox``.

Thread model: events are emitted from admission threads, dispatch
workers, the stream poll loop, and signal/atexit handlers.  The ring
lock guards only the O(1) append and the snapshot copy; the dump lock
serializes file appends (each event is written once — a monotonic
``seq`` marks how far the file has caught up).  ``emit`` is always
called OUTSIDE the caller's own locks (health/breaker/plan state is
computed under their locks, then emitted after release), so the
recorder can never deadlock the paths it observes.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from sparkdl_tpu.analysis.lockcheck import named_lock
from sparkdl_tpu.obs.trace import current_trace_id
from sparkdl_tpu.utils.jsonl import CrashSafeJsonlWriter, read_jsonl
from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = [
    "EVENT_HELP",
    "EVENTS",
    "validate_event",
    "FlightRecorder",
    "emit",
    "get_recorder",
    "configure",
    "configure_from_env",
    "blackbox_from_env",
    "load_flight",
]

#: event -> operator-facing description of the state change it records.
#: THE one catalog (graftlint SDL008 reads it with ``ast``, never by
#: import); keep it sorted by layer, like ``faults.sites.SITE_HELP``.
EVENT_HELP = {
    "health.ready": ("a HealthTracker recovered: degraded -> ready "
                     "(attrs name the tracker)"),
    "health.degraded": ("a HealthTracker degraded: ready -> degraded — "
                        "also triggers a durable dump when a blackbox "
                        "directory is configured"),
    "breaker.open": ("consecutive device errors opened a dispatch "
                     "circuit breaker"),
    "breaker.half_open": ("breaker cooldown elapsed; one trial dispatch "
                          "admitted"),
    "breaker.close": "a trial dispatch succeeded; breaker closed",
    "serving.shed": ("Server shed a request (queue full, breaker open, "
                     "or deadline expired — see attrs.reason)"),
    "serving.drain": "Server.close() began stopping/draining",
    "batch.topoff": ("a forming ragged micro-batch absorbed late "
                     "arrivals up to its bucket boundary before "
                     "dispatch (attrs: rows pulled, base fill, bucket)"),
    "compile.persist": ("persistent XLA compile cache enabled and "
                        "validated against the committed program "
                        "lockfile (attrs name the dir and whether an "
                        "existing population was reused)"),
    "compile.invalidate": ("program-lockfile drift invalidated the "
                           "persistent compile cache — stale entries "
                           "purged, drift classified back to the GC "
                           "rule whose invariant moved"),
    "cache.hit": ("inference cache served a result without an engine "
                  "dispatch (digest re-check passed)"),
    "cache.miss": ("inference cache miss — this request became the "
                   "single-flight leader and pays the dispatch"),
    "cache.coalesced": ("a request parked on an identical in-flight "
                        "leader (zero extra dispatches)"),
    "cache.evict": ("the bounded cache evicted an LRU entry to honor "
                    "its entries/bytes cap"),
    "cache.invalidate": ("cache entries dropped (hot-swap with a "
                         "changed fingerprint, or a corrupt entry "
                         "caught by the digest re-check)"),
    "cache.feature_hit": ("feature-cut cache served a backbone "
                          "featurization without a backbone dispatch — "
                          "the request pays head-milliseconds only "
                          "(head-fanout tier; attrs carry the tenant)"),
    "head.swap": ("a head bank mutated (add/swap/evict of one tenant's "
                  "head) with the backbone program untouched — attrs "
                  "carry tenant, op, and the bank size"),
    "rollout.start": "fleet canary rollout started (stable + canary live)",
    "rollout.promote": "fleet rollout promoted; old version draining",
    "rollout.rollback": "fleet rollout rolled back; canary draining",
    "fleet.shed": ("fleet admission shed a tenant request (priority/"
                   "pressure/quota/in-flight cap — see attrs.reason)"),
    "stream.stall": "stream source silent past the watchdog deadline",
    "stream.stall_recovered": "a stalled stream source yielded again",
    "stream.redelivery": ("restart replayed a chunk a previous run left "
                          "uncommitted"),
    "stream.commit": "a stream chunk's journal commit reached disk",
    "twin.scenario": ("the traffic twin entered a scenario phase "
                      "(flash crowd, retry storm, canary start — attrs "
                      "carry the virtual time and phase)"),
    "policy.adjust": ("the twin policy engine changed a control knob "
                      "(tenant quota, deadline, canary fraction — "
                      "attrs carry the lever and new value)"),
    "placement.plan": ("the HBM-aware placement planner produced a "
                       "fleet-to-mesh-slice plan (attrs carry chips, "
                       "per-chip bytes and the plan digest)"),
    "fault.fired": "an injected fault rule fired at its site",
    "retry.attempt": "a transient failure is about to be re-executed",
    "slo.breach": "an SLO's burn rate crossed its threshold",
    "slo.recovered": "a breaching SLO's burn rate dropped back under",
    "cost.regression": ("a program's rolling device-time/row crossed "
                        "the cost sentinel's baseline or lockfile-"
                        "analytic threshold (attrs carry the program, "
                        "factor and measured/baseline us-per-row)"),
    "cost.recovered": ("a regressed program's device-time/row dropped "
                       "back under the recovery threshold"),
}

#: Registered event names, in layer order (derived from EVENT_HELP so
#: the catalog cannot drift from its documentation — the SITES pattern).
EVENTS: Tuple[str, ...] = tuple(EVENT_HELP)

_OFF = ("", "0", "false", "off", "no")
_ON = ("1", "true", "on", "yes")


def validate_event(name: str) -> str:
    """Return ``name`` if cataloged, else raise ``ValueError`` naming
    the known events — the emit-time gate (SDL008 is the static half)."""
    if name not in EVENT_HELP:
        raise ValueError(
            f"unknown flight event {name!r}; register it in "
            f"obs/flight.py EVENT_HELP (known: {', '.join(EVENTS)})")
    return name


def blackbox_from_env():
    """``(enabled, out_dir)`` from ``SPARKDL_BLACKBOX`` — the
    ``SPARKDL_TRACE`` grammar (``0|1|dir``, see module docstring)."""
    raw = os.environ.get("SPARKDL_BLACKBOX", "").strip()
    low = raw.lower()
    if low in _OFF:
        return False, None
    if low in _ON:
        return True, None
    return True, raw


def _jsonable(v: Any) -> Any:
    """Events must always serialize: scalars pass through, anything
    else (an exception, a numpy scalar) is stringified at emit time."""
    if v is None or isinstance(v, (str, bool, int, float)):
        return v
    return str(v)


class FlightRecorder:
    """The bounded event ring plus its durable dump channel.

    ``capacity`` bounds memory (oldest events evicted first — the black
    box records the RECENT past, like its aviation namesake).  With an
    ``out_dir``, :meth:`dump` appends every not-yet-dumped event to
    ``flight_<pid>.jsonl`` with one fsync'd write per line, so a crash
    between dumps loses at most the events since the last trigger — and
    ready->degraded transitions trigger a dump synchronously, which is
    exactly when the next instants stop being trustworthy.
    """

    def __init__(self, out_dir: Optional[str] = None,
                 capacity: int = 4096):
        self.out_dir = out_dir
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = named_lock("obs.flight.ring")
        self._seq = itertools.count(1)  # next() is atomic in CPython
        self._dump_lock = named_lock("obs.flight.dump")
        self._writer: Optional[CrashSafeJsonlWriter] = None
        self._dumped_seq = 0
        self._dump_path = (os.path.join(out_dir,
                                        f"flight_{os.getpid()}.jsonl")
                           if out_dir else None)

    # -- the hot hook ------------------------------------------------------
    def record(self, name: str,
               attrs: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Append one event.  Stamps wall time (``t_wall`` — the only
        cross-process clock), monotonic time (``t_mono`` — orders events
        and joins the span timeline), and the caller thread's active
        trace id (None when tracing is off), then appends under the ring
        lock.  A ``health.degraded`` event additionally triggers a
        durable dump (see class docstring)."""
        validate_event(name)
        ev: Dict[str, Any] = {
            "seq": next(self._seq),
            "event": name,
            "t_wall": round(time.time(), 6),
            "t_mono": round(time.monotonic(), 6),
            "pid": os.getpid(),
            "trace_id": current_trace_id(),
        }
        if attrs:
            ev["attrs"] = {k: _jsonable(v) for k, v in attrs.items()}
        with self._lock:
            self._ring.append(ev)
        if self._dump_path is not None and name == "health.degraded":
            self.dump()
        return ev

    # -- reading -----------------------------------------------------------
    def __len__(self) -> int:
        return len(self._ring)

    def snapshot(self) -> List[Dict[str, Any]]:
        """Current ring contents, oldest first, as copies (the JSONL
        record schema ``tools/blackbox.py`` consumes)."""
        with self._lock:
            events = list(self._ring)
        return [dict(e) for e in events]

    # -- durability --------------------------------------------------------
    def dump(self, path: Optional[str] = None) -> Optional[str]:
        """Durably persist the ring.

        With an explicit ``path``: write the FULL current snapshot there
        (truncating; a one-off export).  Without one: append every event
        not yet on disk to the configured ``flight_<pid>.jsonl``
        (incremental — each event is written exactly once across atexit/
        SIGTERM/degraded-transition triggers).  Returns the path written,
        or None when nothing is configured or the disk refused (the
        recorder is a rider on the real work, never a reason to fail it
        — the ``utils.jsonl`` failure policy)."""
        if path is not None:
            w = CrashSafeJsonlWriter(path)
            w.reset()
            ok = True
            for ev in self.snapshot():
                ok = w.write_line(json.dumps(ev)) and ok
            w.close()
            return path if ok else None
        if self._dump_path is None:
            return None
        with self._dump_lock:
            if self._writer is None:
                self._writer = CrashSafeJsonlWriter(self._dump_path)
            with self._lock:
                events = [dict(e) for e in self._ring
                          if e["seq"] > self._dumped_seq]
            for ev in events:
                if not self._writer.write_line(json.dumps(ev)):
                    return None
                self._dumped_seq = ev["seq"]
        return self._dump_path

    def close(self) -> None:
        with self._dump_lock:
            if self._writer is not None:
                self._writer.close()
                self._writer = None


def load_flight(path: str) -> List[Dict[str, Any]]:
    """Read a flight dump back, tolerating the torn tail a crash
    mid-append can leave (:func:`~sparkdl_tpu.utils.jsonl.read_jsonl` —
    the same one reader the journal and bench artifact ride)."""
    records, _ = read_jsonl(path)
    return records


# -- module singleton (the faults.inject pattern) --------------------------
_UNSET = object()   # before the first emit() consults SPARKDL_BLACKBOX
_recorder: Any = _UNSET
_recorder_lock = named_lock("obs.flight.configure")
_atexit_registered = False
_prev_sigterm: Any = None
_sigterm_installed = False


def emit(name: str, **attrs: Any) -> Optional[Dict[str, Any]]:
    """The instrumentation hook state-change sites call.

    Disabled path (``SPARKDL_BLACKBOX`` unset): one module-global read +
    identity check + return — guarded by the run-tests.sh recorder-
    overhead stage.  The env var is consulted exactly once, on the first
    call, after which the global is either a recorder or ``None``."""
    r = _recorder
    if r is None:
        return None
    if r is _UNSET:
        r = configure_from_env()
        if r is None:
            return None
    return r.record(name, attrs)


def get_recorder() -> Optional[FlightRecorder]:
    """The active recorder (resolving the env on first ask), or None."""
    r = _recorder
    if r is _UNSET:
        return configure_from_env()
    return r


def _dump_current() -> None:
    r = _recorder
    if r is not None and r is not _UNSET:
        r.dump()


def _register_atexit() -> None:
    global _atexit_registered
    if _atexit_registered:
        return
    import atexit

    # Dump whatever recorder is CURRENT at exit (configure() may have
    # replaced the one that registered the hook) — the obs.trace pattern.
    atexit.register(_dump_current)
    _atexit_registered = True


def _sigterm_handler(signum, frame) -> None:
    """Dump, then hand the signal on: a chained previous handler runs
    as before; a process that deliberately IGNORED SIGTERM keeps
    ignoring it (installing a recorder must not change signal
    semantics); otherwise the default disposition is restored and the
    signal re-raised so SIGTERM still terminates the process."""
    import signal

    try:
        _dump_current()
    except Exception as e:  # noqa: BLE001 — a dump failure must not mask the signal
        logger.warning("flight dump on SIGTERM failed: %s: %s",
                       type(e).__name__, e)
    prev = _prev_sigterm
    if prev is signal.SIG_IGN:
        return
    if callable(prev):
        prev(signum, frame)
    else:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)


def _install_sigterm() -> None:
    global _prev_sigterm, _sigterm_installed
    if _sigterm_installed:
        return
    import signal

    if threading.current_thread() is not threading.main_thread():
        return  # signal handlers can only be installed from the main thread
    try:
        _prev_sigterm = signal.getsignal(signal.SIGTERM)
        signal.signal(signal.SIGTERM, _sigterm_handler)
        _sigterm_installed = True
    except (ValueError, OSError):  # non-main interpreter contexts
        _sigterm_installed = False


def configure(enabled: bool = True, out_dir: Optional[str] = None,
              capacity: int = 4096) -> Optional[FlightRecorder]:
    """Replace the process recorder programmatically (tests, bench).
    ``enabled=False`` disables emission outright (and stops consulting
    the env).  With an ``out_dir``, the atexit and SIGTERM dump hooks
    are installed (once per process)."""
    global _recorder
    with _recorder_lock:
        _recorder = (FlightRecorder(out_dir=out_dir, capacity=capacity)
                     if enabled else None)
        recorder = _recorder
    if recorder is not None and out_dir:
        _register_atexit()
        _install_sigterm()
    return recorder


def configure_from_env() -> Optional[FlightRecorder]:
    """(Re-)configure the process recorder from ``SPARKDL_BLACKBOX``."""
    enabled, out_dir = blackbox_from_env()
    return configure(enabled=enabled, out_dir=out_dir)
