"""Exporters: Chrome trace-event JSON, Prometheus text exposition, and
JSONL snapshots of the :class:`~sparkdl_tpu.utils.metrics.Metrics`
registry.

Three machine-readable shapes, one source of truth each:

* **Chrome trace JSON** (:func:`to_chrome_trace` /
  :func:`write_chrome_trace`) — the tracer ring rendered as complete
  ("ph": "X") events on the shared ``perf_counter`` timeline, one track
  per thread, openable directly in Perfetto (ui.perfetto.dev) or
  chrome://tracing.  Span lineage (trace/span/parent ids) and the
  device-time split ride in ``args``.
* **Span JSONL** (:func:`write_spans_jsonl` / :func:`load_spans`) — one
  span dict per line, the shape ``tools/trace_summary.py`` folds into a
  per-stage table.  ``load_spans`` also reads the Chrome form back, so
  every artifact the system writes is summarizable.
* **Metrics snapshot** (:func:`metrics_snapshot` /
  :func:`write_metrics_jsonl`) and **Prometheus text**
  (:func:`prometheus_text`) — the existing registry aggregated under
  STABLE key names (documented in README "Observability"): counters and
  gauges verbatim; timing series as ``{count, total_s, mean_s, p50_s,
  p99_s}``; unitless histograms as ``{count, mean, p50, p99}``.
"""

from __future__ import annotations

import json
import re
import time
from typing import Any, Dict, List, Optional

from sparkdl_tpu.utils.metrics import Metrics

__all__ = [
    "to_chrome_trace",
    "write_chrome_trace",
    "write_spans_jsonl",
    "load_spans",
    "metrics_snapshot",
    "write_metrics_jsonl",
    "prometheus_text",
]


def _spans(spans_or_tracer) -> List[Dict[str, Any]]:
    if hasattr(spans_or_tracer, "snapshot"):
        return spans_or_tracer.snapshot()
    return list(spans_or_tracer)


# -- Chrome trace-event JSON ----------------------------------------------

def to_chrome_trace(spans_or_tracer) -> Dict[str, Any]:
    """Span dicts -> the Chrome trace-event JSON object (Perfetto /
    chrome://tracing).  Spans become complete events ("ph": "X", ``ts``/
    ``dur`` in microseconds); each thread gets a named track via a
    ``thread_name`` metadata event."""
    spans = _spans(spans_or_tracer)
    events: List[Dict[str, Any]] = []
    named_tids = set()
    for s in spans:
        tid = int(s.get("tid") or 0)
        if tid not in named_tids:
            named_tids.add(tid)
            events.append({"ph": "M", "name": "thread_name", "pid": 0,
                           "tid": tid,
                           "args": {"name": s.get("thread", f"t{tid}")}})
        args = {"trace_id": s.get("trace_id"),
                "span_id": s.get("span_id"),
                "parent_id": s.get("parent_id"),
                "status": s.get("status", "ok")}
        if s.get("device_us") is not None:
            args["device_ms"] = round(s["device_us"] / 1e3, 3)
        args.update(s.get("attrs") or {})
        events.append({"ph": "X", "name": s["name"], "cat": "sparkdl",
                       "pid": 0, "tid": tid, "ts": s["ts_us"],
                       "dur": s["dur_us"], "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, spans_or_tracer) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(spans_or_tracer), f)
    return path


# -- span JSONL ------------------------------------------------------------

def write_spans_jsonl(path: str, spans_or_tracer) -> str:
    with open(path, "w") as f:
        for s in _spans(spans_or_tracer):
            f.write(json.dumps(s) + "\n")
    return path


def load_spans(path: str) -> List[Dict[str, Any]]:
    """Read spans back from any artifact form the system writes: span
    JSONL (one dict per line), Chrome trace JSON (events converted back
    to span dicts; metadata events dropped), or a DIRECTORY of flushed
    artifacts (the ``trace_artifact`` shape bench.py emits for
    subprocess configs — every ``spans_*.jsonl``, or failing that every
    ``trace_*.json``, inside is folded together) — so ``trace_summary``
    folds any trace the system wrote, CPU-only traces included."""
    import glob
    import os

    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "spans_*.jsonl")))
        if not files:
            files = sorted(glob.glob(os.path.join(path, "trace_*.json")))
        spans: List[Dict[str, Any]] = []
        for f in files:
            spans.extend(load_spans(f))
        return spans
    with open(path) as f:
        text = f.read().strip()
    if not text:
        return []
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "traceEvents" in doc:
        spans = []
        for ev in doc["traceEvents"]:
            if ev.get("ph") != "X":
                continue
            args = ev.get("args") or {}
            s = {"name": ev["name"], "ts_us": ev["ts"],
                 "dur_us": ev["dur"], "tid": ev.get("tid", 0),
                 "trace_id": args.get("trace_id"),
                 "span_id": args.get("span_id"),
                 "parent_id": args.get("parent_id"),
                 "status": args.get("status", "ok")}
            if args.get("device_ms") is not None:
                s["device_us"] = float(args["device_ms"]) * 1e3
            spans.append(s)
        return spans
    if isinstance(doc, list):
        return doc
    return [json.loads(line) for line in text.splitlines() if line.strip()]


# -- metrics snapshot (stable schema) --------------------------------------

def metrics_snapshot(metrics: Metrics) -> Dict[str, Any]:
    """The registry as a stable nested dict (schema in the module
    docstring / README "Observability").  Key names are contract: bench
    lines and ``Server.varz`` embed this shape, and drivers diff it
    across rounds."""
    raw = metrics.snapshot_raw()
    # float() everywhere: the recorders already coerce, but the snapshot
    # is the JSON boundary (varz endpoint bodies, bench lines) — and
    # round(np.float64) hands back a numpy scalar json.dumps rejects, so
    # nothing numpy may survive past here
    out: Dict[str, Any] = {
        "counters": {k: float(v) for k, v in raw["counters"].items()},
        "gauges": {k: float(v) for k, v in raw["gauges"].items()},
        "timings_s": {},
        "histograms": {},
    }
    for name, series in raw["timings_s"].items():
        if not series:
            continue
        out["timings_s"][name] = {
            "count": len(series),
            "total_s": float(round(sum(series), 6)),
            "mean_s": float(round(sum(series) / len(series), 6)),
            "p50_s": float(round(Metrics._percentile(series, 50), 6)),
            "p99_s": float(round(Metrics._percentile(series, 99), 6)),
        }
    for name, series in raw["histograms"].items():
        if not series:
            continue
        out["histograms"][name] = {
            "count": len(series),
            "mean": float(round(sum(series) / len(series), 6)),
            "p50": float(round(Metrics._percentile(series, 50), 6)),
            "p99": float(round(Metrics._percentile(series, 99), 6)),
        }
    return out


def write_metrics_jsonl(path: str, metrics: Metrics,
                        extra: Optional[Dict[str, Any]] = None) -> str:
    """APPEND one snapshot line (timestamped) — a long-running process
    calling this periodically builds a machine-readable history."""
    rec = dict(extra or {})
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
    rec.update(metrics_snapshot(metrics))
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    return path


# -- Prometheus text exposition --------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(prefix: str, name: str) -> str:
    n = _NAME_RE.sub("_", f"{prefix}_{name}" if prefix else name)
    if n and n[0].isdigit():
        n = "_" + n
    return n


def prometheus_text(metrics: Metrics, prefix: str = "sparkdl") -> str:
    """Prometheus text exposition (v0.0.4) of the registry: counters as
    ``*_total``, gauges verbatim, timing series as ``*_seconds``
    summaries (p50/p99 quantiles + sum/count over the bounded recent
    window), unitless histograms as plain summaries."""
    raw = metrics.snapshot_raw()
    lines: List[str] = []
    for name in sorted(raw["counters"]):
        n = _prom_name(prefix, name) + "_total"
        lines += [f"# TYPE {n} counter", f"{n} {raw['counters'][name]:g}"]
    for name in sorted(raw["gauges"]):
        n = _prom_name(prefix, name)
        lines += [f"# TYPE {n} gauge", f"{n} {raw['gauges'][name]:g}"]

    def summary(n: str, series: List[float]) -> None:
        lines.append(f"# TYPE {n} summary")
        for q, label in ((50, "0.5"), (99, "0.99")):
            lines.append(f'{n}{{quantile="{label}"}} '
                         f"{Metrics._percentile(series, q):g}")
        lines.append(f"{n}_sum {sum(series):g}")
        lines.append(f"{n}_count {len(series)}")

    for name in sorted(raw["timings_s"]):
        series = raw["timings_s"][name]
        if series:
            summary(_prom_name(prefix, name) + "_seconds", series)
    for name in sorted(raw["histograms"]):
        series = raw["histograms"][name]
        if series:
            summary(_prom_name(prefix, name), series)
    return "\n".join(lines) + ("\n" if lines else "")
