"""Declarative SLOs evaluated with multi-window burn-rate math (ISSUE 9).

``health()`` has always answered "degraded?" without answering
"against WHAT objective?".  This module closes that gap: a set of
:class:`SLO` objectives — availability (good/total counter ratio), tail
latency (p99 of a timing series), and streaming watermark lag (a gauge)
— is evaluated by an :class:`SLOEngine` over the EXISTING
:class:`~sparkdl_tpu.utils.metrics.Metrics` registry; no new
instrumentation, the counters the stack already records are the SLIs.

Burn-rate semantics (the SRE-workbook shape, specialized per kind):

* **availability** — the engine keeps a bounded history of counter
  samples (one per :meth:`SLOEngine.evaluate` call; monitoring polls
  drive sampling) and differences them over a SHORT and a LONG window.
  ``burn = windowed_bad_fraction / (1 - objective)`` — burn 1.0 spends
  the error budget exactly at the sustainable rate.  An objective
  BREACHES when *both* windows burn at ``burn_threshold`` or faster
  (the classic two-window guard: the long window ignores blips, the
  short window ends the alert quickly once the bleeding stops), and
  RECOVERS when the short window drops back under.
* **latency** — ``burn = p99 / threshold`` over the registry's bounded
  recent timing window; breach at ``burn_threshold`` (default 1.0).
* **lag** — ``burn = gauge / threshold`` (e.g. ``stream.lag_seconds``
  against the freshness deadline); breach at ``burn_threshold``.

A breach feeds the owner's :class:`~sparkdl_tpu.utils.health.
HealthTracker` (``note_failure`` with an :class:`SLOViolation`, so
``health()`` flips degraded and names the objective in ``last_error``)
and emits ``slo.breach`` into the flight recorder; recovery of the LAST
breaching objective notes success — but only while the tracker's
``last_error`` is still the SLO's own violation, so an unrelated
failure's "no success since" episode survives an objective's recovery.  ``Server``/``Fleet``/
``StreamScorer`` accept ``slos=[...]`` and surface the evaluation in
``varz()``/``health()``; ``bench.py`` stamps :func:`slo_snapshot` into
every per-config line next to ``metrics_snapshot``.

Determinism: :meth:`SLOEngine.evaluate` accepts an explicit ``now``
(monotonic seconds) so tests drive the windows synthetically and the
breach flips at the EXACT burn-rate crossing — the chip-free guard
ROADMAP's re-anchor note demands.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

from sparkdl_tpu.analysis.lockcheck import named_lock
from sparkdl_tpu.obs.flight import emit as flight_emit
from sparkdl_tpu.utils.metrics import Metrics

__all__ = [
    "SLO",
    "SLOEngine",
    "SLOViolation",
    "default_objectives",
    "slo_snapshot",
]

_KINDS = ("availability", "latency", "lag")


class SLOViolation(RuntimeError):
    """What a breaching objective records into ``health()["last_error"]``
    (never raised by the engine — the policy is degrade + keep serving,
    the stream-stall pattern)."""


class SLO:
    """One declarative objective.  ``kind`` picks the SLI shape:

    * ``availability`` — ``good``/``total`` counter names plus
      ``objective`` in (0, 1) (e.g. 0.999: at most 0.1% of requests
      fail).  ``burn_threshold`` defaults to 14.4 — the fast-burn page
      threshold (a 30-day budget gone in ~2 days).
    * ``latency`` — ``series`` timing name plus ``threshold_ms``;
      ``burn_threshold`` defaults to 1.0 (p99 at the threshold IS the
      breach).
    * ``lag`` — ``gauge`` name plus ``threshold_s``; default 1.0.
    """

    __slots__ = ("name", "kind", "objective", "good", "total", "series",
                 "threshold_ms", "gauge", "threshold_s", "burn_threshold")

    def __init__(self, name: str, kind: str, *,
                 objective: Optional[float] = None,
                 good: Optional[str] = None,
                 total: Optional[str] = None,
                 series: Optional[str] = None,
                 threshold_ms: Optional[float] = None,
                 gauge: Optional[str] = None,
                 threshold_s: Optional[float] = None,
                 burn_threshold: Optional[float] = None):
        if kind not in _KINDS:
            raise ValueError(f"SLO kind must be one of {_KINDS}, "
                             f"got {kind!r}")
        self.name = str(name)
        self.kind = kind
        if kind == "availability":
            if not good or not total:
                raise ValueError(f"availability SLO {name!r} needs good= "
                                 f"and total= counter names")
            if objective is None or not 0.0 < float(objective) < 1.0:
                raise ValueError(f"availability SLO {name!r} needs an "
                                 f"objective in (0, 1), got {objective!r}")
            self.objective = float(objective)
            self.good, self.total = str(good), str(total)
            self.burn_threshold = (14.4 if burn_threshold is None
                                   else float(burn_threshold))
        elif kind == "latency":
            if not series or threshold_ms is None or float(threshold_ms) <= 0:
                raise ValueError(f"latency SLO {name!r} needs series= and "
                                 f"a positive threshold_ms=")
            self.series = str(series)
            self.threshold_ms = float(threshold_ms)
            self.burn_threshold = (1.0 if burn_threshold is None
                                   else float(burn_threshold))
        else:  # lag
            if not gauge or threshold_s is None or float(threshold_s) <= 0:
                raise ValueError(f"lag SLO {name!r} needs gauge= and a "
                                 f"positive threshold_s=")
            self.gauge = str(gauge)
            self.threshold_s = float(threshold_s)
            self.burn_threshold = (1.0 if burn_threshold is None
                                   else float(burn_threshold))
        if self.burn_threshold <= 0:
            raise ValueError(f"SLO {name!r} burn_threshold must be "
                             f"positive")

    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"name": self.name, "kind": self.kind,
                               "burn_threshold": self.burn_threshold}
        if self.kind == "availability":
            out.update(objective=self.objective, good=self.good,
                       total=self.total)
        elif self.kind == "latency":
            out.update(series=self.series, threshold_ms=self.threshold_ms)
        else:
            out.update(gauge=self.gauge, threshold_s=self.threshold_s)
        return out


class SLOEngine:
    """Evaluates a set of objectives against one ``Metrics`` registry.

    ``health`` is an optional :class:`~sparkdl_tpu.utils.health.
    HealthTracker` the engine degrades on breach (and recovers when the
    last breach clears).  ``seed_zero_baseline=True`` seeds an implicit
    all-zero counter sample "at the beginning of time", so a one-shot
    evaluation (the bench stamp) rates the whole run instead of
    reporting no-data.
    """

    def __init__(self, metrics: Metrics, objectives: Sequence[SLO], *,
                 health: Any = None,
                 short_window_s: float = 60.0,
                 long_window_s: float = 300.0,
                 max_samples: int = 512,
                 seed_zero_baseline: bool = False,
                 clock: Optional[Callable[[], float]] = None):
        self.metrics = metrics
        # monotonic source for evaluate()'s implicit ``now`` — injectable
        # so a virtual-time harness samples burn windows deterministically
        self._clock = clock if clock is not None else time.monotonic
        self.objectives: List[SLO] = list(objectives)
        for o in self.objectives:
            if not isinstance(o, SLO):
                raise TypeError(f"objectives must be SLO instances, got "
                                f"{type(o).__name__}")
        self._health = health
        self.short_window_s = float(short_window_s)
        self.long_window_s = float(long_window_s)
        if self.short_window_s > self.long_window_s:
            raise ValueError("short_window_s must be <= long_window_s")
        self._counter_names = sorted(
            {o.good for o in self.objectives if o.kind == "availability"}
            | {o.total for o in self.objectives
               if o.kind == "availability"})
        self._lock = named_lock("obs.slo.state")
        #: (t_monotonic, {counter: value}) history; t=None marks the
        #: seeded zero baseline ("before everything").
        self._samples: deque = deque(maxlen=max(2, int(max_samples)))
        if seed_zero_baseline and self._counter_names:
            self._samples.append(
                (None, {n: 0.0 for n in self._counter_names}))
        self._breaching: Dict[str, bool] = {o.name: False
                                            for o in self.objectives}

    # -- window math -------------------------------------------------------
    def _baseline(self, now: float, window_s: float):
        """Newest sample at or before ``now - window_s`` (seeded zero
        baseline matches any window), else the OLDEST sample (partial
        window), else None (no history at all) — caller holds the
        lock."""
        cutoff = now - window_s
        best = None
        for t, vals in self._samples:
            if t is None or t <= cutoff:
                best = (t, vals)
            else:
                break  # samples are time-ordered
        if best is not None:
            return best
        return self._samples[0] if self._samples else None

    @staticmethod
    def _burn_availability(slo: SLO, cur: Dict[str, float],
                           base: Optional[tuple]) -> Optional[float]:
        """Windowed burn rate, or None when the window holds no
        traffic (no verdict — absence of requests is not availability)."""
        if base is None:
            return None
        base_vals = base[1]
        total_d = cur.get(slo.total, 0.0) - base_vals.get(slo.total, 0.0)
        if total_d <= 0:
            return None
        good_d = cur.get(slo.good, 0.0) - base_vals.get(slo.good, 0.0)
        bad_fraction = min(1.0, max(0.0, (total_d - good_d) / total_d))
        return bad_fraction / (1.0 - slo.objective)

    # -- evaluation --------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Take one sample, rate every objective, and return the
        JSON-serializable snapshot (stable schema — README
        "Flight recorder & SLOs").  Transitions feed the health tracker
        and the flight recorder AFTER the engine lock is released."""
        if now is None:
            now = self._clock()
        raw = self.metrics.snapshot_raw()
        counters = raw["counters"]
        cur = {n: float(counters.get(n, 0.0)) for n in self._counter_names}
        statuses: List[Dict[str, Any]] = []
        breached: List[tuple] = []
        recovered: List[str] = []
        with self._lock:
            if self._counter_names:
                self._samples.append((now, cur))
            was_any = any(self._breaching.values())
            for slo in self.objectives:
                st = self._status(slo, cur, now, raw)
                was = self._breaching[slo.name]
                is_breach = st["state"] == "breach"
                # two-window hysteresis for availability: an active
                # breach only clears when the SHORT window drops back
                # under (the long window lags by design)
                if slo.kind == "availability" and was and not is_breach:
                    if (st["burn_short"] is not None
                            and st["burn_short"] >= slo.burn_threshold):
                        is_breach = True
                        st["state"] = "breach"
                self._breaching[slo.name] = is_breach
                if is_breach and not was:
                    breached.append((slo.name, st.get("burn")))
                elif was and not is_breach:
                    recovered.append(slo.name)
                statuses.append(st)
            now_any = any(self._breaching.values())
        state = "breach" if now_any else "ok"
        for name, burn in breached:
            flight_emit("slo.breach", slo=name, burn=burn)
            if self._health is not None:
                self._health.note_failure(SLOViolation(
                    f"SLO {name!r} burning at {burn} >= threshold"))
        for name in recovered:
            flight_emit("slo.recovered", slo=name)
        if was_any and not now_any and recovered and self._health is not None:
            # clear only a degradation the SLO engine itself caused: if
            # some OTHER failure (a dispatch error, a stall) degraded
            # the tracker since the breach, its "no success since"
            # episode must survive this objective's recovery
            last = self._health.snapshot().get("last_error")
            if last is not None and last.get("type") == "SLOViolation":
                self._health.note_success()
        return {"state": state, "t_mono": round(now, 3),
                "objectives": statuses}

    def _status(self, slo: SLO, cur: Dict[str, float], now: float,
                raw: Dict[str, Dict]) -> Dict[str, Any]:
        """One objective's status (caller holds the engine lock)."""
        st: Dict[str, Any] = {"name": slo.name, "kind": slo.kind,
                              "burn_threshold": slo.burn_threshold}
        if slo.kind == "availability":
            b_short = self._burn_availability(
                slo, cur, self._baseline(now, self.short_window_s))
            b_long = self._burn_availability(
                slo, cur, self._baseline(now, self.long_window_s))
            breach = (b_short is not None and b_long is not None
                      and b_short >= slo.burn_threshold
                      and b_long >= slo.burn_threshold)
            st.update({
                "objective": slo.objective,
                "burn_short": (None if b_short is None
                               else round(b_short, 4)),
                "burn_long": (None if b_long is None
                              else round(b_long, 4)),
                "burn": (None if b_short is None and b_long is None
                         else round(max(b_short or 0.0, b_long or 0.0),
                                    4)),
                "short_window_s": self.short_window_s,
                "long_window_s": self.long_window_s,
            })
        elif slo.kind == "latency":
            series = raw["timings_s"].get(slo.series) or []
            p99_s = (Metrics._percentile(series, 99) if series else None)
            burn = (None if p99_s is None
                    else (p99_s * 1e3) / slo.threshold_ms)
            breach = burn is not None and burn >= slo.burn_threshold
            st.update({
                "threshold_ms": slo.threshold_ms,
                "p99_ms": (None if p99_s is None
                           else round(p99_s * 1e3, 3)),
                "burn": None if burn is None else round(burn, 4),
            })
        else:  # lag
            g = raw["gauges"].get(slo.gauge)
            burn = None if g is None else float(g) / slo.threshold_s
            breach = burn is not None and burn >= slo.burn_threshold
            st.update({
                "threshold_s": slo.threshold_s,
                "lag_s": None if g is None else round(float(g), 3),
                "burn": None if burn is None else round(burn, 4),
            })
        st["state"] = "breach" if breach else "ok"
        return st


#: Documented defaults for the bench stamp (README "Flight recorder &
#: SLOs"): informational objectives derived from whichever series a
#: config actually recorded.
_DEFAULT_AVAILABILITY = 0.999
_DEFAULT_LATENCY_MS = 1000.0
_DEFAULT_LAG_S = 30.0


def default_objectives(metrics: Metrics) -> List[SLO]:
    """The standard objective set for whatever a registry recorded:
    serving/fleet availability + p99 latency, streaming commit
    availability + watermark lag — each included only when its series
    exists."""
    raw = metrics.snapshot_raw()
    counters, timings = raw["counters"], raw["timings_s"]
    objs: List[SLO] = []
    if "serving.requests" in counters:
        objs.append(SLO("serving-availability", "availability",
                        good="serving.completed",
                        total="serving.requests",
                        objective=_DEFAULT_AVAILABILITY))
    if timings.get("serving.request_latency"):
        objs.append(SLO("serving-p99-latency", "latency",
                        series="serving.request_latency",
                        threshold_ms=_DEFAULT_LATENCY_MS))
    if "fleet.requests" in counters:
        objs.append(SLO("fleet-availability", "availability",
                        good="fleet.completed", total="fleet.requests",
                        objective=_DEFAULT_AVAILABILITY))
    if timings.get("fleet.request_latency"):
        objs.append(SLO("fleet-p99-latency", "latency",
                        series="fleet.request_latency",
                        threshold_ms=_DEFAULT_LATENCY_MS))
    if "stream.chunks" in counters:
        objs.append(SLO("stream-commit-availability", "availability",
                        good="stream.commits", total="stream.chunks",
                        objective=_DEFAULT_AVAILABILITY))
    if "stream.lag_seconds" in raw["gauges"]:
        objs.append(SLO("stream-watermark-lag", "lag",
                        gauge="stream.lag_seconds",
                        threshold_s=_DEFAULT_LAG_S))
    return objs


def slo_snapshot(metrics: Metrics) -> Optional[Dict[str, Any]]:
    """One-shot whole-run evaluation of :func:`default_objectives` —
    the ``slo`` rider ``bench.py`` stamps next to ``metrics_snapshot``
    (burn_threshold left at the kind defaults; the zero baseline makes
    the single sample rate the entire run).  None when the registry
    holds nothing the default objectives apply to."""
    objs = default_objectives(metrics)
    if not objs:
        return None
    eng = SLOEngine(metrics, objs, seed_zero_baseline=True)
    return eng.evaluate()
