"""Low-overhead span tracing for the scoring stack.

SURVEY.md §5: the reference had no metrics beyond the Spark UI, and
VERDICT r5 found every perf claim living in builder-side artifacts —
gap stories stayed qualitative because nothing in the pipeline could
say WHERE a request's time went.  This module makes every run
self-describing: a :class:`Tracer` issues trace/span IDs that propagate
serving request → batcher micro-batch → engine dispatch → pipeline
stage, recording parent/child spans (wall clock on a shared
``perf_counter`` timeline, plus ``block_until_ready``-bracketed device
time where a stage must force the device anyway) into a bounded,
lock-cheap ring buffer (a ``deque(maxlen)`` whose lock guards only the
O(1) append/copy, never span construction).

Gate: ``SPARKDL_TRACE``
  * ``""``/``0``/``false``/``off``/``no`` — DISABLED (default).  The
    disabled path is near-zero cost: every instrumentation site does
    one enabled-check and receives the shared no-op :data:`NULL_SPAN`;
    no IDs, no timestamps, no ring writes, and
    ``NULL_SPAN.block_until_ready`` never blocks, so async dispatch
    behavior is byte-identical to the un-instrumented code.
  * ``1``/``true``/``on``/``yes`` — enabled, in-memory ring only
    (read it with :meth:`Tracer.snapshot` / ``obs.export``).
  * anything else — treated as a DIRECTORY: enabled, and an ``atexit``
    hook flushes ``trace_<pid>.json`` (Chrome trace-event JSON,
    viewable in Perfetto / chrome://tracing) plus ``spans_<pid>.jsonl``
    there on interpreter exit (or call :meth:`Tracer.flush` yourself).

Thread model: spans cross threads by design (a serving request is
admitted on the caller's thread, batched on the dispatcher thread,
dispatched on a worker).  Parenting therefore composes two mechanisms:
an explicit ``parent=`` handle for cross-thread edges, and a per-thread
current-span stack (``tracer.span(...)`` as a context manager pushes;
:meth:`Tracer.use` re-roots a thread onto a span started elsewhere) so
same-thread nesting is automatic.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from sparkdl_tpu.analysis.lockcheck import named_lock

__all__ = [
    "Span",
    "NULL_SPAN",
    "Tracer",
    "get_tracer",
    "configure",
    "configure_from_env",
    "current_trace_id",
    "tracing_from_env",
]

_OFF = ("", "0", "false", "off", "no")
_ON = ("1", "true", "on", "yes")


def tracing_from_env():
    """``(enabled, out_dir)`` from ``SPARKDL_TRACE`` — the one parser
    every gate shares (``0|1|dir``, see module docstring)."""
    raw = os.environ.get("SPARKDL_TRACE", "").strip()
    low = raw.lower()
    if low in _OFF:
        return False, None
    if low in _ON:
        return True, None
    return True, raw


class _NullSpan:
    """The disabled-path span: a shared, stateless no-op.  Supports the
    full Span surface so instrumentation sites never branch on enabled
    beyond the one check inside ``tracer.span()``."""

    __slots__ = ()
    name = None
    trace_id = None
    span_id = None
    parent_id = None
    device_s = 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **attrs):
        return self

    def block_until_ready(self, x):
        # Disabled tracing must not alter async-dispatch behavior: the
        # value passes through UNBLOCKED.
        return x

    def finish(self, status: str = "ok"):
        return self


NULL_SPAN = _NullSpan()


class Span:
    """One timed operation.  ``t0``/``t1`` are ``time.perf_counter``
    seconds (a single process-wide monotonic timeline, so spans from
    different threads order correctly); ``device_s`` accumulates
    ``block_until_ready``-bracketed device wait inside the span."""

    __slots__ = ("tracer", "name", "trace_id", "span_id", "parent_id",
                 "attrs", "thread", "tid", "t0", "t1", "device_s",
                 "status")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: str, parent_id: Optional[str],
                 attrs: Dict[str, Any]):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        t = threading.current_thread()
        self.thread = t.name
        self.tid = t.ident or 0
        self.t0 = time.perf_counter()
        self.t1: Optional[float] = None
        self.device_s = 0.0
        self.status = "ok"

    def annotate(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def block_until_ready(self, x):
        """Force device completion of ``x`` inside this span, crediting
        the wait to ``device_s`` (the wall-vs-device split the exporter
        surfaces).  Use only where the stage must block anyway (gather)
        — never on the async dispatch path."""
        import jax

        t0 = time.perf_counter()
        jax.block_until_ready(x)
        self.device_s += time.perf_counter() - t0
        return x

    def finish(self, status: str = "ok") -> "Span":
        """Close the span and record it.  Idempotent UNDER RACES: the
        claim (t1 check-and-set) and the ring append happen in one ring-
        lock hold, so concurrent finishers (worker demux vs. the stall
        watchdog settling the same batch) record the span exactly once —
        the first caller's timestamp/status win."""
        t1 = time.perf_counter()
        tracer = self.tracer
        with tracer._ring_lock:
            if self.t1 is not None:
                return self
            self.t1 = t1
            if status != "ok":
                self.status = status
            tracer._ring.append(self)
        return self

    # -- context-manager form: push/pop the thread-current stack -------
    def __enter__(self) -> "Span":
        self.tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        self.tracer._pop(self)
        self.finish("error" if exc_type is not None else "ok")
        return False

    def as_dict(self) -> Dict[str, Any]:
        d = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "ts_us": round(self.t0 * 1e6, 1),
            "dur_us": round(((self.t1 if self.t1 is not None
                              else time.perf_counter()) - self.t0) * 1e6,
                            1),
            "thread": self.thread,
            "tid": self.tid,
            "status": self.status,
        }
        if self.device_s > 0.0:
            d["device_us"] = round(self.device_s * 1e6, 1)
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d


class _Use:
    """Context manager re-rooting THIS thread's current-span stack onto
    a span started elsewhere (cross-thread continuation) without
    finishing it on exit."""

    __slots__ = ("tracer", "span")

    def __init__(self, tracer: "Tracer", span: Span):
        self.tracer = tracer
        self.span = span

    def __enter__(self) -> Span:
        self.tracer._push(self.span)
        return self.span

    def __exit__(self, *exc):
        self.tracer._pop(self.span)
        return False


class Tracer:
    """Issues IDs, tracks per-thread current spans, and keeps finished
    spans in a bounded ring (oldest evicted first)."""

    def __init__(self, enabled: bool = False,
                 out_dir: Optional[str] = None,
                 capacity: int = 8192):
        self.enabled = bool(enabled)
        self.out_dir = out_dir
        self.capacity = int(capacity)
        # Lock-cheap ring: the bounded deque evicts oldest-first, and the
        # lock guards only the O(1) append (record hot path) and the
        # snapshot copy — never span construction or ID issue.  A bare
        # maxlen-deque append is GIL-atomic, but readers (snapshot /
        # exemplar capture under live traffic) would then race iteration
        # against appends and hit "deque mutated during iteration".
        self._ring: deque = deque(maxlen=self.capacity)
        self._ring_lock = named_lock("obs.trace.ring")
        self._ids = itertools.count(1)  # next() is atomic in CPython
        self._local = threading.local()

    # -- ids / context -------------------------------------------------
    def _next(self) -> int:
        return next(self._ids)

    def _stack(self) -> list:
        s = getattr(self._local, "stack", None)
        if s is None:
            s = self._local.stack = []
        return s

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop(self, span: Span) -> None:
        stack = self._stack()
        if stack and stack[-1] is span:
            stack.pop()
        elif span in stack:  # tolerate out-of-order exits
            stack.remove(span)

    def current(self) -> Optional[Span]:
        """This thread's innermost open span (None outside any span)."""
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else None

    # -- span creation -------------------------------------------------
    def span(self, name: str, parent: Optional[Span] = None, **attrs):
        """A span as a context manager: nests under ``parent`` (or this
        thread's current span; a new trace root when neither exists) and
        records itself on exit.  Returns :data:`NULL_SPAN` when
        disabled — the caller's ``with`` block costs two no-op calls."""
        if not self.enabled:
            return NULL_SPAN
        return self._make(name, parent, attrs)

    def start_span(self, name: str, parent: Optional[Span] = None,
                   **attrs):
        """A manually-finished span for operations that cross threads
        (e.g. a serving request: started at submit on the caller's
        thread, finished at future-settle on a worker).  NOT pushed on
        any thread stack — pair with :meth:`use` to parent same-thread
        children under it.  Call :meth:`Span.finish` exactly once."""
        if not self.enabled:
            return NULL_SPAN
        return self._make(name, parent, attrs)

    def _make(self, name, parent, attrs) -> Span:
        if parent is None:
            parent = self.current()
        if parent is None or parent is NULL_SPAN:
            trace_id = f"t{self._next():06x}"
            parent_id = None
        else:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        return Span(self, name, trace_id, f"s{self._next():06x}",
                    parent_id, attrs)

    def use(self, span):
        """Make ``span`` this thread's current parent for the duration
        of the ``with`` block (no-op for None / the null span)."""
        if not self.enabled or span is None or span is NULL_SPAN:
            return NULL_SPAN
        return _Use(self, span)

    # -- ring ----------------------------------------------------------
    def __len__(self) -> int:
        return len(self._ring)

    def snapshot(self) -> List[Dict[str, Any]]:
        """Finished spans, oldest first, as plain dicts (the JSONL span
        schema ``tools/trace_summary.py`` and ``obs.export`` consume)."""
        with self._ring_lock:
            spans = list(self._ring)
        return [s.as_dict() for s in spans]

    def clear(self) -> None:
        with self._ring_lock:
            self._ring.clear()

    # -- flush ---------------------------------------------------------
    def flush(self, out_dir: Optional[str] = None) -> List[str]:
        """Write the ring to ``out_dir`` (default: the directory from
        ``SPARKDL_TRACE=<dir>``): Chrome trace-event JSON + span JSONL.
        Returns the written paths ([] when there is nothing to write or
        no directory is configured)."""
        out_dir = out_dir or self.out_dir
        spans = self.snapshot()
        if not out_dir or not spans:
            return []
        from sparkdl_tpu.obs.export import (write_chrome_trace,
                                            write_spans_jsonl)

        os.makedirs(out_dir, exist_ok=True)
        pid = os.getpid()
        chrome = os.path.join(out_dir, f"trace_{pid}.json")
        jsonl = os.path.join(out_dir, f"spans_{pid}.jsonl")
        write_chrome_trace(chrome, spans)
        write_spans_jsonl(jsonl, spans)
        return [chrome, jsonl]


# -- module singleton ------------------------------------------------------
_tracer: Optional[Tracer] = None
_tracer_lock = named_lock("obs.trace.configure")
_atexit_registered = False


def _register_atexit() -> None:
    global _atexit_registered
    if _atexit_registered:
        return
    import atexit

    # Flush whatever tracer is CURRENT at exit (configure() may have
    # replaced the one that registered the hook).
    atexit.register(lambda: _tracer is not None and _tracer.flush())
    _atexit_registered = True


def get_tracer() -> Tracer:
    """The process tracer, lazily configured from ``SPARKDL_TRACE`` on
    first use.  Cheap enough for hot paths: one global read + None
    check after initialization."""
    t = _tracer
    if t is not None:
        return t
    return configure_from_env()


def configure(enabled: bool = True, out_dir: Optional[str] = None,
              capacity: int = 8192) -> Tracer:
    """Replace the process tracer programmatically (tests, bench.py).
    A fresh tracer starts with an empty ring."""
    global _tracer
    with _tracer_lock:
        _tracer = Tracer(enabled=enabled, out_dir=out_dir,
                         capacity=capacity)
        if out_dir:
            _register_atexit()
        return _tracer


def configure_from_env() -> Tracer:
    """(Re-)configure the process tracer from ``SPARKDL_TRACE``."""
    enabled, out_dir = tracing_from_env()
    return configure(enabled=enabled, out_dir=out_dir)


def current_trace_id() -> Optional[str]:
    """The calling thread's current trace id, or None — the hook the
    trace-id-aware log format uses; must stay near-free when tracing is
    off (one global read, no tracer construction)."""
    t = _tracer
    if t is None or not t.enabled:
        return None
    s = t.current()
    return s.trace_id if s is not None else None
