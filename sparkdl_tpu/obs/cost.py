"""Per-tenant / per-program hardware cost attribution + the
perf-regression sentinel (ISSUE 18).

The stack meters device time, FLOPs, HBM residency, queue wait, and pad
tax GLOBALLY (``engine_call`` timings, the ``engine.rows``/
``engine.pad_rows`` ledger, the ISSUE-14 sharding gauges) — but nothing
answers "who is spending the hardware".  :class:`CostLedger` is that
layer: every settled micro-batch is attributed to a bounded
per-``(tenant, model, program, bucket)`` line set, where

* **device seconds** come from the engine's ``perf_counter``-metered
  call span, split across the batch's tenants proportional to their
  REAL rows over the PADDED device rows — so the pad tax falls out as
  the exact residual and is charged to a separate shared ``__pad__``
  line, never to a tenant (conservation holds per batch by
  construction: ``sum(tenant shares) + pad residual == device_s``);
* **queue seconds** are the batcher's per-request time-in-queue,
  summed per tenant by the server at dispatch;
* **FLOPs** are analytic — rows x the committed
  ``PROGRAMS.lock.json`` ``flops_per_row`` for the (model, bucket)
  dispatch program (read-only lockfile consumer; programs the lockfile
  does not cover charge rows only);
* **HBM byte-seconds** multiply each attributed second by the bucket
  engine's per-chip parameter bytes (the ISSUE-12/14 sharding gauge);
* **cache / feature / coalesced hits** charge near-zero (zero device
  seconds — that is the point of the cache) but are itemized per
  tenant so showback still sees who rode the warm entries.

Cardinality is BOUNDED: at most ``max_tenants`` tenants are tracked
individually (ranked by attributed device seconds); the rest fold into
one ``__overflow__`` tenant, so an adversarial tenant-id storm (or a
64-tenant twin day) can never grow ``varz()`` unboundedly.  Folding
merges lines — conservation sums are unaffected.

**Regression sentinel.**  Per program, a rolling window of the last
``window`` batches yields measured device-seconds/row.  The sentinel
compares it against (a) a pinned baseline (:meth:`CostLedger.
pin_baseline`, or auto-pinned from the first full window) and (b) the
lockfile ANALYTIC expectation — ``flops_per_row`` x the best
seconds-per-FLOP rate calibrated across pinned programs — so a program
whose baseline was pinned while already slow is still caught relative
to its peers.  A crossing emits a ``cost.regression`` flight event and
an SLO-style ``note_failure`` (:class:`CostRegression`) into the bound
:class:`~sparkdl_tpu.utils.health.HealthTracker`, so a perf regression
degrades ``health()`` exactly like an availability breach; dropping
back under ``recover_factor`` emits ``cost.recovered`` and clears the
degradation — but only while ``last_error`` is still the sentinel's
own violation (the SLOEngine recovery guard).

Fault site: ``cost.attr`` fires at the top of :meth:`CostLedger.
record_batch` — attribution is OBSERVABILITY, so callers wrap the
charge and an injected failure degrades to an error counter, never a
failed request (the batch.topoff contract).

Gate: ``SPARKDL_COST`` (the ``SPARKDL_CACHE`` env pattern — consulted
once, on first use)::

    unset / "0" / "off"        -> no process-default ledger (default)
    "1" / "on"                 -> process-default ledger, default knobs
    "tenants=K,window=N,factor=F" -> custom bounds

Constructor-side resolution (:func:`resolve_cost`) follows
``serving.cache.resolve_cache``: ``cost=None`` resolves the process
default, ``cost=False`` forces unmetered, a :class:`CostLedger` passes
through (the fleet shares ONE across its servers).  The disabled
``record_*`` path is one attribute read + return, guarded by the
run-tests.sh cost-overhead stage.
"""

from __future__ import annotations

import os
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from sparkdl_tpu.analysis.lockcheck import named_lock
from sparkdl_tpu.obs.flight import emit as flight_emit
from sparkdl_tpu.utils.logging import get_logger

_inject = None


def inject(site: str) -> None:
    """``faults.inject``, bound on first use — ``obs`` is imported by
    ``faults.plan`` (flight events), so a module-level import here
    would close an import cycle whenever ``faults`` loads first."""
    global _inject
    if _inject is None:
        from sparkdl_tpu.faults import inject as _bound
        _inject = _bound
    _inject(site)

logger = get_logger(__name__)

__all__ = [
    "CostLedger",
    "CostRegression",
    "OVERFLOW_TENANT",
    "PAD_TENANT",
    "configure",
    "configure_from_env",
    "cost_from_env",
    "get_default",
    "resolve_cost",
    "cost_rider",
]

#: the fold target for tenants beyond the top-``max_tenants`` by spend
OVERFLOW_TENANT = "__overflow__"
#: the shared line pad tax is charged to (never a tenant)
PAD_TENANT = "__pad__"

_OFF = ("", "0", "false", "off", "no")
_ON = ("1", "true", "on", "yes")

#: default knobs (env-configured ledgers and bare ``CostLedger()``)
DEFAULT_MAX_TENANTS = 32
DEFAULT_WINDOW = 16


class CostRegression(RuntimeError):
    """What an open per-program cost regression records into
    ``health()["last_error"]`` (never raised by the ledger — the policy
    is degrade + keep serving, the SLOViolation pattern)."""


class _Line:
    """One ``(tenant, model, program, bucket)`` accumulator."""

    __slots__ = ("rows", "device_s", "queue_s", "flops", "hbm_bytes_s",
                 "hits", "coalesced", "feature_hits")

    def __init__(self):
        self.rows = 0
        self.device_s = 0.0
        self.queue_s = 0.0
        self.flops = 0.0
        self.hbm_bytes_s = 0.0
        self.hits = 0
        self.coalesced = 0
        self.feature_hits = 0

    def merge(self, other: "_Line") -> None:
        self.rows += other.rows
        self.device_s += other.device_s
        self.queue_s += other.queue_s
        self.flops += other.flops
        self.hbm_bytes_s += other.hbm_bytes_s
        self.hits += other.hits
        self.coalesced += other.coalesced
        self.feature_hits += other.feature_hits

    def as_dict(self) -> Dict[str, Any]:
        return {
            "rows": self.rows,
            "device_s": self.device_s,
            "queue_s": self.queue_s,
            "flops": self.flops,
            "hbm_bytes_s": self.hbm_bytes_s,
            "hits": self.hits,
            "coalesced": self.coalesced,
            "feature_hits": self.feature_hits,
        }


def _load_program_index(path: Optional[str]
                        ) -> Dict[Tuple[str, int], Dict[str, Any]]:
    """``(model, bucket_rows) -> {program, fingerprint, flops_per_row,
    bytes_accessed}`` over the lockfile's ``kind == "dispatch"`` records
    that carry a model name.  Read-only consumer: a missing or
    unreadable lockfile degrades to rows-only attribution (logged), it
    never fails a charge."""
    from sparkdl_tpu.analysis.program.lockfile import (DEFAULT_LOCKFILE,
                                                       read_lockfile)

    path = path if path is not None else DEFAULT_LOCKFILE
    try:
        doc = read_lockfile(path)
    except Exception as e:  # noqa: BLE001 — observability must degrade, not fail
        logger.info("cost ledger: no usable lockfile at %s (%s: %s); "
                    "FLOPs attribution disabled", path, type(e).__name__, e)
        return {}
    idx: Dict[Tuple[str, int], Dict[str, Any]] = {}
    for name, rec in (doc.get("programs") or {}).items():
        if rec.get("kind") != "dispatch" or not rec.get("model"):
            continue
        try:
            key = (str(rec["model"]), int(rec.get("rows") or 0))
        except (TypeError, ValueError):
            continue
        idx[key] = {
            "program": name,
            "fingerprint": rec.get("fingerprint"),
            "flops_per_row": float(rec.get("flops_per_row") or 0.0),
            "bytes_accessed": float(rec.get("bytes_accessed") or 0.0),
        }
    return idx


class CostLedger:
    """Bounded per-(tenant, model, program, bucket) hardware cost
    attribution + the per-program perf-regression sentinel (module
    docstring).  Thread-safe; one instance is shared across a fleet's
    servers.  All mutation is under one named lock (``obs.cost``);
    flight events and health transitions are emitted OUTSIDE it."""

    def __init__(self, *,
                 max_tenants: int = DEFAULT_MAX_TENANTS,
                 window: int = DEFAULT_WINDOW,
                 min_batches: int = 4,
                 regress_factor: float = 2.0,
                 recover_factor: float = 1.5,
                 analytic_slack: float = 64.0,
                 lockfile_path: Optional[str] = None,
                 health: Any = None,
                 enabled: bool = True):
        self.enabled = bool(enabled)
        self.max_tenants = max(1, int(max_tenants))
        self.window = max(1, int(window))
        self.min_batches = max(1, min(int(min_batches), self.window))
        self.regress_factor = float(regress_factor)
        self.recover_factor = min(float(recover_factor),
                                  self.regress_factor)
        self.analytic_slack = float(analytic_slack)
        self._lockfile_path = lockfile_path
        self._lock = named_lock("obs.cost")
        self._health = health
        #: lazily-loaded lockfile dispatch-program index
        self._programs: Optional[Dict[Tuple[str, int],
                                      Dict[str, Any]]] = None
        self._lines: Dict[Tuple[str, str, str, int], _Line] = {}
        #: tenant -> attributed device seconds (the top-K ranking axis);
        #: excludes the pad line, includes ``__overflow__``
        self._tenant_spend: Dict[str, float] = {}
        self._batches = 0
        self._total_device_s = 0.0
        self._total_queue_s = 0.0
        self._total_rows = 0
        self._total_pad_rows = 0
        self._errors = 0
        # -- sentinel state, per program name --
        self._windows: Dict[str, deque] = {}
        self._baseline: Dict[str, float] = {}
        self._open: Dict[str, Dict[str, Any]] = {}
        self._s_per_flop: Optional[float] = None

    # -- health binding ----------------------------------------------------
    def bind_health(self, tracker: Any) -> None:
        """Bind the :class:`~sparkdl_tpu.utils.health.HealthTracker`
        sentinel transitions feed.  First binder wins (the fleet binds
        its fleet-wide tracker before handing the shared ledger to its
        servers; a standalone server binds its own)."""
        if self._health is None and tracker is not None:
            self._health = tracker

    # -- program resolution ------------------------------------------------
    def _program_info(self, model: str, bucket: int) -> Dict[str, Any]:
        if self._programs is None:
            self._programs = _load_program_index(self._lockfile_path)
        info = self._programs.get((model, bucket))
        if info is not None:
            return info
        return {"program": f"{model}/b{bucket}", "fingerprint": None,
                "flops_per_row": 0.0, "bytes_accessed": 0.0}

    # -- charges -----------------------------------------------------------
    def record_batch(self, *, model: str, bucket: int,
                     tenant_rows: Dict[str, int],
                     device_s: float,
                     queue_s_by_tenant: Optional[Dict[str, float]] = None,
                     pad_rows: int = 0,
                     hbm_bytes: Optional[float] = None) -> None:
        """Attribute one settled micro-batch.

        ``tenant_rows`` maps tenant -> REAL rows dispatched for it this
        batch; ``device_s`` is the engine's metered call seconds
        (summed over retry attempts); ``pad_rows`` is the engine's pad
        ledger delta for the dispatch; ``hbm_bytes`` the bucket
        engine's per-chip parameter bytes.  Tenant shares are
        ``device_s * rows / (rows + pad_rows)`` and the pad line gets
        the exact float residual — per-batch conservation by
        construction.  Raises only what the ``cost.attr`` fault site
        injects (callers wrap the charge; see module docstring)."""
        if not self.enabled:
            return
        inject("cost.attr")
        model = str(model)
        bucket = int(bucket)
        total_rows = sum(int(n) for n in tenant_rows.values())
        if total_rows <= 0:
            return
        pad_rows = max(0, int(pad_rows))
        padded = total_rows + pad_rows
        device_s = float(device_s)
        queue_by = queue_s_by_tenant or {}
        info = self._program_info(model, bucket)
        program = info["program"]
        fpr = info["flops_per_row"]
        hbm = float(hbm_bytes) if hbm_bytes else 0.0
        opened: List[Dict[str, Any]] = []
        closed: List[str] = []
        with self._lock:
            attributed = 0.0
            for tenant in sorted(tenant_rows):
                rows = int(tenant_rows[tenant])
                if rows <= 0:
                    continue
                share = device_s * (rows / padded)
                attributed += share
                key_tenant = self._tenant_key(str(tenant))
                line = self._line(key_tenant, model, program, bucket)
                line.rows += rows
                line.device_s += share
                line.queue_s += float(queue_by.get(tenant, 0.0))
                line.flops += rows * fpr
                line.hbm_bytes_s += hbm * share
                self._tenant_spend[key_tenant] = (
                    self._tenant_spend.get(key_tenant, 0.0) + share)
            # pad tax: the exact residual, so per-batch conservation
            # (sum of tenant shares + pad == device_s) holds in floats
            residual = device_s - attributed
            pad_line = self._line(PAD_TENANT, model, program, bucket)
            pad_line.rows += pad_rows
            pad_line.device_s += residual
            pad_line.flops += pad_rows * fpr
            pad_line.hbm_bytes_s += hbm * residual
            self._batches += 1
            self._total_device_s += device_s
            self._total_queue_s += sum(
                float(queue_by.get(t, 0.0)) for t in tenant_rows)
            self._total_rows += total_rows
            self._total_pad_rows += pad_rows
            self._compact()
            opened, closed = self._sentinel_update(
                program, device_s, padded, fpr)
            still_open = bool(self._open)
        self._emit_transitions(opened, closed, still_open)

    def record_hit(self, *, tenant: str, model: str,
                   kind: str = "hit") -> None:
        """Charge a near-zero line for a request the cache absorbed:
        ``kind`` is ``"hit"`` (result cache), ``"coalesced"``
        (single-flight follower), or ``"feature_hit"`` (feature-cut
        short-circuit).  Zero device seconds — that is the cache's
        point — but itemized per tenant so showback sees who rode the
        warm entries."""
        if not self.enabled:
            return
        inject("cost.attr")
        field = {"hit": "hits", "coalesced": "coalesced",
                 "feature_hit": "feature_hits"}.get(kind)
        if field is None:
            raise ValueError(f"unknown cost hit kind {kind!r}")
        with self._lock:
            key_tenant = self._tenant_key(str(tenant))
            line = self._line(key_tenant, str(model), "__cache__", 0)
            setattr(line, field, getattr(line, field) + 1)
            self._tenant_spend.setdefault(key_tenant, 0.0)
            self._compact()

    def record_error(self) -> None:
        """Count a swallowed attribution failure (the caller's
        degrade-not-fail handler)."""
        with self._lock:
            self._errors += 1

    # -- internals (caller holds the lock) ---------------------------------
    def _line(self, tenant: str, model: str, program: str,
              bucket: int) -> _Line:
        key = (tenant, model, program, bucket)
        line = self._lines.get(key)
        if line is None:
            line = self._lines[key] = _Line()
        return line

    def _tenant_key(self, tenant: str) -> str:
        """Every tenant is admitted provisionally — :meth:`_compact`
        runs after the charge and folds whoever then ranks below the
        top-``max_tenants`` by spend, so a late big spender earns its
        own line while a storm tenant's one tiny charge folds straight
        back into ``__overflow__``."""
        return tenant

    def _compact(self) -> None:
        """Fold everything but the top-``max_tenants`` tenants (by
        attributed device seconds, ties broken by name — deterministic)
        into ``__overflow__``.  Conservation sums are unaffected: lines
        merge, nothing is dropped."""
        ranked = [t for t in self._tenant_spend if t != OVERFLOW_TENANT]
        if len(ranked) <= self.max_tenants:
            return
        ranked.sort(key=lambda t: (-self._tenant_spend[t], t))
        for tenant in ranked[self.max_tenants:]:
            spend = self._tenant_spend.pop(tenant)
            self._tenant_spend[OVERFLOW_TENANT] = (
                self._tenant_spend.get(OVERFLOW_TENANT, 0.0) + spend)
            for key in [k for k in self._lines if k[0] == tenant]:
                line = self._lines.pop(key)
                self._line(OVERFLOW_TENANT, key[1], key[2],
                           key[3]).merge(line)

    def _sentinel_update(self, program: str, device_s: float,
                         device_rows: int, flops_per_row: float
                         ) -> Tuple[List[Dict[str, Any]], List[str]]:
        """Roll the program's window and compute open/close transitions
        (returned for emission OUTSIDE the lock)."""
        win = self._windows.get(program)
        if win is None:
            win = self._windows[program] = deque(maxlen=self.window)
        win.append((device_s, device_rows))
        if len(win) < self.min_batches:
            return [], []
        measured = (sum(d for d, _ in win)
                    / max(1, sum(r for _, r in win)))
        baseline = self._baseline.get(program)
        if baseline is None:
            # auto-pin: the first full-enough window IS the baseline
            # (explicit pin_baseline overrides); also calibrate the
            # fleet-wide best seconds-per-FLOP rate for the analytic
            # cross-check
            self._baseline[program] = baseline = measured
            self._calibrate(baseline, flops_per_row)
            return [], []
        factor = measured / baseline if baseline > 0 else 1.0
        expected = (flops_per_row * self._s_per_flop
                    if flops_per_row > 0 and self._s_per_flop else None)
        analytic_breach = (expected is not None
                           and measured >= self.analytic_slack * expected)
        breach = factor >= self.regress_factor or analytic_breach
        opened: List[Dict[str, Any]] = []
        closed: List[str] = []
        if breach and program not in self._open:
            rec = {
                "program": program,
                "measured_s_per_row": measured,
                "baseline_s_per_row": baseline,
                "factor": round(factor, 4),
                "analytic_expected_s_per_row": expected,
                "reason": ("analytic" if analytic_breach
                           and factor < self.regress_factor
                           else "baseline"),
                "opened_batch": self._batches,
            }
            self._open[program] = rec
            opened.append(dict(rec))
        elif program in self._open:
            recovered = (factor < self.recover_factor
                         and (expected is None
                              or measured <
                              self.analytic_slack * expected))
            if recovered:
                del self._open[program]
                closed.append(program)
            else:
                self._open[program]["measured_s_per_row"] = measured
                self._open[program]["factor"] = round(factor, 4)
        return opened, closed

    def _calibrate(self, baseline_s_per_row: float,
                   flops_per_row: float) -> None:
        if flops_per_row > 0 and baseline_s_per_row > 0:
            rate = baseline_s_per_row / flops_per_row
            if self._s_per_flop is None or rate < self._s_per_flop:
                self._s_per_flop = rate

    def _emit_transitions(self, opened: List[Dict[str, Any]],
                          closed: List[str], still_open: bool) -> None:
        """Flight events + health transitions, OUTSIDE the ledger lock
        (the SLOEngine emission pattern, including its recovery guard:
        only clear a degradation the sentinel itself caused)."""
        for rec in opened:
            flight_emit("cost.regression", program=rec["program"],
                        factor=rec["factor"],
                        measured_us_per_row=round(
                            rec["measured_s_per_row"] * 1e6, 3),
                        baseline_us_per_row=round(
                            rec["baseline_s_per_row"] * 1e6, 3),
                        reason=rec["reason"])
            if self._health is not None:
                self._health.note_failure(CostRegression(
                    f"program {rec['program']!r} device-time/row "
                    f"{rec['measured_s_per_row']:.3e}s is "
                    f"{rec['factor']}x its baseline "
                    f"{rec['baseline_s_per_row']:.3e}s "
                    f"({rec['reason']} check)"))
        for program in closed:
            flight_emit("cost.recovered", program=program)
        if closed and not still_open and self._health is not None:
            last = self._health.snapshot().get("last_error")
            if last is not None and last.get("type") == "CostRegression":
                self._health.note_success()

    # -- sentinel control / queries ----------------------------------------
    def pin_baseline(self, program: Optional[str] = None,
                     s_per_row: Optional[float] = None) -> Dict[str, float]:
        """Pin the sentinel baseline: for one ``program`` (explicit
        ``s_per_row``, or its current rolling window), or for EVERY
        program with a window when ``program`` is None.  Returns the
        pinned ``{program: s_per_row}`` map."""
        pinned: Dict[str, float] = {}
        with self._lock:
            if program is not None:
                if s_per_row is None:
                    win = self._windows.get(program)
                    if not win:
                        raise ValueError(
                            f"no batches recorded for program "
                            f"{program!r}; pass s_per_row explicitly")
                    s_per_row = (sum(d for d, _ in win)
                                 / max(1, sum(r for _, r in win)))
                self._baseline[program] = float(s_per_row)
                pinned[program] = float(s_per_row)
            else:
                for name, win in sorted(self._windows.items()):
                    if not win:
                        continue
                    m = (sum(d for d, _ in win)
                         / max(1, sum(r for _, r in win)))
                    self._baseline[name] = m
                    pinned[name] = m
        return pinned

    def regressions(self) -> Dict[str, Dict[str, Any]]:
        """The OPEN per-program regressions (empty when healthy)."""
        with self._lock:
            return {k: dict(v) for k, v in sorted(self._open.items())}

    def tenant_costs(self) -> Dict[str, float]:
        """Deterministic per-tenant cost units for the twin's fairness
        axis: attributed lockfile FLOPs where the program is covered,
        attributed ROWS otherwise — never wall-measured seconds, so a
        virtual-time day's event lines stay byte-identical across
        runs.  Excludes the shared pad line."""
        out: Dict[str, float] = {}
        with self._lock:
            for (tenant, _m, _p, _b), line in self._lines.items():
                if tenant == PAD_TENANT:
                    continue
                units = line.flops if line.flops > 0 else float(line.rows)
                out[tenant] = out.get(tenant, 0.0) + units
        return dict(sorted(out.items()))

    # -- export ------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable ledger + sentinel state (the ``cost``
        section of ``varz()`` and the bench rider's source).  Device/
        queue seconds are NOT rounded — the conservation proof sums
        them."""
        with self._lock:
            lines = []
            tenants: Dict[str, Dict[str, Any]] = {}
            pad = _Line()
            for key in sorted(self._lines):
                tenant, model, program, bucket = key
                line = self._lines[key]
                lines.append(dict(tenant=tenant, model=model,
                                  program=program, bucket=bucket,
                                  **line.as_dict()))
                if tenant == PAD_TENANT:
                    pad.merge(line)
                    continue
                agg = tenants.get(tenant)
                if agg is None:
                    agg = tenants[tenant] = {
                        "rows": 0, "device_s": 0.0, "queue_s": 0.0,
                        "flops": 0.0, "hbm_bytes_s": 0.0, "hits": 0,
                        "coalesced": 0, "feature_hits": 0}
                for k, v in line.as_dict().items():
                    agg[k] += v
            programs: Dict[str, Dict[str, Any]] = {}
            for name in sorted(self._windows):
                win = self._windows[name]
                measured = (sum(d for d, _ in win)
                            / max(1, sum(r for _, r in win))
                            if win else None)
                programs[name] = {
                    "window_batches": len(win),
                    "measured_s_per_row": measured,
                    "baseline_s_per_row": self._baseline.get(name),
                    "regressed": name in self._open,
                }
            return {
                "totals": {
                    "batches": self._batches,
                    "rows": self._total_rows,
                    "pad_rows": self._total_pad_rows,
                    "device_s": self._total_device_s,
                    "queue_s": self._total_queue_s,
                    "pad_device_s": pad.device_s,
                    "attributed_device_s": sum(
                        l.device_s for l in self._lines.values()),
                    "hits": sum(t["hits"] for t in tenants.values()),
                    "coalesced": sum(t["coalesced"]
                                     for t in tenants.values()),
                    "feature_hits": sum(t["feature_hits"]
                                        for t in tenants.values()),
                    "attr_errors": self._errors,
                },
                "tenants": tenants,
                "pad": pad.as_dict(),
                "programs": programs,
                "sentinel": {
                    "open": {k: dict(v)
                             for k, v in sorted(self._open.items())},
                    "window": self.window,
                    "min_batches": self.min_batches,
                    "regress_factor": self.regress_factor,
                    "recover_factor": self.recover_factor,
                    "analytic_slack": self.analytic_slack,
                    "s_per_flop": self._s_per_flop,
                },
                "tracked_tenants": len([t for t in self._tenant_spend
                                        if t != OVERFLOW_TENANT]),
                "max_tenants": self.max_tenants,
                "overflow": OVERFLOW_TENANT in self._tenant_spend,
            }

    def prometheus_text(self, prefix: str = "sparkdl") -> str:
        """Labeled Prometheus text exposition of the ledger (the
        companion of ``obs.export.prometheus_text``, which cannot carry
        labels).  Deterministic line order; label cardinality is the
        ledger's own bound."""
        def esc(v: Any) -> str:
            return (str(v).replace("\\", r"\\").replace('"', r'\"')
                    .replace("\n", r"\n"))

        base = f"{prefix}_cost"
        out: List[str] = []
        metric_fields = (
            ("device_seconds_total", "device_s",
             "attributed device seconds"),
            ("rows_total", "rows", "attributed real rows"),
            ("queue_seconds_total", "queue_s", "attributed queue wait"),
            ("flops_total", "flops", "lockfile-analytic FLOPs"),
            ("hbm_byte_seconds_total", "hbm_bytes_s",
             "per-chip HBM byte-seconds"),
            ("cache_hits_total", "hits", "result-cache hits"),
            ("coalesced_total", "coalesced", "single-flight followers"),
            ("feature_hits_total", "feature_hits",
             "feature-cut short-circuits"),
        )
        with self._lock:
            keys = sorted(self._lines)
            rows = [(k, self._lines[k].as_dict()) for k in keys]
            open_programs = sorted(self._open)
        for suffix, field, help_text in metric_fields:
            name = f"{base}_{suffix}"
            out.append(f"# HELP {name} {help_text}")
            out.append(f"# TYPE {name} counter")
            for (tenant, model, program, bucket), vals in rows:
                v = vals[field]
                if not v:
                    continue
                out.append(
                    f'{name}{{tenant="{esc(tenant)}",'
                    f'model="{esc(model)}",program="{esc(program)}",'
                    f'bucket="{bucket}"}} {float(v)}')
        name = f"{base}_regression_open"
        out.append(f"# HELP {name} 1 while the program's cost "
                   f"regression is open")
        out.append(f"# TYPE {name} gauge")
        for program in open_programs:
            out.append(f'{name}{{program="{esc(program)}"}} 1')
        return "\n".join(out) + "\n"


def cost_rider(ledger: Optional[CostLedger]) -> Optional[Dict[str, Any]]:
    """The compact bench-line rider: per-tenant spend breakdown + the
    sentinel verdict (``None`` when no ledger is live — the rider is
    omitted, not empty)."""
    if ledger is None:
        return None
    snap = ledger.snapshot()
    return {
        "tenants": {t: {"device_s": round(v["device_s"], 6),
                        "rows": v["rows"],
                        "hits": (v["hits"] + v["coalesced"]
                                 + v["feature_hits"])}
                    for t, v in snap["tenants"].items()},
        "pad_device_s": round(snap["totals"]["pad_device_s"], 6),
        "sentinel": ("regressed" if snap["sentinel"]["open"] else "ok"),
        "open_regressions": sorted(snap["sentinel"]["open"]),
    }


# -- module default (the faults.inject / SPARKDL_CACHE pattern) ------------
_UNSET = object()   # before the first ask consults SPARKDL_COST
_default: Any = _UNSET
_default_lock = named_lock("obs.cost.configure")


def cost_from_env() -> Optional[CostLedger]:
    """A :class:`CostLedger` per the ``SPARKDL_COST`` grammar (module
    docstring), or None when the knob is off/unset.  Raises on
    malformed specs — a typo must not silently disable showback."""
    spec = os.environ.get("SPARKDL_COST", "").strip().lower()
    if spec in _OFF:
        return None
    if spec in _ON:
        return CostLedger()
    kwargs: Dict[str, Any] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"SPARKDL_COST: expected 0|1|tenants=K,window=N,"
                f"factor=F, got {spec!r}")
        k, v = part.split("=", 1)
        k = k.strip()
        try:
            if k == "tenants":
                kwargs["max_tenants"] = int(v)
            elif k == "window":
                kwargs["window"] = int(v)
            elif k == "factor":
                kwargs["regress_factor"] = float(v)
            else:
                raise ValueError(f"unknown key {k!r}")
        except ValueError as e:
            raise ValueError(f"SPARKDL_COST: bad component {part!r} "
                             f"({e})") from e
    return CostLedger(**kwargs)


def configure(ledger: Optional[CostLedger]) -> Optional[CostLedger]:
    """Install ``ledger`` as the process default (None disables).
    Returns it."""
    global _default
    with _default_lock:
        _default = ledger
    return ledger


def configure_from_env() -> Optional[CostLedger]:
    """Resolve ``SPARKDL_COST`` into the process default (idempotent
    after the first call unless :func:`configure` intervenes)."""
    global _default
    with _default_lock:
        if _default is _UNSET:
            _default = cost_from_env()
        return _default


def get_default() -> Optional[CostLedger]:
    """The process-default ledger, resolving the env on first ask.
    Disabled path: one module-global read + identity check (the
    ``faults.inject`` budget, guarded by the run-tests.sh cost-overhead
    stage)."""
    d = _default
    if d is _UNSET:
        return configure_from_env()
    return d


def resolve_cost(cost: Any) -> Optional[CostLedger]:
    """The ONE constructor-side resolution rule (the
    ``serving.cache.resolve_cache`` pattern): ``None`` resolves the
    ``SPARKDL_COST`` process default, ``False`` forces unmetered, a
    :class:`CostLedger` passes through."""
    if cost is None:
        return get_default()
    if cost is False:
        return None
    if not isinstance(cost, CostLedger):
        raise TypeError(f"cost= expects a CostLedger, None, or False; "
                        f"got {type(cost).__name__}")
    return cost
