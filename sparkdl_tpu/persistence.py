"""Stage persistence: save/load for pipeline stages and fitted models.

The reference's stages inherit Spark ML's writable/readable contract
(``stage.save(path)`` / ``Stage.load(path)``); round 1 only had raw pytree
checkpointing.  Layout per stage directory:

  <path>/metadata.json   — {class, uid, params (JSON-able), extra, version}
  <path>/variables/      — orbax checkpoint (model pytrees), when present
  <path>/payload.pkl     — pickled callables (loaders/fns), when present
  <path>/stages/<k>_*/   — nested stages (PipelineModel, CrossValidatorModel)

Stages customize via two hooks:

  ``_persist(self) -> (extra: dict, pytree | None, pickles: dict)``
  ``cls._restore(cls, extra, pytree, pickles) -> stage``  (classmethod)

The default implementation persists all explicitly-set JSON-able params and
refuses (loudly) to silently drop non-serializable ones a subclass didn't
handle.  Callables go through pickle — module-level functions round-trip;
lambdas/closures fail at SAVE time with a clear error, matching Spark's
behavior of failing writes for non-serializable stage state.

**Trust model:** ``load_stage`` imports the class named in ``metadata.json``
and unpickles ``payload.pkl`` — loading a directory you did not write is
arbitrary code execution (see the :func:`load_stage` warning).
"""

from __future__ import annotations

import importlib
import json
import os
import pickle
import shutil
from typing import Any, Dict, Optional, Tuple

from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_FORMAT_VERSION = 1


def persistable_train_fn(mf):
    """``mf.train_fn`` if it survives pickling, else None (with a warning).

    Module-level train_fns round-trip; closure-built ones (e.g. from
    ``ModelFunction.from_flax``) cannot be pickled — rather than failing a
    save that used to succeed, the restored stage gets ``train_fn=None``
    and loses only the ability to re-fit with ``trainBatchStats=True``."""
    fn = getattr(mf, "train_fn", None)
    if fn is None:
        return None
    try:
        pickle.dumps(fn)
    except Exception:
        logger.warning(
            "modelFunction.train_fn is not picklable (closure?); the "
            "restored stage will have train_fn=None and cannot re-fit "
            "with trainBatchStats=True")
        return None
    return fn


def modelfunction_payload(mf) -> Dict[str, Any]:
    """The pickles payload for a ModelFunction (sans variables — those go
    to orbax).  The single source of truth for the payload schema; the
    inverse is :func:`modelfunction_from_payload`."""
    return {
        "fn": mf.fn,
        "train_fn": persistable_train_fn(mf),
        "input_names": list(mf.input_names),
        "output_names": list(mf.output_names),
    }


def modelfunction_from_payload(payload: Dict[str, Any], variables):
    """Rebuild a ModelFunction from :func:`modelfunction_payload` output."""
    from sparkdl_tpu.graph.function import ModelFunction

    return ModelFunction(fn=payload["fn"], variables=variables,
                         train_fn=payload.get("train_fn"),
                         input_names=tuple(payload["input_names"]),
                         output_names=tuple(payload["output_names"]))


def _is_jsonable(v) -> bool:
    if isinstance(v, (str, int, float, bool, type(None))):
        return True
    if isinstance(v, (list, tuple)):
        return all(_is_jsonable(i) for i in v)
    if isinstance(v, dict):
        return all(isinstance(k, str) and _is_jsonable(val)
                   for k, val in v.items())
    return False


def save_stage(stage, path: str, overwrite: bool = False) -> str:
    """Write ``stage`` under ``path`` (a directory)."""
    path = os.path.abspath(path)
    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(
                f"{path} exists; pass overwrite=True to replace it")
        shutil.rmtree(path)
    os.makedirs(path)

    params: Dict[str, Any] = {}
    unsupported = []
    for p in getattr(stage, "params", []):
        if not stage.isSet(p):
            continue
        value = stage.getOrDefault(p)
        if _is_jsonable(value):
            params[p.name] = value
        else:
            unsupported.append(p.name)

    extra, pytree, pickles = stage._persist(path)
    leftover = [n for n in unsupported
                if n not in extra and n not in pickles]
    if leftover:
        raise ValueError(
            f"{type(stage).__name__} cannot persist params {leftover} "
            f"(not JSON-serializable and not handled by the stage's "
            f"_persist hook)")

    meta = {
        "class": f"{type(stage).__module__}.{type(stage).__qualname__}",
        "uid": getattr(stage, "uid", None),
        "version": _FORMAT_VERSION,
        "params": params,
        "extra": extra,
        "has_variables": pytree is not None,
        "pickles": sorted(pickles),
    }
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)

    if pytree is not None:
        from sparkdl_tpu.checkpoint import save_pytree

        save_pytree(os.path.join(path, "variables"), pytree)
    if pickles:
        try:
            blob = pickle.dumps(pickles)
        except Exception as e:
            raise ValueError(
                f"{type(stage).__name__} has non-picklable state "
                f"({sorted(pickles)}): {e}. Use module-level functions "
                f"instead of lambdas/closures for loaders and model fns, "
                f"or reconstruct them after load") from e
        with open(os.path.join(path, "payload.pkl"), "wb") as f:
            f.write(blob)
    return path


def load_stage(path: str):
    """Read a stage previously written by :func:`save_stage`.

    .. warning:: **Trust model — load only directories you wrote.**
       The metadata names a class to import and ``payload.pkl`` is
       unpickled: loading a stage directory from an untrusted source is
       arbitrary code execution, exactly like ``pickle.load`` (and like
       loading untrusted Keras ``.h5``/TF SavedModels).  There is no
       sandbox; treat stage directories as code, not data.
    """
    path = os.path.abspath(path)
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    module_name, _, qualname = meta["class"].rpartition(".")
    cls = importlib.import_module(module_name)
    for part in qualname.split("."):
        cls = getattr(cls, part)

    pytree = None
    if meta.get("has_variables"):
        from sparkdl_tpu.checkpoint import restore_pytree

        pytree = restore_pytree(os.path.join(path, "variables"))
    pickles: Dict[str, Any] = {}
    pkl_path = os.path.join(path, "payload.pkl")
    if os.path.isfile(pkl_path):
        with open(pkl_path, "rb") as f:
            pickles = pickle.load(f)

    stage = cls._restore(meta.get("extra", {}), pytree, pickles, path)
    if meta.get("params"):
        stage._set(**meta["params"])
    return stage


class PersistableModelFunctionMixin:
    """Persistence for stages holding a ``modelFunction`` param (and an
    optional ``imageLoader``): variables go to orbax, the fn (and train_fn,
    when present) through pickle (module-level fns only).  Stages with a set
    ``modelFile`` skip pickling the fns — they are rebuilt from the keras
    file on load, which currently yields ``train_fn=None`` (keras-converted
    models have no train-mode apply; only flax-backed ModelFunctions keep
    ``trainBatchStats`` refit ability through a save/load round-trip)."""

    def _persist(self, path: str):
        extra: Dict[str, Any] = {}
        pickles: Dict[str, Any] = {}
        pytree = None
        has_model_file = (self.hasParam("modelFile")
                          and self.isSet(self.getParam("modelFile")))
        if self.isSet(self.getParam("modelFunction")):
            mf = self.getModelFunction()
            pytree = {"variables": mf.variables}
            if has_model_file:
                extra["modelFunction"] = "from-modelFile"
            else:
                pickles["modelFunction"] = modelfunction_payload(mf)
        if (self.hasParam("imageLoader")
                and self.isSet(self.getParam("imageLoader"))):
            pickles["imageLoader"] = self.getImageLoader()
        return extra, pytree, pickles

    @classmethod
    def _restore(cls, extra: Dict, pytree, pickles: Dict, path: str):
        stage = cls()
        mfp = pickles.get("modelFunction")
        if mfp is not None:
            stage._set(modelFunction=modelfunction_from_payload(
                mfp, pytree["variables"]))
        if "imageLoader" in pickles:
            stage._set(imageLoader=pickles["imageLoader"])
        return stage


# -- nested-stage helpers (PipelineModel / CrossValidatorModel) -------------

def save_nested(stages, path: str) -> list:
    """Write ``stages`` under ``<path>/stages/<idx>_<Class>/``; returns the
    relative dir names in order."""
    names = []
    base = os.path.join(path, "stages")
    os.makedirs(base, exist_ok=True)
    for i, stage in enumerate(stages):
        name = f"{i:03d}_{type(stage).__name__}"
        save_stage(stage, os.path.join(base, name))
        names.append(name)
    return names


def load_nested(path: str, names) -> list:
    return [load_stage(os.path.join(path, "stages", n)) for n in names]
