"""Serving-layer exceptions.

Every failure mode of the online path is a distinct type so callers can
route them: retry later (``QueueFullError`` — carries ``retry_after_s``),
tighten deadlines or shed load upstream (``DeadlineExceededError``),
treat the model as wedged (``DispatchTimeoutError``), or stop sending
(``ServerClosedError``).
"""

from __future__ import annotations


class ServingError(RuntimeError):
    """Base class of all serving-layer errors."""


class QueueFullError(ServingError):
    """Admission rejected: the bounded queue is full (backpressure).

    ``retry_after_s`` is the server's estimate of when capacity frees up
    (queue depth x recent per-batch service time) — the reject-with-
    retry-after contract of clipper-style front-ends.
    """

    def __init__(self, message: str, retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class ServiceUnavailableError(ServingError):
    """Admission shed because the engine's dispatch circuit breaker is
    OPEN (the device has been failing every dispatch): rather than
    admitting requests that would queue, dispatch into a dead device,
    and time out one batch at a time, the server fails them at submit
    with ``retry_after_s`` = the breaker's remaining cool-down.  Same
    retry-later contract as :class:`QueueFullError`, different cause —
    the queue has room; the device does not.
    """

    def __init__(self, message: str, retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class QuotaExceededError(QueueFullError):
    """Fleet admission rejected THIS TENANT: its token-bucket rate quota
    is exhausted or its in-flight cap is reached (other tenants are
    unaffected — that is the point of per-tenant admission).  Subclasses
    :class:`QueueFullError` so existing retry-later client handling
    keeps working; ``retry_after_s`` is the token-refill estimate (capped
    — a zero-quota tenant is never admitted and gets the cap).
    """

    def __init__(self, message: str, retry_after_s: float = 0.0,
                 tenant: str = ""):
        super().__init__(message, retry_after_s=retry_after_s)
        self.tenant = tenant


class DeadlineExceededError(ServingError):
    """The request's deadline expired while it waited in the queue; it was
    shed before dispatch (no device work was spent on it)."""


class DispatchTimeoutError(ServingError):
    """The model call for this request's batch exceeded the server's
    ``dispatch_timeout_ms``: the batch's futures fail, the stalled worker
    is abandoned, and later batches proceed."""


class ServerClosedError(ServingError):
    """The server is closed (or closing): no new requests are admitted."""
