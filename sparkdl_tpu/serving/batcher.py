"""Dynamic micro-batching: bounded admission queue + flush policy.

The clipper-style adaptive-batching core of the serving layer: single
requests accumulate in a bounded FIFO and flush as one micro-batch when
the batch is full (``max_batch_size``) or the OLDEST waiting request has
waited ``max_wait_ms`` — so light traffic pays at most one wait window of
latency and heavy traffic amortizes dispatch over full batches.

Continuous RAGGED batching (ISSUE 13): when the batcher knows the
server's compiled ``bucket_plan``, an age/deadline-triggered flush no
longer grabs *everything waiting* and pads it into the nearest bucket —
it cuts the queue at the largest bucket boundary the depth covers, so
that cut dispatches with ZERO pad rows and only the true sub-bucket
residual ever pays the engine's ``_pad`` path.  The residual itself can
still be topped off by late arrivals right up to dispatch
(:meth:`DynamicBatcher.top_off`, pulled by ``Server._execute`` after it
picks the bucket).  ``SPARKDL_RAGGED=0`` restores the flush-on-full
baseline everywhere (:func:`ragged_enabled_from_env`).

Responsibilities split: the batcher owns admission (backpressure via
``QueueFullError``), the flush policy, and deadline shedding at flush
time; the :class:`~sparkdl_tpu.serving.server.Server` owns bucketing,
dispatch, and demultiplexing.
"""

from __future__ import annotations

import os
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable, List, Optional, Sequence

from sparkdl_tpu.analysis.lockcheck import named_condition
from sparkdl_tpu.faults import inject
from sparkdl_tpu.obs.flight import emit as flight_emit
from sparkdl_tpu.obs.trace import get_tracer
from sparkdl_tpu.serving.errors import (DeadlineExceededError, QueueFullError,
                                        ServerClosedError)
from sparkdl_tpu.utils.logging import get_logger
from sparkdl_tpu.utils.metrics import Metrics

logger = get_logger(__name__)


def ragged_enabled_from_env() -> bool:
    """``SPARKDL_RAGGED`` (default ON) — the one parser every
    ragged-aware call site shares (the ``SPARKDL_PIPELINE`` pattern).
    ``0``/``false``/``off``/``no`` restore the flush-on-full baseline:
    an age-triggered flush takes everything waiting and pads it into
    the nearest bucket."""
    raw = os.environ.get("SPARKDL_RAGGED", "").strip().lower()
    return raw not in ("0", "false", "off", "no")


class Request:
    """One admitted example: payload + completion future + queue timing.

    ``deadline`` is absolute ``time.monotonic()`` seconds (None = no
    deadline).  The future settles exactly once — with the model output
    row, or with a serving error (shed / rejected / batch failure).

    Tracing (``SPARKDL_TRACE``): ``span`` is the request's root span
    (opened at submit, closed at settle); ``batch_span`` rides the
    FIRST live request of a flushed micro-batch and carries the
    batcher→engine segment (see :meth:`DynamicBatcher.next_batch`).
    Both stay None with tracing off.
    """

    __slots__ = ("payload", "future", "enqueued_at", "deadline", "span",
                 "batch_span", "tenant")

    def __init__(self, payload: Any, deadline: Optional[float] = None,
                 now: Optional[float] = None, tenant: str = "default"):
        self.payload = payload
        # cost-attribution identity only (admission/quota live in the
        # Fleet): every request charges SOME tenant, anonymous = "default"
        self.tenant = tenant
        self.future: Future = Future()
        # ``now`` lets a clock-injected caller stamp queue entry on the
        # same (possibly virtual) timeline its deadlines live on
        self.enqueued_at = time.monotonic() if now is None else now
        self.deadline = deadline
        self.span = None
        self.batch_span = None

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        return (time.monotonic() if now is None else now) >= self.deadline

    def finish_span(self, status: str = "ok") -> None:
        """Close this request's root span exactly once (settle paths
        race: worker demux vs. watchdog vs. close — ``Span.finish`` is
        idempotent, so the losers are no-ops)."""
        sp = self.span
        if sp is not None:
            self.span = None
            sp.finish(status)


class DynamicBatcher:
    """Bounded request queue with size-or-age flush.

    Thread model: any number of submitter threads call :meth:`submit`;
    ONE dispatcher thread blocks in :meth:`next_batch`.  ``close`` may be
    called from any thread.
    """

    def __init__(self, *, max_batch_size: int = 64,
                 max_wait_ms: float = 5.0,
                 max_queue: int = 1024,
                 bucket_plan: Optional[Sequence[int]] = None,
                 align: int = 1,
                 metrics: Optional[Metrics] = None,
                 clock: Optional[Callable[[], float]] = None):
        if max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1, got "
                             f"{max_batch_size}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_batch_size = int(max_batch_size)
        # Mesh alignment (ISSUE 14): ``align`` is the serving mesh's
        # data-axis size.  Every ragged CUT must land on a compiled
        # bucket boundary that is a multiple of it (the engine rounds
        # its device batch the same way — effective_device_batch), so a
        # raw bucket plan is rounded up here exactly as the engine
        # would round it; the Server already passes mesh-rounded
        # buckets, making this a no-op there, but a batcher constructed
        # directly with raw buckets must not cut at sizes the mesh
        # cannot split evenly.  A bucket rounded ABOVE max_batch_size
        # is reachable only via top-off, exactly like a Server whose
        # mesh-rounded bucket exceeds its configured batch (_ragged_take
        # keeps the baseline's max_batch_size cut contract).
        self.align = max(1, int(align))
        # Ragged mode (ISSUE 13): with the server's compiled bucket plan
        # in hand, flushes cut the queue at bucket boundaries (module
        # docstring).  None = the flush-on-full baseline.
        if bucket_plan is not None:
            bucket_plan = sorted(int(b) for b in bucket_plan)
            if not bucket_plan or bucket_plan[0] < 1:
                raise ValueError(f"bucket_plan must be positive, got "
                                 f"{bucket_plan}")
            if self.align > 1:
                bucket_plan = sorted(
                    {b + (self.align - b % self.align) % self.align
                     for b in bucket_plan})
        self.bucket_plan = bucket_plan
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        self.max_queue = int(max_queue)
        # Flush-early guard: a queued request whose deadline lands INSIDE
        # the wait window flushes this long before expiry, so a timeout
        # shorter than max_wait_ms still dispatches under light load
        # instead of being shed with 100% loss.  Sized above typical
        # thread-wakeup jitter; expiry is then judged at the FLUSH
        # DECISION (see next_batch), so scheduler overshoot between the
        # decision and the pop can't shed a request that made the flush.
        self.deadline_guard_s = 10e-3
        self.metrics = metrics if metrics is not None else Metrics()
        # Server-maintained estimate of one batch's service time; seeds the
        # retry_after hint before the first batch completes.
        self.batch_seconds_hint = max(self.max_wait_s, 1e-3)
        # Injected monotonic clock: every flush/age/deadline judgement
        # reads THIS source, so a virtual clock (the traffic twin's)
        # drives the whole wait-window state machine deterministically.
        # Condition WAITS still time out on the real clock — a frozen
        # virtual clock re-checks flush conditions on submit/:meth:`wake`.
        self._clock = clock if clock is not None else time.monotonic
        self._q: deque = deque()
        self._cond = named_condition("serving.batcher")
        self._closed = False
        self._drain = True

    # -- admission (submitter threads) ------------------------------------
    def submit(self, request: Request) -> None:
        """Admit one request or raise: ``ServerClosedError`` after close,
        ``QueueFullError`` (with a ``retry_after_s`` hint) when the queue
        is at capacity — admission never blocks the caller.  A queue-full
        shed is a ``serving.shed`` flight event (emitted AFTER the
        batcher lock is released — the recorder never runs under the
        locks it observes)."""
        full = None
        with self._cond:
            if self._closed:
                raise ServerClosedError("server is closed")
            # fault site: a queue-full storm (exc=queue_full) or an
            # admission stall (a sleep here holds the batcher lock —
            # deliberately: that IS a stalled admission path) — AFTER
            # the closed check, so injected faults never mask
            # ServerClosedError for clients of a closed server
            inject("serving.admit")
            if len(self._q) >= self.max_queue:
                self.metrics.incr("serving.rejected_queue_full")
                # Capacity frees one batch at a time: full-queue drain time
                # is (depth / batch) service periods.
                periods = len(self._q) / self.max_batch_size
                hint = max(1e-3, periods * self.batch_seconds_hint)
                full = (len(self._q), hint)
            else:
                self._q.append(request)
                self.metrics.gauge("serving.queue_depth",
                                   float(len(self._q)))
                self._cond.notify_all()
        if full is not None:
            depth, hint = full
            flight_emit("serving.shed", reason="queue_full", depth=depth,
                        retry_after_s=round(hint, 4))
            raise QueueFullError(
                f"admission queue full ({depth}/{self.max_queue})",
                retry_after_s=hint)

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    def wake(self) -> None:
        """Nudge the dispatcher to re-evaluate its flush conditions.

        With an injected clock the age/deadline triggers only move when
        that clock does — and nothing else notifies the condition when
        it moves.  A virtual-time driver advances its clock, then calls
        this, so wait-window flushes fire at the virtual instant they
        would have fired at on the real clock."""
        with self._cond:
            self._cond.notify_all()

    # -- flush (dispatcher thread) ----------------------------------------
    def next_batch(self) -> Optional[List[Request]]:
        """Block until a micro-batch is due; return its LIVE requests.

        Flush triggers: queue holds ``max_batch_size`` requests, the
        oldest waiting request is ``max_wait_s`` old, a queued request's
        deadline is about to expire (within ``deadline_guard_s`` — a
        timeout tighter than the wait window flushes early rather than
        being shed), or the batcher is closing (drain: remaining requests
        flush immediately).  Expired deadlines are shed HERE — after the
        flush decision, before any device work — so a shed request costs
        nothing downstream.  May return an empty list (whole batch shed);
        returns None only when closed and fully drained.
        """
        with self._cond:
            now = self._clock()
            while True:
                if self._q:
                    if self._closed:
                        break  # draining: flush whatever is left
                    now = self._clock()
                    oldest_wait = now - self._q[0].enqueued_at
                    earliest = min(
                        (r.deadline for r in self._q
                         if r.deadline is not None), default=None)
                    if (len(self._q) >= self.max_batch_size
                            or oldest_wait >= self.max_wait_s
                            or (earliest is not None
                                and earliest - now <= self.deadline_guard_s)):
                        break
                    timeout = self.max_wait_s - oldest_wait
                    if earliest is not None:
                        timeout = min(timeout, earliest - now
                                      - self.deadline_guard_s)
                    self._cond.wait(max(timeout, 1e-4))
                elif self._closed:
                    return None
                else:
                    self._cond.wait()
                    now = self._clock()
            take = min(len(self._q), self.max_batch_size)
            if self.bucket_plan is not None:
                take = self._ragged_take(len(self._q), now)
            batch = [self._q.popleft() for _ in range(take)]
            self.metrics.gauge("serving.queue_depth", float(len(self._q)))
        # expiry is judged at the flush DECISION: a request the guard
        # selected while still live dispatches even if the pop itself was
        # delayed past its deadline by scheduling jitter
        live = self._shed_expired(batch, now)
        tracer = get_tracer()
        if tracer.enabled and live:
            # the micro-batch span adopts the FIRST live request's trace
            # (the convention that keeps one strict serving → batcher →
            # engine nesting chain; sibling requests keep their own root
            # spans and are recorded on the batch as an attribute)
            live[0].batch_span = tracer.start_span(
                "serving.microbatch", parent=live[0].span,
                batch_size=len(live), shed=len(batch) - len(live),
                member_traces=[r.span.trace_id for r in live
                               if r.span is not None])
        return live

    def _ragged_take(self, depth: int, now: float) -> int:
        """How many requests THIS flush should pop (called under the
        condition lock): the largest compiled bucket the queue depth
        covers — that cut dispatches with zero pad rows — or the whole
        sub-bucket residual when the depth is below the smallest
        bucket.  A deadline about to expire PAST the cut grows it to
        the smallest bucket covering that request (capped at the
        largest bucket; the loop re-flushes immediately for anything
        still beyond it), so ragged cuts never starve an urgent
        request the baseline would have carried."""
        buckets = self.bucket_plan
        # the flush cut never exceeds max_batch_size — a mesh-rounded
        # bucket can be LARGER than the configured batch, and popping
        # past the baseline's cut would merge requests the flush policy
        # promised separate batches (top-off may still fill the pad gap
        # up to the bucket, but only with stack-compatible arrivals)
        depth = min(depth, self.max_batch_size)
        take = depth
        for b in reversed(buckets):
            if depth >= b:
                take = b
                break
        else:
            return depth  # sub-bucket residual: pad is the true floor
        if take >= depth:
            return take
        # urgent-deadline coverage beyond the cut (bounded scan: at most
        # max_batch_size entries — deque indexing stays cheap)
        last_urgent = -1
        for i in range(take, depth):
            r = self._q[i]
            if (r.deadline is not None
                    and r.deadline - now <= self.deadline_guard_s):
                last_urgent = i
        if last_urgent >= take:
            for b in buckets:
                if b > last_urgent:
                    return min(depth, b)
        return take

    @staticmethod
    def _payload_signature(payload: Any):
        """(shape, dtype) per leaf — what has to match for two requests
        to stack into one device batch."""
        import jax

        return tuple((tuple(getattr(l, "shape", ())),
                      str(getattr(l, "dtype", type(l).__name__)))
                     for l in jax.tree_util.tree_leaves(payload))

    def top_off(self, k: int, like: Any = None) -> List[Request]:
        """Pop up to ``k`` late-arriving requests to TOP OFF a forming
        batch right before dispatch (the continuous half of ragged
        batching): a sub-bucket residual the flush popped can absorb
        arrivals that landed between the flush decision and the stack,
        up to its bucket boundary, instead of dispatching pad rows.

        ``like`` (a payload of the forming batch) bounds the pull to
        STACK-COMPATIBLE requests only, stopping at the first mismatch
        (FIFO preserved, never reordered): a poison-shaped request must
        keep failing only the batch the flush policy would have put it
        in — top-off can shrink pad, never widen a failure's blast
        radius.  Expired deadlines among the pulled requests are shed
        exactly like a flush would (they cost nothing downstream).
        Returns the LIVE pulled requests; safe from any dispatch worker
        thread."""
        if k <= 0:
            return []
        sig = (None if like is None
               else self._payload_signature(like))
        with self._cond:
            take = min(int(k), len(self._q))
            if take <= 0:
                return []
            batch: List[Request] = []
            for _ in range(take):
                if sig is not None and self._payload_signature(
                        self._q[0].payload) != sig:
                    break
                batch.append(self._q.popleft())
            if not batch:
                return []
            self.metrics.gauge("serving.queue_depth", float(len(self._q)))
            now = self._clock()
        return self._shed_expired(batch, now)

    def _shed_expired(self, batch: List[Request],
                      now: float) -> List[Request]:
        live: List[Request] = []
        for r in batch:
            if r.expired(now):
                self.metrics.incr("serving.shed_deadline")
                flight_emit("serving.shed", reason="deadline",
                            waited_s=round(now - r.enqueued_at, 4))
                try:
                    r.future.set_exception(DeadlineExceededError(
                        f"deadline expired after "
                        f"{now - r.enqueued_at:.3f}s in queue"))
                except InvalidStateError:
                    pass  # client cancel() raced us; never kill the
                    # dispatcher over an already-settled future
                r.finish_span("shed")
            else:
                live.append(r)
        if len(live) < len(batch):
            logger.info("shed %d expired request(s) before dispatch",
                        len(batch) - len(live))
        return live

    # -- shutdown ----------------------------------------------------------
    def close(self, drain: bool = True) -> None:
        """Stop admission.  ``drain=True`` lets the dispatcher flush the
        remaining queue; ``drain=False`` fails every queued future with
        ``ServerClosedError`` immediately."""
        with self._cond:
            self._closed = True
            if not drain:
                while self._q:
                    r = self._q.popleft()
                    try:
                        r.future.set_exception(
                            ServerClosedError("server closed before "
                                              "dispatch"))
                    except InvalidStateError:
                        pass  # client cancel() raced the close
                    r.finish_span("closed")
                self.metrics.gauge("serving.queue_depth", 0.0)
            self._cond.notify_all()


def ragged_arrival_benchmark(n_bursts: int = 10,
                             max_batch_size: int = 32,
                             bucket_sizes=(8, 16, 32),
                             dispatch_ms: float = 8.0,
                             max_wait_ms: float = 25.0,
                             gap_ms: float = 70.0,
                             seed: int = 0,
                             feature_dim: int = 8):
    """Deterministic chip-free proof of the ragged-batching lever
    (ISSUE 13 — the ``synthetic_overlap_benchmark`` /
    ``zipfian_cache_benchmark`` pattern: a sleep stands in for the
    device, so the result is stable on any host and needs no relay).

    A seeded MIXED-SIZE arrival process — ``n_bursts`` bursts of
    1..``max_batch_size`` requests, each burst isolated by ``gap_ms`` >
    ``max_wait_ms`` so every burst forms its own flush window — is
    replayed twice through a real sleep-wrapped
    :class:`~sparkdl_tpu.serving.server.Server`: once with
    ``ragged=False`` (the flush-on-full baseline: each burst pops whole
    and pads into the nearest covering bucket) and once with
    ``ragged=True`` (bucket-boundary cuts + top-off: only the true
    sub-bucket residual pads).  The model fn is row-local elementwise
    math, so per-request outputs are BIT-IDENTICAL regardless of which
    micro-batch or bucket a request lands in — the ragged path must be
    a pure pad-row optimization, never an approximation.  Pad
    accounting comes from the machinery that already exists: the
    engine's ``engine.rows``/``engine.pad_rows`` ledger and the
    ``serving.batch_fill_ratio`` histogram.
    """
    import time as _time

    import numpy as np

    from sparkdl_tpu.serving.server import Server
    from sparkdl_tpu.utils.metrics import Metrics as _Metrics

    rng = np.random.default_rng(seed)
    sizes = [int(s) for s in rng.integers(1, max_batch_size + 1,
                                          size=n_bursts)]
    n_requests = sum(sizes)
    variables = {"scale": np.float32(2.0)}

    def fn(v, x):
        import jax.numpy as jnp

        # row-local elementwise math: a request's output row depends
        # only on its own input row, never on batch size or pad
        # content — what makes the cross-mode bit-identity assertable
        return jnp.tanh(x * v["scale"] + 0.5)

    payloads = [rng.normal(size=(feature_dim,)).astype(np.float32)
                for _ in range(n_requests)]

    def run(ragged: bool):
        metrics = _Metrics()
        srv = Server(fn, variables, max_batch_size=max_batch_size,
                     max_wait_ms=max_wait_ms,
                     max_queue=n_requests + 16,
                     bucket_sizes=list(bucket_sizes),
                     max_inflight_batches=4,
                     ragged=ragged, cache=False, metrics=metrics)
        try:
            srv.warmup(payloads[0])  # compile BEFORE the sleep wrap
            dispatches = [0]
            for b in srv.bucket_sizes:
                eng = srv._engine_for(b)
                real = eng.run_padded

                def slow(batch, _real=real):  # the synthetic device
                    dispatches[0] += 1
                    _time.sleep(dispatch_ms / 1e3)
                    return _real(batch)

                eng.run_padded = slow
            # warmup dispatched one exact-fill batch per bucket; snapshot
            # its ledger so the returned accounting covers the replay only
            warm = dict(metrics.snapshot_raw()["counters"])
            warm_fills = len(metrics.histograms.get(
                "serving.batch_fill_ratio", []))
            futs = []
            t0 = _time.perf_counter()
            i = 0
            for s in sizes:
                for _ in range(s):
                    futs.append(srv.submit(payloads[i]))
                    i += 1
                _time.sleep(gap_ms / 1e3)
            outs = [np.asarray(f.result(timeout=60)) for f in futs]
            wall_s = _time.perf_counter() - t0
        finally:
            srv.close()
        snap = metrics.snapshot_raw()
        counters = {k: v - warm.get(k, 0.0)
                    for k, v in snap["counters"].items()}
        fills = list(metrics.histograms.get(
            "serving.batch_fill_ratio", []))[warm_fills:]
        return {
            "wall_s": round(wall_s, 4),
            "dispatches": dispatches[0],
            "rows": int(counters.get("engine.rows", 0)),
            "pad_rows": int(counters.get("engine.pad_rows", 0)),
            "topoff_rows": int(counters.get("serving.topoff_rows", 0)),
            "batches": int(counters.get("serving.batches", 0)),
            "fill_mean": (round(float(np.mean(fills)), 4)
                          if len(fills) else None),
        }, outs

    flush, flush_out = run(ragged=False)
    ragged, ragged_out = run(ragged=True)
    bit_identical = all(np.array_equal(a, b)
                        for a, b in zip(flush_out, ragged_out))
    total = max(1, flush["rows"] + flush["pad_rows"])
    rtotal = max(1, ragged["rows"] + ragged["pad_rows"])
    return {
        "n_requests": n_requests,
        "n_bursts": n_bursts,
        "burst_sizes": sizes,
        "bucket_sizes": list(bucket_sizes),
        "dispatch_ms": dispatch_ms,
        "flush": flush,
        "ragged": ragged,
        "flush_pad_frac": round(flush["pad_rows"] / total, 4),
        "ragged_pad_frac": round(ragged["pad_rows"] / rtotal, 4),
        "pad_rows_saved": flush["pad_rows"] - ragged["pad_rows"],
        "bit_identical": bit_identical,
    }
