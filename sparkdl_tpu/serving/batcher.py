"""Dynamic micro-batching: bounded admission queue + flush policy.

The clipper-style adaptive-batching core of the serving layer: single
requests accumulate in a bounded FIFO and flush as one micro-batch when
the batch is full (``max_batch_size``) or the OLDEST waiting request has
waited ``max_wait_ms`` — so light traffic pays at most one wait window of
latency and heavy traffic amortizes dispatch over full batches.

Responsibilities split: the batcher owns admission (backpressure via
``QueueFullError``), the flush policy, and deadline shedding at flush
time; the :class:`~sparkdl_tpu.serving.server.Server` owns bucketing,
dispatch, and demultiplexing.
"""

from __future__ import annotations

import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import Any, List, Optional

from sparkdl_tpu.analysis.lockcheck import named_condition
from sparkdl_tpu.faults import inject
from sparkdl_tpu.obs.flight import emit as flight_emit
from sparkdl_tpu.obs.trace import get_tracer
from sparkdl_tpu.serving.errors import (DeadlineExceededError, QueueFullError,
                                        ServerClosedError)
from sparkdl_tpu.utils.logging import get_logger
from sparkdl_tpu.utils.metrics import Metrics

logger = get_logger(__name__)


class Request:
    """One admitted example: payload + completion future + queue timing.

    ``deadline`` is absolute ``time.monotonic()`` seconds (None = no
    deadline).  The future settles exactly once — with the model output
    row, or with a serving error (shed / rejected / batch failure).

    Tracing (``SPARKDL_TRACE``): ``span`` is the request's root span
    (opened at submit, closed at settle); ``batch_span`` rides the
    FIRST live request of a flushed micro-batch and carries the
    batcher→engine segment (see :meth:`DynamicBatcher.next_batch`).
    Both stay None with tracing off.
    """

    __slots__ = ("payload", "future", "enqueued_at", "deadline", "span",
                 "batch_span")

    def __init__(self, payload: Any, deadline: Optional[float] = None):
        self.payload = payload
        self.future: Future = Future()
        self.enqueued_at = time.monotonic()
        self.deadline = deadline
        self.span = None
        self.batch_span = None

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        return (time.monotonic() if now is None else now) >= self.deadline

    def finish_span(self, status: str = "ok") -> None:
        """Close this request's root span exactly once (settle paths
        race: worker demux vs. watchdog vs. close — ``Span.finish`` is
        idempotent, so the losers are no-ops)."""
        sp = self.span
        if sp is not None:
            self.span = None
            sp.finish(status)


class DynamicBatcher:
    """Bounded request queue with size-or-age flush.

    Thread model: any number of submitter threads call :meth:`submit`;
    ONE dispatcher thread blocks in :meth:`next_batch`.  ``close`` may be
    called from any thread.
    """

    def __init__(self, *, max_batch_size: int = 64,
                 max_wait_ms: float = 5.0,
                 max_queue: int = 1024,
                 metrics: Optional[Metrics] = None):
        if max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1, got "
                             f"{max_batch_size}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_batch_size = int(max_batch_size)
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        self.max_queue = int(max_queue)
        # Flush-early guard: a queued request whose deadline lands INSIDE
        # the wait window flushes this long before expiry, so a timeout
        # shorter than max_wait_ms still dispatches under light load
        # instead of being shed with 100% loss.  Sized above typical
        # thread-wakeup jitter; expiry is then judged at the FLUSH
        # DECISION (see next_batch), so scheduler overshoot between the
        # decision and the pop can't shed a request that made the flush.
        self.deadline_guard_s = 10e-3
        self.metrics = metrics if metrics is not None else Metrics()
        # Server-maintained estimate of one batch's service time; seeds the
        # retry_after hint before the first batch completes.
        self.batch_seconds_hint = max(self.max_wait_s, 1e-3)
        self._q: deque = deque()
        self._cond = named_condition("serving.batcher")
        self._closed = False
        self._drain = True

    # -- admission (submitter threads) ------------------------------------
    def submit(self, request: Request) -> None:
        """Admit one request or raise: ``ServerClosedError`` after close,
        ``QueueFullError`` (with a ``retry_after_s`` hint) when the queue
        is at capacity — admission never blocks the caller.  A queue-full
        shed is a ``serving.shed`` flight event (emitted AFTER the
        batcher lock is released — the recorder never runs under the
        locks it observes)."""
        full = None
        with self._cond:
            if self._closed:
                raise ServerClosedError("server is closed")
            # fault site: a queue-full storm (exc=queue_full) or an
            # admission stall (a sleep here holds the batcher lock —
            # deliberately: that IS a stalled admission path) — AFTER
            # the closed check, so injected faults never mask
            # ServerClosedError for clients of a closed server
            inject("serving.admit")
            if len(self._q) >= self.max_queue:
                self.metrics.incr("serving.rejected_queue_full")
                # Capacity frees one batch at a time: full-queue drain time
                # is (depth / batch) service periods.
                periods = len(self._q) / self.max_batch_size
                hint = max(1e-3, periods * self.batch_seconds_hint)
                full = (len(self._q), hint)
            else:
                self._q.append(request)
                self.metrics.gauge("serving.queue_depth",
                                   float(len(self._q)))
                self._cond.notify_all()
        if full is not None:
            depth, hint = full
            flight_emit("serving.shed", reason="queue_full", depth=depth,
                        retry_after_s=round(hint, 4))
            raise QueueFullError(
                f"admission queue full ({depth}/{self.max_queue})",
                retry_after_s=hint)

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    # -- flush (dispatcher thread) ----------------------------------------
    def next_batch(self) -> Optional[List[Request]]:
        """Block until a micro-batch is due; return its LIVE requests.

        Flush triggers: queue holds ``max_batch_size`` requests, the
        oldest waiting request is ``max_wait_s`` old, a queued request's
        deadline is about to expire (within ``deadline_guard_s`` — a
        timeout tighter than the wait window flushes early rather than
        being shed), or the batcher is closing (drain: remaining requests
        flush immediately).  Expired deadlines are shed HERE — after the
        flush decision, before any device work — so a shed request costs
        nothing downstream.  May return an empty list (whole batch shed);
        returns None only when closed and fully drained.
        """
        with self._cond:
            now = time.monotonic()
            while True:
                if self._q:
                    if self._closed:
                        break  # draining: flush whatever is left
                    now = time.monotonic()
                    oldest_wait = now - self._q[0].enqueued_at
                    earliest = min(
                        (r.deadline for r in self._q
                         if r.deadline is not None), default=None)
                    if (len(self._q) >= self.max_batch_size
                            or oldest_wait >= self.max_wait_s
                            or (earliest is not None
                                and earliest - now <= self.deadline_guard_s)):
                        break
                    timeout = self.max_wait_s - oldest_wait
                    if earliest is not None:
                        timeout = min(timeout, earliest - now
                                      - self.deadline_guard_s)
                    self._cond.wait(max(timeout, 1e-4))
                elif self._closed:
                    return None
                else:
                    self._cond.wait()
                    now = time.monotonic()
            batch = [self._q.popleft()
                     for _ in range(min(len(self._q), self.max_batch_size))]
            self.metrics.gauge("serving.queue_depth", float(len(self._q)))
        # expiry is judged at the flush DECISION: a request the guard
        # selected while still live dispatches even if the pop itself was
        # delayed past its deadline by scheduling jitter
        live = self._shed_expired(batch, now)
        tracer = get_tracer()
        if tracer.enabled and live:
            # the micro-batch span adopts the FIRST live request's trace
            # (the convention that keeps one strict serving → batcher →
            # engine nesting chain; sibling requests keep their own root
            # spans and are recorded on the batch as an attribute)
            live[0].batch_span = tracer.start_span(
                "serving.microbatch", parent=live[0].span,
                batch_size=len(live), shed=len(batch) - len(live),
                member_traces=[r.span.trace_id for r in live
                               if r.span is not None])
        return live

    def _shed_expired(self, batch: List[Request],
                      now: float) -> List[Request]:
        live: List[Request] = []
        for r in batch:
            if r.expired(now):
                self.metrics.incr("serving.shed_deadline")
                flight_emit("serving.shed", reason="deadline",
                            waited_s=round(now - r.enqueued_at, 4))
                try:
                    r.future.set_exception(DeadlineExceededError(
                        f"deadline expired after "
                        f"{now - r.enqueued_at:.3f}s in queue"))
                except InvalidStateError:
                    pass  # client cancel() raced us; never kill the
                    # dispatcher over an already-settled future
                r.finish_span("shed")
            else:
                live.append(r)
        if len(live) < len(batch):
            logger.info("shed %d expired request(s) before dispatch",
                        len(batch) - len(live))
        return live

    # -- shutdown ----------------------------------------------------------
    def close(self, drain: bool = True) -> None:
        """Stop admission.  ``drain=True`` lets the dispatcher flush the
        remaining queue; ``drain=False`` fails every queued future with
        ``ServerClosedError`` immediately."""
        with self._cond:
            self._closed = True
            if not drain:
                while self._q:
                    r = self._q.popleft()
                    try:
                        r.future.set_exception(
                            ServerClosedError("server closed before "
                                              "dispatch"))
                    except InvalidStateError:
                        pass  # client cancel() raced the close
                    r.finish_span("closed")
                self.metrics.gauge("serving.queue_depth", 0.0)
            self._cond.notify_all()
